"""Continuous-batching inference server over the paged-KV decode
primitive (ISSUE 16 tentpole).

Request lifecycle — admission → prefill → continuous-batch decode loop
→ detokenize (caller-side):

1. **Admission**: :meth:`InferenceServer.submit` enqueues a request;
   the decode thread admits from the queue *between decode steps*
   whenever a batch slot AND enough free KV pages exist — new requests
   join the in-flight batch immediately instead of waiting for it to
   drain (the continuous-batching property).  Page tables come from one
   shared :class:`~paddle_tpu.serving.pagepool.PagePool`; each request
   reserves ``prompt + max_new_tokens`` worth of pages up front, so a
   request that admits can never die of pool exhaustion mid-decode —
   exhaustion is pure admission backpressure.
2. **Prefill**: every request admitted in the same round runs in ONE
   ``flash_attention_packed`` launch (mixed prompt lengths packed into
   a single [1, B·T] row), which also writes the prompt K/V into the
   request's pages and yields the first generated token — the TTFT
   moment.
3. **Decode loop**: one ``paged_decode_attention`` step per iteration
   over a fixed-width batch (``--serve_max_batch``; inactive slots are
   padded with scratch-page tables so there is exactly one compiled
   decode shape).  Finished requests retire at step boundaries, their
   pages recycle instantly — the kernel's stale-page immunity makes a
   freed page safe to reissue without scrubbing.

The kill switch ``--serve_continuous=false`` degrades the same loop to
sequential single-request serving (admit one, run to completion, batch
width 1).  Because every per-request computation in
``serving/model.py`` is row-independent, both modes generate
byte-for-byte identical tokens — pinned in both directions by
``tests/test_serving_server.py``.

Telemetry (all optional, live when ``paddle_tpu.observe`` is active):
``serve_ttft_seconds`` / ``serve_request_seconds`` reservoir histograms
(p99 SLO source), ``serve_queue_depth`` / ``serve_batch_size`` gauges,
``serve_requests`` / ``serve_tokens_generated`` counters,
``serve_page_pool_pages`` pool census, and the
``serve_admit`` / ``serve_prefill`` / ``serve_decode_step`` span
family.  Threads are ``ptpu-serve-decode`` and ``ptpu-serve-http``
(the conftest thread-leak guard and ptpu-lint key on the prefix).

Crash safety: with ``snapshot_path`` set, the allocator state persists
atomically after every mutation; a restarted server restores it only
if it validates (:class:`~paddle_tpu.serving.pagepool.TornSnapshot`
otherwise), then releases the orphaned tables — KV content died with
the process — and serves from a verified-clean pool.  A torn page
table is never served; ``testing/fault.py`` SIGKILLs this promise.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..analysis.lockorder import named_condition
from ..utils import FLAGS, enforce, get_logger
from .model import DecoderModel
from .pagepool import PagePool, PagePoolExhausted, SCRATCH_PAGE, TornSnapshot

try:                         # telemetry optional, as in loader.py
    from ..observe import REGISTRY as _registry
    from ..observe import counter as _counter, gauge as _gauge
    from ..observe import histogram as _histogram, trace as _trace
    from ..observe import fleet as _fleet
    from ..observe.http import make_threading_server, resolve_bind_host
except ImportError:  # pragma: no cover - standalone copy
    _counter = _gauge = _histogram = _trace = _fleet = _registry = None
    make_threading_server = resolve_bind_host = None

log = get_logger("serving")

#: Decode-loop thread name (thread-leak guard + ptpu-lint contract).
DECODE_THREAD_NAME = "ptpu-serve-decode"
#: HTTP front-end thread name.
HTTP_THREAD_NAME = "ptpu-serve-http"

_REQ_IDS = itertools.count()


def _span_admit(**attrs):
    return contextlib.nullcontext() if _trace is None \
        else _trace.span("serve_admit", **attrs)


def _span_prefill(**attrs):
    return contextlib.nullcontext() if _trace is None \
        else _trace.span("serve_prefill", **attrs)


def _span_decode_step(**attrs):
    return contextlib.nullcontext() if _trace is None \
        else _trace.span("serve_decode_step", **attrs)


class Request:
    """One generation request and its lifecycle state.  ``tokens`` holds
    the generated ids (prompt excluded); ``length`` counts tokens whose
    K/V is already written to this request's pages."""

    __slots__ = ("id", "prompt", "max_new_tokens", "tokens", "state",
                 "error", "done", "length", "next_token",
                 "t_submit", "t_first", "t_done")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int):
        self.id = f"req{next(_REQ_IDS)}"
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []
        self.state = "queued"            # queued|active|done|failed
        self.error: Optional[str] = None
        self.done = threading.Event()
        self.length = 0                  # tokens materialized in pages
        self.next_token = -1             # token to feed the next step
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None

    @property
    def ttft_s(self) -> Optional[float]:
        return None if self.t_first is None else self.t_first - self.t_submit

    @property
    def latency_s(self) -> Optional[float]:
        return None if self.t_done is None else self.t_done - self.t_submit


class SwapTicket:
    """A pending hot-swap: the fully built replacement model plus the
    handshake back to the requester.  ``event`` fires once the decode
    loop has applied (or rolled back) the swap; ``report`` then holds
    the outcome — ``result`` (``ok``/``rolled_back``), the pointer-flip
    ``pause_s``, and which in-flight requests were re-prefilled."""

    __slots__ = ("model", "version", "inflight", "exported_at",
                 "event", "report")

    def __init__(self, model: DecoderModel, version: str, inflight: str,
                 exported_at: Optional[float]):
        self.model = model
        self.version = version
        self.inflight = inflight
        self.exported_at = exported_at
        self.event = threading.Event()
        self.report: Dict = {"result": "pending", "version": version,
                             "inflight": inflight}

    def wait(self, timeout: Optional[float] = None) -> Dict:
        if not self.event.wait(timeout):
            raise TimeoutError(f"swap to {self.version[:12]} not applied "
                               f"within {timeout}s")
        return dict(self.report)


class InferenceServer:
    """The continuous-batching decode loop around a
    :class:`~paddle_tpu.serving.model.DecoderModel` and a
    :class:`~paddle_tpu.serving.pagepool.PagePool`.

    With ``--rollout`` (default on) the server also speaks the
    zero-downtime train→serve protocol (``serving/rollout.py``):
    :meth:`request_swap` parks a fully built replacement model as a
    :class:`SwapTicket`; the decode loop applies it at a step boundary
    — ``drain`` finishes in-flight requests on the OLD model first
    (admissions pause), ``reprefill`` flips immediately and restarts
    in-flight generation from the prompt on the NEW model — so every
    response's tokens come from exactly one model.  ``--rollout=false``
    is the kill switch: no swap surface, ``/healthz`` and the 404 body
    byte-identical to the pre-rollout server."""

    def __init__(self, model: DecoderModel,
                 max_batch: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 page_size: Optional[int] = None,
                 continuous: Optional[bool] = None,
                 snapshot_path: Optional[str] = None,
                 rollout: Optional[bool] = None,
                 model_version: str = "unversioned",
                 model_exported_at: Optional[float] = None):
        self.model = model
        self.max_batch = int(FLAGS.get("serve_max_batch")
                             if max_batch is None else max_batch)
        n_pages = int(FLAGS.get("kv_pool_pages")
                      if n_pages is None else n_pages)
        page_size = int(FLAGS.get("kv_page_size")
                        if page_size is None else page_size)
        self.continuous = bool(FLAGS.get("serve_continuous")
                               if continuous is None else continuous)
        enforce(self.max_batch >= 1,
                f"serve_max_batch must be >= 1, got {self.max_batch}")
        self.snapshot_path = snapshot_path
        self.pool = self._make_pool(n_pages, page_size, snapshot_path)
        self._k_pool, self._v_pool = model.new_pools(n_pages, page_size)
        # one page-table width for every request: enough pages to cover
        # a max_context-long sequence (or the whole pool if smaller)
        self.max_pages = min(self.pool.capacity,
                             self.pool.pages_needed(model.cfg.max_context))
        self._cond = named_condition("serve.admission")
        self._queue: collections.deque = collections.deque()
        self._active: List[Request] = []
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        self.served = 0
        self.generated_tokens = 0
        self.rollout_enabled = bool(FLAGS.get("rollout")
                                    if rollout is None else rollout)
        self.model_version = model_version
        self.model_exported_at = model_exported_at
        self.rollout_state = "serving"     # serving|swapping|rolled_back
        self.last_swap_error: Optional[str] = None
        self._pending_swap: Optional[SwapTicket] = None

    @staticmethod
    def _make_pool(n_pages: int, page_size: int,
                   snapshot_path: Optional[str]) -> PagePool:
        """Fresh pool, or crash recovery from a prior snapshot: a valid
        snapshot restores and then RELEASES every orphaned table (the
        KV content died with the previous process); a torn one is
        refused and replaced by a fresh pool.  Either way the served
        pool verifies clean — never a torn page table."""
        if snapshot_path:
            try:
                pool = PagePool.restore(snapshot_path)
            except FileNotFoundError:
                pool = None
            except TornSnapshot as e:
                log.warning("pool snapshot refused (%s); starting fresh",
                            e)
                pool = None
            if pool is not None:
                enforce(pool.n_pages == n_pages
                        and pool.page_size == page_size,
                        f"pool snapshot geometry {pool.n_pages}x"
                        f"{pool.page_size} != configured {n_pages}x"
                        f"{page_size}")
                for owner in pool.owners():
                    pool.release(owner)
                pool.verify()
                return pool
        return PagePool(n_pages, page_size)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InferenceServer":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name=DECODE_THREAD_NAME, daemon=True)
            self._thread.start()
            self._publish_serving_info()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)
        self.stop_http()
        # unblock every waiter; their requests will never run
        with self._cond:
            pending = list(self._queue) + list(self._active)
            self._queue.clear()
            self._active = []
            swap, self._pending_swap = self._pending_swap, None
        for r in pending:
            self.pool.release(r.id)
            r.state = "failed"
            r.error = "server stopped"
            r.done.set()
        if swap is not None:       # a parked swap never applies now
            swap.report.update(result="rolled_back",
                               error="server stopped")
            swap.event.set()

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- clients
    def submit(self, prompt: Sequence[int],
               max_new_tokens: int = 16) -> Request:
        """Enqueue a generation request; returns immediately.  Rejects
        (raises) only what could NEVER run: an empty prompt, a sequence
        longer than ``max_context``, or a page-table need beyond the
        whole pool — a merely-busy pool is backpressure, not an error."""
        enforce(len(prompt) >= 1, "empty prompt")
        enforce(max_new_tokens >= 1,
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        total = len(prompt) + max_new_tokens
        enforce(total <= self.model.cfg.max_context,
                f"prompt + max_new_tokens = {total} exceeds max_context "
                f"{self.model.cfg.max_context}")
        enforce(self.pool.pages_needed(total) <= self.max_pages,
                f"request needs {self.pool.pages_needed(total)} pages, "
                f"page tables hold {self.max_pages}")
        vocab = self.model.cfg.vocab
        enforce(all(0 <= int(t) < vocab for t in prompt),
                f"prompt token out of range [0, {vocab})")
        r = Request(prompt, max_new_tokens)
        with self._cond:
            enforce(not self._stop, "server is stopped")
            self._queue.append(r)
            self._publish_queue_locked()
            self._cond.notify_all()
        return r

    def result(self, r: Request, timeout: Optional[float] = None
               ) -> List[int]:
        """Block until a request finishes; returns its generated token
        ids (prompt excluded)."""
        if not r.done.wait(timeout):
            raise TimeoutError(f"{r.id}: no result within {timeout}s")
        if r.state != "done":
            raise RuntimeError(f"{r.id}: {r.error or r.state}")
        return list(r.tokens)

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 timeout: Optional[float] = None) -> List[int]:
        return self.result(self.submit(prompt, max_new_tokens), timeout)

    def stats(self) -> Dict[str, int]:
        with self._cond:
            q, a = len(self._queue), len(self._active)
            rollout = None
            if self.rollout_enabled:
                rollout = {"model_version": self.model_version,
                           "model_exported_at": self.model_exported_at,
                           "rollout_state": self.rollout_state,
                           "last_swap_error": self.last_swap_error}
        out = {"queue_depth": q, "active": a,
               "free_pages": self.pool.free_pages(),
               "used_pages": self.pool.used_pages(),
               "served": self.served,
               "generated_tokens": self.generated_tokens,
               "continuous": int(self.continuous),
               "max_batch": self.max_batch}
        if rollout is not None:
            # gated on the kill switch so --rollout=false keeps stats()
            # (and with it the /healthz body) byte-identical to the
            # pre-rollout server
            out.update(rollout)
        slo_ms = float(FLAGS.get("serve_slo_ms") or 0.0)
        if slo_ms > 0 and _registry is not None:
            # WINDOWED p99 (last 60s), not the lifetime reservoir: a
            # recovered server must stop advertising a stale bad p99
            # forever.  Gated on the flag (default 0) so the default
            # /healthz body stays byte-identical.
            h = _registry.find("serve_ttft_seconds")
            p99 = h.window_quantile(0.99, 60.0) \
                if h is not None and hasattr(h, "window_quantile") \
                else None
            out["ttft_p99_ms"] = None if p99 is None \
                else round(p99 * 1e3, 3)
            out["slo_met"] = int(p99 is None or p99 * 1e3 <= slo_ms)
        return out

    # ------------------------------------------------------------ hot swap
    def request_swap(self, model: DecoderModel,
                     version: str = "unversioned",
                     inflight: Optional[str] = None,
                     exported_at: Optional[float] = None) -> SwapTicket:
        """Park a fully built replacement model for the decode loop to
        apply at its next step boundary; returns the
        :class:`SwapTicket` to ``wait()`` on.  The model must already
        be built, verified, and probed — this method does NO loading
        (``rollout.swap_from_artifact`` is the full pipeline)."""
        enforce(self.rollout_enabled,
                "rollout disabled (--rollout=false): request_swap refused")
        inflight = str(FLAGS.get("rollout_inflight")
                       if inflight is None else inflight)
        enforce(inflight in ("drain", "reprefill"),
                f"unknown in-flight policy {inflight!r} "
                "(expected 'drain' or 'reprefill')")
        # same architecture is the contract (continuous training swaps
        # weights, not shapes): pools, page tables, and every compiled
        # shape bucket carry over only because the config is identical
        enforce(model.cfg == self.model.cfg,
                f"swap model config {model.cfg} != serving config "
                f"{self.model.cfg}")
        ticket = SwapTicket(model, version, inflight, exported_at)
        with self._cond:
            enforce(not self._stop, "server is stopped")
            enforce(self._pending_swap is None,
                    "a swap is already in progress")
            self._pending_swap = ticket
            self.rollout_state = "swapping"
            ticket.report["inflight_at_request"] = len(self._active)
            self._cond.notify_all()
        self._publish_serving_info()
        return ticket

    def record_swap_failure(self, reason: str) -> None:
        """Record a swap that failed BEFORE a ticket was ever parked
        (artifact verify/load/probe ran off-thread and rolled back).
        The old model keeps serving; ``/healthz`` carries the reason."""
        with self._cond:
            self.rollout_state = "rolled_back"
            self.last_swap_error = reason
        self._publish_serving_info()

    def _apply_swap_locked(self, ticket: SwapTicket) -> List[Request]:
        """Apply a parked swap at the decode-loop boundary (``_cond``
        held).  Returns the in-flight requests to re-prefill on the new
        model (``reprefill`` policy; empty under ``drain``, which only
        gets here with no actives).  Failure to stand up the new pools
        rolls back — the old model/pools were never unhooked."""
        t0 = time.perf_counter()
        old_version = self.model_version
        try:
            k_pool, v_pool = ticket.model.new_pools(self.pool.n_pages,
                                                    self.pool.page_size)
        except Exception as e:  # noqa: BLE001 - rollback, keep serving
            self._pending_swap = None
            self.rollout_state = "rolled_back"
            self.last_swap_error = f"pool standup: {type(e).__name__}: {e}"
            ticket.report.update(result="rolled_back",
                                 error=self.last_swap_error)
            if _counter is not None:
                _counter("rollout_swap_total",
                         "hot-swap attempts by outcome").inc(
                    result="rolled_back")
            log.error("swap to %s rolled back (%s)", ticket.version[:12],
                      self.last_swap_error)
            ticket.event.set()
            return []
        reprefill: List[Request] = []
        if ticket.inflight == "reprefill" and self._active:
            # restart in-flight generation from the prompt on the NEW
            # model: drop every old-model token (exactly-one-model
            # semantics), keep the page tables — fresh pools mean the
            # prompt K/V is rewritten by the re-prefill
            for r in self._active:
                r.tokens.clear()
                r.length = 0
                r.next_token = -1
                r.t_first = None
            reprefill = list(self._active)
            ticket.report["reprefilled"] = [r.id for r in reprefill]
        self.model = ticket.model
        self._k_pool, self._v_pool = k_pool, v_pool
        self.model_version = ticket.version
        self.model_exported_at = ticket.exported_at
        self.rollout_state = "serving"
        self.last_swap_error = None
        self._pending_swap = None
        pause_s = time.perf_counter() - t0
        ticket.report.update(result="ok", pause_s=pause_s)
        if _counter is not None:
            _counter("rollout_swap_total",
                     "hot-swap attempts by outcome").inc(result="ok")
            _histogram("rollout_swap_pause_seconds",
                       "decode-loop pause for the atomic pointer flip "
                       "(pool standup + in-flight bookkeeping; the "
                       "model build/verify/probe ran off-thread)"
                       ).observe(pause_s)
            g = _gauge("rollout_model_version",
                       "1 for the live artifact digest, 0 for retired "
                       "ones (info gauge keyed by digest label)")
            if old_version:
                g.set(0.0, digest=old_version)
            g.set(1.0, digest=ticket.version)
        log.info("hot-swapped model %s -> %s (pause %.1f ms, %d "
                 "re-prefilled)", old_version[:12], ticket.version[:12],
                 pause_s * 1e3, len(reprefill))
        ticket.event.set()
        return reprefill

    def _publish_serving_info(self) -> None:
        """Push model version + rollout state into the fleet identity so
        every frame this process pushes carries them (``/fleet/topology``
        and the ``--watch`` version column)."""
        if _fleet is None or not self.rollout_enabled:
            return
        _fleet.set_serving_info(version=self.model_version,
                                state=self.rollout_state,
                                exported_at=self.model_exported_at,
                                error=self.last_swap_error)

    # ---------------------------------------------------------- decode loop
    def _loop(self) -> None:
        while True:
            swapped = False
            with self._cond:
                while not self._stop and not self._queue \
                        and not self._active \
                        and self._pending_swap is None:
                    self._cond.wait(0.05)
                if self._stop:
                    return
                reprefill: List[Request] = []
                pending = self._pending_swap
                if pending is not None and (pending.inflight == "reprefill"
                                            or not self._active):
                    # the atomic pointer flip, at the step boundary.
                    # drain policy only flips once the actives emptied;
                    # reprefill flips now and restarts them below
                    reprefill = self._apply_swap_locked(pending)
                    pending = None
                    swapped = True
                # a pending drain swap pauses admission: new requests
                # must first-run on the NEW model, and the flip waits
                # for the actives to finish on the old one
                admitted = [] if pending is not None \
                    else self._admit_locked()
            if swapped:
                self._publish_serving_info()
            try:
                batch = reprefill + admitted
                changed = bool(batch)
                if batch:
                    with _span_prefill(n=len(batch)):
                        self._prefill(batch)
                if self._active:
                    with _span_decode_step(batch=len(self._active)):
                        self._decode_step()
                    changed = True
            except Exception as e:  # noqa: BLE001 - one bad batch must
                # not kill the serve loop: fail its requests, recycle
                # their pages, keep serving the queue
                log.exception("decode loop error; failing %d in-flight "
                              "request(s)", len(self._active))
                with self._cond:
                    failed, self._active = self._active, []
                for r in failed:
                    self.pool.release(r.id)
                    r.state = "failed"
                    r.error = f"{type(e).__name__}: {e}"
                    r.done.set()
                    if _histogram is not None:
                        # unit events: window_rate = failures/s — the
                        # canary bake's error-rate signal and the
                        # --slo rate-objective source
                        _histogram("serve_request_failures",
                                   "failed requests as unit events "
                                   "(windowed rate = failures/sec)"
                                   ).observe(1.0)
                changed = True
            if changed and self.snapshot_path:
                self.pool.snapshot(self.snapshot_path)

    def _admit_locked(self) -> List[Request]:
        """Move requests queue → active while a batch slot and enough
        free pages exist.  Sequential mode (the kill switch) admits one
        request only when the batch is empty — single-request serving."""
        cap = self.max_batch if self.continuous else 1
        admitted: List[Request] = []
        with _span_admit(queued=len(self._queue)):
            while self._queue and len(self._active) + len(admitted) < cap:
                r = self._queue[0]
                try:
                    self.pool.alloc(
                        r.id, len(r.prompt) + r.max_new_tokens)
                except PagePoolExhausted:
                    break            # backpressure: retry after retires
                self._queue.popleft()
                r.state = "active"
                self._active.append(r)
                admitted.append(r)
        if admitted:
            self._publish_queue_locked()
        return admitted

    def _table_row(self, r: Request) -> List[int]:
        t = self.pool.table_of(r.id)
        return t + [SCRATCH_PAGE] * (self.max_pages - len(t))

    def _prefill(self, admitted: List[Request]) -> None:
        """One packed launch for every request admitted this round;
        produces each request's first generated token (TTFT)."""
        b = len(admitted)
        t_pad = max(len(r.prompt) for r in admitted)
        # bucket the pad length: bounded set of compiled prefill shapes
        t_pad = -(-t_pad // 16) * 16
        t_pad = min(t_pad, self.model.cfg.max_context)
        tokens = np.zeros((b, t_pad), np.int32)
        lengths = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.max_pages), np.int32)
        for i, r in enumerate(admitted):
            tokens[i, :len(r.prompt)] = r.prompt
            lengths[i] = len(r.prompt)
            tables[i] = self._table_row(r)
        # testing/bench knob: a seeded-slow artifact (manifest
        # debug_prefill_delay_ms) inflates TTFT here — inside the
        # TTFT stamp, before the launch — so a canary bake has a
        # deterministic latency regression to detect.  Swap probes
        # call model.prefill directly and never pay it.
        delay = getattr(self.model, "debug_prefill_delay_s", 0.0)
        if delay:
            time.sleep(delay)
        nxt, _, self._k_pool, self._v_pool = self.model.prefill(
            self._k_pool, self._v_pool, tokens, lengths, tables)
        now = time.perf_counter()
        for i, r in enumerate(admitted):
            r.length = len(r.prompt)
            r.t_first = now
            if _histogram is not None:
                _histogram("serve_ttft_seconds",
                           "submit-to-first-token latency").observe(
                    now - r.t_submit)
            self._emit_token(r, int(nxt[i]))

    def _decode_step(self) -> None:
        """Advance every active request one token in a single
        fixed-width paged-attention launch; retire finished requests
        and recycle their pages at the step boundary."""
        slots = list(self._active)
        b = self.max_batch if self.continuous else 1
        enforce(len(slots) <= b,
                f"active {len(slots)} exceeds batch width {b}")
        tokens = np.zeros((b,), np.int32)
        lengths = np.ones((b,), np.int32)
        active = np.zeros((b,), bool)
        tables = np.full((b, self.max_pages), SCRATCH_PAGE, np.int32)
        for i, r in enumerate(slots):
            tokens[i] = r.next_token
            lengths[i] = r.length + 1    # feeding one new token
            active[i] = True
            tables[i] = self._table_row(r)
        if _gauge is not None:
            _gauge("serve_batch_size",
                   "requests in the most recent inference launch").set(
                len(slots))
        nxt, _, self._k_pool, self._v_pool = self.model.decode(
            self._k_pool, self._v_pool, tokens, tables, lengths, active)
        for i, r in enumerate(slots):
            r.length += 1
            self._emit_token(r, int(nxt[i]))

    def _emit_token(self, r: Request, token: int) -> None:
        """Record one generated token; finish the request on EOS or the
        token budget, releasing its pages for immediate recycling."""
        r.tokens.append(token)
        r.next_token = token
        self.generated_tokens += 1
        if _counter is not None:
            _counter("serve_tokens_generated",
                     "tokens generated across requests").inc()
        if token == self.model.cfg.eos_id \
                or len(r.tokens) >= r.max_new_tokens:
            self._finish(r)

    def _finish(self, r: Request) -> None:
        r.t_done = time.perf_counter()
        r.state = "done"
        self.pool.release(r.id)
        with self._cond:
            if r in self._active:
                self._active.remove(r)
            self._cond.notify_all()
        self.served += 1
        if _histogram is not None:
            _histogram("serve_request_seconds",
                       "submit-to-last-token latency").observe(
                r.latency_s)
            _counter("serve_requests", "requests served").inc()
        r.done.set()

    def _publish_queue_locked(self) -> None:
        if _gauge is not None:
            _gauge("serve_queue_depth",
                   "requests waiting for admission").set(len(self._queue))

    # --------------------------------------------------------- HTTP front
    def start_http(self, port: Optional[int] = None) -> int:
        """Serve ``POST /v1/generate`` + ``GET /healthz`` on
        ``--serve_bind`` (loopback unless explicitly opted out, same
        trust contract as ``--metrics_bind``).  Returns the bound port."""
        enforce(make_threading_server is not None,
                "observe.http unavailable: no HTTP front-end")
        if self._httpd is not None:
            return self._httpd.server_address[1]
        port = int(FLAGS.get("serve_port")) if port is None else int(port)
        host = resolve_bind_host("serve_bind")
        self._httpd = make_threading_server(host, port, _make_handler(self))
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=HTTP_THREAD_NAME, daemon=True)
        self._http_thread.start()
        bound = self._httpd.server_address[1]
        log.info("serving endpoint on http://%s:%d (/v1/generate /healthz)",
                 host, bound)
        return bound

    def stop_http(self) -> None:
        httpd, self._httpd = self._httpd, None
        t, self._http_thread = self._http_thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if t is not None:
            t.join(timeout=5.0)


def _make_handler(server: InferenceServer):
    class _Handler(BaseHTTPRequestHandler):
        server_version = "paddle-tpu-serving"

        def _send(self, code: int, payload: Dict) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self) -> None:  # noqa: N802 - stdlib API
            if self.path.split("?", 1)[0].rstrip("/") == "/healthz":
                self._send(200, dict(server.stats(), status="ok"))
            else:
                # /v1/swap only exists with rollout on — the kill
                # switch keeps this body byte-identical to pre-rollout
                paths = ["/v1/generate", "/healthz"]
                if server.rollout_enabled:
                    paths.append("/v1/swap")
                self._send(404, {"error": "unknown path",
                                 "paths": paths})

        def _do_swap(self) -> None:
            """POST /v1/swap {"artifact": dir[, "inflight": policy]} —
            the rolling coordinator's per-replica step.  Runs the full
            off-thread pipeline (verify → load → probe → flip) and
            returns the swap report; 500 carries a rolled-back report,
            so the coordinator halts without guessing."""
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                from . import rollout as _rollout
                report = _rollout.swap_from_artifact(
                    server, body["artifact"],
                    inflight=body.get("inflight"))
                ok = report.get("result") in ("ok", "unchanged")
                if ok and body.get("reason"):
                    # a coordinator-driven ROLLBACK swap: the swap
                    # itself succeeded (back to the old artifact) but
                    # the reason — e.g. a failed canary bake — must
                    # land on /healthz as a rolled_back state
                    server.record_swap_failure(str(body["reason"]))
                    report = dict(report, reason=str(body["reason"]))
                self._send(200 if ok else 500, report)
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001 - bad request must
                self._send(400, {"error": str(e)})  # never kill serving

        def do_POST(self) -> None:  # noqa: N802 - stdlib API
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/v1/swap" and server.rollout_enabled:
                self._do_swap()
                return
            if path != "/v1/generate":
                self._send(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                prompt = body["prompt"]
                max_new = int(body.get("max_new_tokens", 16))
                req = server.submit(prompt, max_new)
                tokens = server.result(req, timeout=60.0)
                self._send(200, {"id": req.id, "tokens": tokens,
                                 "ttft_ms": round(req.ttft_s * 1e3, 3),
                                 "latency_ms": round(
                                     req.latency_s * 1e3, 3)})
            except BrokenPipeError:      # client hung up mid-response
                pass
            except Exception as e:  # noqa: BLE001 - a bad request must
                self._send(400, {"error": str(e)})  # never kill serving

        def log_message(self, fmt: str, *args) -> None:
            log.debug("http %s", fmt % args)

    return _Handler

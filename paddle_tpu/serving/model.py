"""Decoder-only transformer for the serving stack — the model half of
the continuous-batching server (``serving/server.py``).

The two entry points mirror the two serving kernels from PR 14/15:

- :meth:`DecoderModel.prefill` runs a batch of mixed-length prompts in
  ONE ``flash_attention_packed`` launch per layer ([B, T] rows flattened
  to one packed [1, B*T] row with ``segments_from_lengths``), writes
  every prompt token's K/V into the rows' KV pages via
  ``paged_kv_write``, and returns each row's first generated token;
- :meth:`DecoderModel.decode` advances a fixed-width decode batch one
  token with ``paged_decode_attention`` over the shared page pool —
  inactive (padded) slots carry a scratch page table, zero write count,
  and length 1, so the kernel touches no memory the slot does not own.

Batch invariance is a load-bearing property, not an accident: every
per-row computation (matmuls, RMS norms, per-(b,h) attention grid rows,
``argmax`` sampling) is row-independent and runs in the same
within-row reduction order regardless of batch width, which is what
lets the ``--serve_continuous`` kill switch promise byte-for-byte
identical tokens between batched-continuous and sequential
single-request serving (pinned in ``tests/test_serving_server.py``).

Artifacts: :func:`export_decoder` writes the version-2 weights-only
int8 layout of ``serving/export.py`` (same ``weights.npz`` schema, no
StableHLO module — the decode loop is live code) with
``"kind": "decoder"`` in the manifest; :meth:`DecoderModel.from_artifact`
loads it through the shared ``loader.read_manifest`` /
``loader.load_weight_entries`` path, int8 dequantization included.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..layers.beam_search import eos_frozen_logits
from ..ops.pallas_attention import (flash_attention_packed, paged_kv_write,
                                    paged_decode_attention,
                                    segments_from_lengths)
from ..utils import enforce
from . import export as _export
from . import loader as _loader


class DecoderConfig(NamedTuple):
    """Shape of the served decoder (all sizes static — one compiled
    prefill per (B, T) bucket, one compiled decode step per batch
    width)."""
    vocab: int
    dim: int
    heads: int
    layers: int
    ffn: int
    max_context: int = 256
    eos_id: int = 1


def init_decoder_params(cfg: DecoderConfig, seed: int = 0
                        ) -> Dict[str, np.ndarray]:
    """Random fp32 decoder weights (scaled normal init); names are the
    artifact contract: ``embed``, ``pos_embed``, per layer
    ``l{i}.{ln1,ln2,wq,wk,wv,wo,w1,w2}``, ``ln_f``, ``lm_head``."""
    enforce(cfg.dim % cfg.heads == 0,
            f"dim {cfg.dim} not divisible by heads {cfg.heads}")
    rng = np.random.default_rng(seed)

    def mat(n_in, n_out):
        return (rng.standard_normal((n_in, n_out)) /
                np.sqrt(n_in)).astype(np.float32)

    p: Dict[str, np.ndarray] = {
        "embed": mat(cfg.vocab, cfg.dim) * np.float32(np.sqrt(cfg.vocab)),
        "pos_embed": (0.02 * rng.standard_normal(
            (cfg.max_context, cfg.dim))).astype(np.float32),
        "ln_f": np.ones(cfg.dim, np.float32),
        "lm_head": mat(cfg.dim, cfg.vocab),
    }
    for i in range(cfg.layers):
        p[f"l{i}.ln1"] = np.ones(cfg.dim, np.float32)
        p[f"l{i}.ln2"] = np.ones(cfg.dim, np.float32)
        for w, (a, b) in {"wq": (cfg.dim, cfg.dim), "wk": (cfg.dim, cfg.dim),
                          "wv": (cfg.dim, cfg.dim), "wo": (cfg.dim, cfg.dim),
                          "w1": (cfg.dim, cfg.ffn),
                          "w2": (cfg.ffn, cfg.dim)}.items():
            p[f"l{i}.{w}"] = mat(a, b)
    return p


def _rms(x, g, eps=1e-6):
    return (x * jax.lax.rsqrt(
        jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)) * g


def _ffn(x, p, i):
    h = jax.nn.gelu(_rms(x, p[f"l{i}.ln2"]) @ p[f"l{i}.w1"])
    return x + h @ p[f"l{i}.w2"]


def _qkv(xn, p, i, heads):
    b, t, d = xn.shape
    dh = d // heads

    def proj(w):
        return (xn @ p[f"l{i}.{w}"]).reshape(b, t, heads, dh)
    return proj("wq"), proj("wk"), proj("wv")


def _prefill_impl(params, k_pool, v_pool, tokens, lengths, page_indices,
                  cfg: DecoderConfig):
    """[B, T] padded prompts → ([B] first generated tokens, [B, V]
    logits, updated pools).  Packed causal attention: the batch is ONE
    [1, B*T] row; segment ids keep rows from attending across each
    other and mask padding outright."""
    b, t = tokens.shape
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    x = params["embed"][tokens] + params["pos_embed"][
        jnp.clip(pos, 0, cfg.max_context - 1)]
    segments = segments_from_lengths(lengths, b, t)
    zero = jnp.zeros((b,), jnp.int32)
    for i in range(cfg.layers):
        q, k, v = _qkv(_rms(x, params[f"l{i}.ln1"]), params, i, cfg.heads)
        # the decode contract: K/V must be in the pages before any
        # later step queries them — write the whole prompt now
        kp, vp = paged_kv_write(k_pool[i], v_pool[i], k, v,
                                page_indices, zero, lengths)
        k_pool = k_pool.at[i].set(kp)
        v_pool = v_pool.at[i].set(vp)
        dh = cfg.dim // cfg.heads
        attn = flash_attention_packed(
            q.reshape(1, b * t, cfg.heads, dh),
            k.reshape(1, b * t, cfg.heads, dh),
            v.reshape(1, b * t, cfg.heads, dh),
            segments, causal=True, slot=t)
        x = x + attn.reshape(b, t, cfg.dim) @ params[f"l{i}.wo"]
        x = _ffn(x, params, i)
    last = jnp.take_along_axis(
        x, jnp.clip(lengths - 1, 0, t - 1)[:, None, None], axis=1)[:, 0]
    logits = _rms(last, params["ln_f"]) @ params["lm_head"]
    active = lengths > 0
    nxt = jnp.argmax(eos_frozen_logits(logits, active, cfg.eos_id), -1)
    return nxt.astype(jnp.int32), logits, k_pool, v_pool


def _decode_impl(params, k_pool, v_pool, tokens, page_indices, lengths,
                 active, cfg: DecoderConfig):
    """One decode step for a fixed-width batch.  ``lengths`` INCLUDE the
    token being fed (its position is ``lengths - 1``); ``active`` masks
    padded slots — their K/V write count is zero and their kernel
    length clamps to 1 over the scratch page, so padding can neither
    write nor read real pool state."""
    b = tokens.shape[0]
    pos = jnp.clip(lengths - 1, 0, cfg.max_context - 1)
    x = (params["embed"][tokens] + params["pos_embed"][pos])[:, None, :]
    counts = active.astype(jnp.int32)
    klen = jnp.where(active, lengths, 1).astype(jnp.int32)
    for i in range(cfg.layers):
        q, k, v = _qkv(_rms(x, params[f"l{i}.ln1"]), params, i, cfg.heads)
        kp, vp = paged_kv_write(k_pool[i], v_pool[i], k, v,
                                page_indices, lengths - 1, counts)
        k_pool = k_pool.at[i].set(kp)
        v_pool = v_pool.at[i].set(vp)
        attn = paged_decode_attention(q, kp, vp, page_indices, klen)
        x = x + attn.reshape(b, 1, cfg.dim) @ params[f"l{i}.wo"]
        x = _ffn(x, params, i)
    logits = _rms(x[:, 0], params["ln_f"]) @ params["lm_head"]
    nxt = jnp.argmax(eos_frozen_logits(logits, active, cfg.eos_id), -1)
    return nxt.astype(jnp.int32), logits, k_pool, v_pool


@functools.lru_cache(maxsize=None)
def _jitted_steps(cfg: DecoderConfig):
    """One jitted (prefill, decode) pair PER CONFIG, shared by every
    :class:`DecoderModel` of that config.  Params are traced arguments
    (not closure constants), so two models with the same config hit
    the same executables — which is what makes a hot-swap
    (``serving/rollout.py``) actually zero-downtime: the swapped-in
    model rides every (B, T) bucket the serving process has already
    compiled instead of stalling the first post-flip requests behind
    a full recompile."""
    # static cfg via closure; jax caches one executable per
    # (B, T)/(B,) shape bucket.  No buffer donation: CPU (the test
    # platform) does not alias donations and warns per compile —
    # on TPU the pools would be donate_argnums=(1, 2)
    prefill = jax.jit(
        lambda p, kp, vp, tk, ln, pi: _prefill_impl(
            p, kp, vp, tk, ln, pi, cfg))
    decode = jax.jit(
        lambda p, kp, vp, tk, pi, ln, ac: _decode_impl(
            p, kp, vp, tk, pi, ln, ac, cfg))
    return prefill, decode


class DecoderModel:
    """A loaded decoder + its jitted prefill/decode steps.

    Pools are owned by the caller (the server) and threaded through
    every call — the model never holds KV state, so one model instance
    serves any number of pools/replicas reentrantly."""

    def __init__(self, params: Dict[str, Any], cfg: DecoderConfig):
        enforce(cfg.dim % cfg.heads == 0,
                f"dim {cfg.dim} not divisible by heads {cfg.heads}")
        self.cfg = cfg
        # fp32 on-device once; dequantized int8 artifacts land here too
        self.params = {k: jax.device_put(np.asarray(v))
                       for k, v in params.items()}
        self._prefill, self._decode = _jitted_steps(cfg)

    # ----------------------------------------------------------- pools
    def new_pools(self, n_pages: int, page_size: int
                  ) -> Tuple[jax.Array, jax.Array]:
        """Zeroed per-layer K/V pools, ``[L, P, page, H, Dh]``."""
        dh = self.cfg.dim // self.cfg.heads
        shape = (self.cfg.layers, n_pages, page_size, self.cfg.heads, dh)
        return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)

    # ----------------------------------------------------------- steps
    def prefill(self, k_pool, v_pool, tokens, lengths, page_indices):
        """Prompts in, first generated token out (plus updated pools).
        ``tokens`` [B, T] int32 padded, ``lengths`` [B], ``page_indices``
        [B, max_pages] physical page tables covering each prompt PLUS
        the tokens to be generated."""
        tokens = jnp.asarray(tokens, jnp.int32)
        enforce(tokens.ndim == 2 and tokens.shape[1] <= self.cfg.max_context,
                f"prompt batch {tokens.shape} exceeds max_context "
                f"{self.cfg.max_context}")
        nxt, logits, k_pool, v_pool = self._prefill(
            self.params, k_pool, v_pool, tokens,
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(page_indices, jnp.int32))
        return np.asarray(nxt), np.asarray(logits), k_pool, v_pool

    def decode(self, k_pool, v_pool, tokens, page_indices, lengths, active):
        """One continuous-batching decode step over the page pool."""
        nxt, logits, k_pool, v_pool = self._decode(
            self.params, k_pool, v_pool,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(page_indices, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(active, bool))
        return np.asarray(nxt), np.asarray(logits), k_pool, v_pool

    # -------------------------------------------------------- artifacts
    @classmethod
    def from_artifact(cls, dirname: str, verify: bool = True
                      ) -> "DecoderModel":
        """Load an exported decoder artifact (int8 entries dequantized
        once at load through the shared loader path).  ``verify``
        re-hashes the payload against the manifest digests first —
        a torn artifact raises :class:`loader.TornArtifact` before any
        weight byte is interpreted."""
        manifest = _loader.read_manifest(dirname)
        if verify:
            _loader.verify_artifact(dirname, manifest)
        enforce(manifest.get("kind") == "decoder",
                f"{dirname}: not a decoder artifact "
                f"(kind={manifest.get('kind')!r}); ServedModel.load "
                "handles module artifacts")
        cfg = DecoderConfig(**manifest["decoder"])
        wsec = manifest["weights"]
        weights = _loader.load_weight_entries(dirname, wsec)
        params = {e["name"]: w
                  for e, w in zip(wsec["entries"], weights)}
        model = cls(params, cfg)
        # testing/bench knob (export_decoder extra_meta): a seeded-slow
        # artifact carries debug_prefill_delay_ms in its manifest; the
        # server's _prefill sleeps it inside the TTFT stamp so a canary
        # bake has a deterministic latency regression to detect
        delay_ms = manifest.get("debug_prefill_delay_ms")
        if delay_ms:
            model.debug_prefill_delay_s = float(delay_ms) / 1e3
        return model


def export_decoder(params: Dict[str, Any], cfg: DecoderConfig,
                   dirname: str, quantize: Optional[str] = "int8",
                   dequant_dtype: str = "float32",
                   extra_meta: Optional[Dict[str, Any]] = None) -> str:
    """Write a decoder artifact: the version-2 weights layout of
    ``serving/export.py`` (int8 per-channel for ≥2-D floats when
    ``quantize="int8"``, raw otherwise) plus ``"kind": "decoder"`` and
    the :class:`DecoderConfig` in the manifest.  No StableHLO module —
    the paged decode loop is live code, not an exported graph.

    ``extra_meta`` lands verbatim in the manifest — the rollout
    pipeline records provenance there (``source_ckpt_digest``,
    ``source_ckpt``) so exactly-once export survives watcher restarts
    without any side-channel state file."""
    if quantize is None:
        store = {}
        entries = []
        for name in sorted(params):
            arr = np.asarray(params[name])
            store["w::" + name] = arr
            entries.append({"name": name, "shape": list(arr.shape),
                            "dtype": str(arr.dtype), "quantized": False,
                            "axis": None})
        scheme = "none"
    else:
        enforce(quantize == "int8",
                f"export_decoder: unknown quantize scheme {quantize!r}")
        store, entries = _export.quantize_weight_store(params, dequant_dtype)
        scheme = _export.QUANT_SCHEME
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, _export.WEIGHTS_FILE), **store)
    manifest = {
        "format": _export.FORMAT_NAME,
        "version": _export.QUANT_FORMAT_VERSION,
        "kind": "decoder",
        "decoder": dict(cfg._asdict()),
        "weights": {
            "file": _export.WEIGHTS_FILE,
            "scheme": scheme,
            "dequant_dtype": dequant_dtype,
            "entries": entries,
        },
    }
    if extra_meta:
        for k, v in extra_meta.items():
            enforce(k not in manifest,
                    f"export_decoder: extra_meta key {k!r} collides with "
                    "a manifest field")
            manifest[k] = v
    _export.stamp_manifest(manifest, dirname, [_export.WEIGHTS_FILE])
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return dirname

"""Pallas embedding row-gather: scalar-prefetched touched-row DMA.

The sparse-exchange gather (``parallel/sparse.py``): a batch's deduped
row-index table rides the grid spec's scalar prefetch, so each grid
step's HBM→VMEM DMA fetches exactly ONE touched table row — the [V, D]
table is never streamed, only the K rows the batch actually uses (the
PR 14 pattern: attention pair tables / page tables, transferred to
row-index prefetch; Ragged Paged Attention lineage).  Pad rows
(``height`` from ``unique_rows_sorted``, or -1 from ``unique_rows``)
clamp to a valid row in the index map — a repeated block index costs
no re-DMA — and their gathered values are dropped downstream
(``mode='drop'`` scatters / zero cotangents).

Fallback tier (the ``rnn_dispatch_total`` convention): shapes the
kernel doesn't cover take the plain XLA ``take`` gather with a
one-time warning; ``--embedding_kernel=false`` is the kill switch —
the dense gather path, byte-for-byte (both paths copy rows verbatim).
Off-TPU the dispatch also falls back (reason ``no_tpu``): interpret
mode executes the grid one emulated step at a time — seconds per call
at production K — so it is a numerics harness, not a runtime tier;
``--embedding_kernel_interpret`` opts tests into it at tiny shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..observe import counter
from ..utils import FLAGS
from ..utils.logger import get_logger, warn_once

_log = get_logger("ops.embedding")

# jax renamed TPUCompilerParams → CompilerParams (0.5.x); resolve once
# here so the module runs interpret-mode CI on either version.
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def record_embedding_dispatch(path: str, reason: str = "") -> None:
    """Count one embedding-gather lowering decision (trace-time: once
    per compiled program per shape).  ``reason`` is set when a
    kernel-capable call took the dense fallback, with the same labels
    the one-time fallback warnings use."""
    counter(
        "embedding_dispatch_total",
        "embedding row-gather lowering decisions by path (trace-time; "
        "reason labels match the one-time fallback warnings)",
    ).inc(path=path, reason=reason)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _gather_kernel(rows_ref, table_ref, out_ref):
    # the index map already steered this step's DMA to the selected
    # row; the body is a straight VMEM copy
    out_ref[:] = table_ref[:]       # ptpu: lint-ok[PT-TRACE] pallas ref


def gather_rows_reference(table: jax.Array, rows: jax.Array) -> jax.Array:
    """Dense XLA gather — the interpret-mode numerics contract and the
    kill-switch/fallback path.  Pad rows (-1 or >= V) clamp to a valid
    row; their values are unused by every caller."""
    safe = jnp.clip(rows.astype(jnp.int32), 0, table.shape[0] - 1)
    return jnp.take(table, safe, axis=0)


def _gather_rows_kernel(table: jax.Array, rows: jax.Array) -> jax.Array:
    v, d = table.shape
    k = rows.shape[0]
    # clamp pads (-1 / height) to a real row index at prefetch time so
    # the index map stays a pure table lookup
    safe = jnp.clip(rows.astype(jnp.int32), 0, v - 1)
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(k,),
            in_specs=[
                # one touched row per grid step: the scalar-prefetched
                # index table addresses the (1, D) HBM block directly
                pl.BlockSpec((1, d), lambda i, rows: (rows[i], 0)),
            ],
            out_specs=pl.BlockSpec((1, d), lambda i, rows: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((k, d), table.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(safe, table)


def _kernel_fallback_reason(table, rows, allow_kernel: bool) -> str:
    """Why this gather can't run the Pallas kernel ('' = it can)."""
    if not FLAGS.embedding_kernel:
        return "flag_off"
    if _interpret() and not FLAGS.embedding_kernel_interpret:
        # interpret mode emulates the grid step by step (seconds per
        # call at production K) — numerics-contract harness only
        return "no_tpu"
    if not allow_kernel:
        # caller-side veto: the table is mesh-sharded (the kernel is a
        # single-device program; the SPMD gather stays with XLA)
        return "sharded"
    if table.ndim != 2 or rows.ndim != 1:
        return "rank"
    if table.shape[1] % 128 != 0:
        return "unaligned"
    if table.dtype != jnp.float32:
        return "dtype"
    return ""


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _gather_rows(table, rows, allow_kernel):
    reason = _kernel_fallback_reason(table, rows, allow_kernel)
    if not reason:
        record_embedding_dispatch("kernel")
        return _gather_rows_kernel(table, rows)
    record_embedding_dispatch("dense", reason=reason)
    if reason not in ("flag_off", "sharded", "no_tpu"):
        warn_once(
            f"embedding_gather_dense_fallback:{reason}:"
            f"{tuple(table.shape)}",
            "embedding row gather: dense XLA fallback taken for table "
            "%s rows [%d]: %s", tuple(table.shape), rows.shape[0],
            reason, logger=_log)
    return gather_rows_reference(table, rows)


def _gather_rows_fwd(table, rows, allow_kernel):
    return _gather_rows(table, rows, allow_kernel), (rows, table)


def _gather_rows_bwd(allow_kernel, res, g):
    # cotangent w.r.t. the table: scatter the row cotangents back
    # (pads routed out of bounds and dropped).  Only taken when someone
    # differentiates THROUGH the gather — the trainer's exchange path
    # differentiates w.r.t. the gathered block instead, so the dense
    # [V, D] cotangent never appears there.
    rows, table = res
    v = table.shape[0]
    idx = jnp.where((rows < 0) | (rows >= v), v, rows)
    dt = jnp.zeros_like(table).at[idx].add(g.astype(table.dtype),
                                           mode="drop")
    return dt, None


_gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


def gather_rows(table: jax.Array, rows: jax.Array,
                allow_kernel: bool = True) -> jax.Array:
    """Gather ``table[rows]`` → [K, D], Pallas scalar-prefetch kernel
    on capable shapes (2-D fp32 table, lane-aligned D, ``allow_kernel``
    — callers veto when the table is mesh-sharded), dense XLA gather
    otherwise.  Pad rows (-1 or >= V) yield a clamped row whose value
    every caller discards."""
    return _gather_rows(table, rows, bool(allow_kernel))

"""Recurrent ops: LSTM / GRU / vanilla RNN over padded sequences.

The reference hand-fuses these in CUDA (``hl_cuda_lstm.cu``,
``paddle/gserver/layers/LstmCompute.cu``, ``GruCompute.cu``,
``paddle/operators/math/lstm_compute``) and batches variable-length
sequences per-timestep via length-sorting (``SequenceToBatch.h``,
``sequence2batch.h``).

TPU-first design: the input projection for *all* timesteps is one big
[B*T, 4H] matmul (MXU-saturating); only the small recurrent matmul sits in a
``lax.scan`` over time.  Padding is handled by carrying state through masked
steps unchanged — numerically identical to the reference's no-padding
scheduling, without dynamic shapes.  Peephole ("check") weights follow the
reference LSTM formulation.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.sequence import SequenceBatch
from .activations import get_activation
from .math_ops import matmul
from .registry import register_op


class LstmState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_gate_step(xw: jax.Array, state: LstmState, w_hh: jax.Array,
                   check_i: Optional[jax.Array] = None,
                   check_f: Optional[jax.Array] = None,
                   check_o: Optional[jax.Array] = None,
                   gate_act: str = "sigmoid", cell_act: str = "tanh",
                   out_act: str = "tanh") -> Tuple[LstmState, jax.Array]:
    """One fused LSTM step. xw: [B, 4H] pre-projected input (i,f,c,o order —
    reference gate layout in ``LstmCompute``); returns (new_state, h)."""
    h_dim = state.h.shape[-1]
    gates = xw + matmul(state.h, w_hh)
    i, f, c_in, o = jnp.split(gates, 4, axis=-1)
    ga = get_activation(gate_act)
    ca = get_activation(cell_act)
    oa = get_activation(out_act)
    if check_i is not None:
        i = i + state.c * check_i
        f = f + state.c * check_f
    i = ga(i)
    f = ga(f)
    c = f * state.c + i * ca(c_in)
    if check_o is not None:
        o = o + c * check_o
    o = ga(o)
    h = o * oa(c)
    return LstmState(h=h, c=c), h


@register_op("lstm")
def lstm_sequence(seq: SequenceBatch, w_ih, w_hh, bias=None,
                  check_i=None, check_f=None, check_o=None,
                  h0=None, c0=None, reverse: bool = False,
                  gate_act: str = "sigmoid", cell_act: str = "tanh",
                  out_act: str = "tanh") -> Tuple[SequenceBatch, LstmState]:
    """Run an LSTM over a padded sequence batch.

    seq.data: [B, T, D]; w_ih: [D, 4H]; w_hh: [H, 4H]; bias: [4H] (or
    [7H] with flattened peepholes when check_* are None).
    Returns (hidden SequenceBatch [B, T, H], final state).
    """
    b, t, _ = seq.data.shape
    h_dim = w_hh.shape[0]
    if w_ih is None:  # input already projected to 4H (lstmemory convention)
        xw = seq.data
    else:
        xw = matmul(seq.data.reshape(b * t, -1), w_ih).reshape(b, t, 4 * h_dim)
    if bias is not None:
        xw = xw + bias
    mask = seq.mask(xw.dtype)  # [B, T]
    if reverse:
        xw = xw[:, ::-1]
        mask = mask[:, ::-1]
    init = LstmState(
        h=jnp.zeros((b, h_dim), xw.dtype) if h0 is None else h0,
        c=jnp.zeros((b, h_dim), xw.dtype) if c0 is None else c0,
    )

    def step(state: LstmState, inputs):
        xw_t, m_t = inputs
        new_state, h = lstm_gate_step(
            xw_t, state, w_hh, check_i, check_f, check_o,
            gate_act, cell_act, out_act)
        m = m_t[:, None]
        keep = LstmState(h=m * new_state.h + (1 - m) * state.h,
                         c=m * new_state.c + (1 - m) * state.c)
        return keep, m * h

    final, hs = lax.scan(step, init, (jnp.moveaxis(xw, 1, 0), jnp.moveaxis(mask, 1, 0)), unroll=2)
    hs = jnp.moveaxis(hs, 0, 1)
    if reverse:
        hs = hs[:, ::-1]
    return SequenceBatch(data=hs, length=seq.length), final


@register_op("gru")
def gru_sequence(seq: SequenceBatch, w_ih, w_hh, bias=None, h0=None,
                 reverse: bool = False, gate_act: str = "sigmoid",
                 act: str = "tanh") -> Tuple[SequenceBatch, jax.Array]:
    """GRU over a padded batch (reference ``GruCompute``/``gru_unit_op``).

    Gate layout (u, r, c) matching the reference: w_ih [D, 3H],
    w_hh packs [H, 2H] update/reset and [H, H] candidate.
    """
    b, t, _ = seq.data.shape
    h_dim = w_hh.shape[0]
    if w_ih is None:  # input already projected to 3H (grumemory convention)
        xw = seq.data
    else:
        xw = matmul(seq.data.reshape(b * t, -1), w_ih).reshape(b, t, 3 * h_dim)
    if bias is not None:
        xw = xw + bias
    mask = seq.mask(xw.dtype)
    if reverse:
        xw = xw[:, ::-1]
        mask = mask[:, ::-1]
    w_gates = w_hh[:, : 2 * h_dim]
    w_cand = w_hh[:, 2 * h_dim:]
    ga = get_activation(gate_act)
    ca = get_activation(act)
    init = jnp.zeros((b, h_dim), xw.dtype) if h0 is None else h0

    def step(h, inputs):
        xw_t, m_t = inputs
        xu, xr, xc = jnp.split(xw_t, 3, axis=-1)
        gates = matmul(h, w_gates)
        hu, hr = jnp.split(gates, 2, axis=-1)
        u = ga(xu + hu)
        r = ga(xr + hr)
        c = ca(xc + matmul(r * h, w_cand))
        # reference GruCompute: h_new = u * h_prev + (1 - u) * c
        h_new = u * h + (1.0 - u) * c
        m = m_t[:, None]
        h_keep = m * h_new + (1 - m) * h
        return h_keep, m * h_new

    final, hs = lax.scan(step, init, (jnp.moveaxis(xw, 1, 0), jnp.moveaxis(mask, 1, 0)), unroll=2)
    hs = jnp.moveaxis(hs, 0, 1)
    if reverse:
        hs = hs[:, ::-1]
    return SequenceBatch(data=hs, length=seq.length), final


@register_op("recurrent")
def simple_rnn(seq: SequenceBatch, w_hh, bias=None, h0=None,
               reverse: bool = False, act: str = "tanh"
               ) -> Tuple[SequenceBatch, jax.Array]:
    """Plain recurrent layer (``RecurrentLayer``): input is already
    projected; h_t = act(x_t + h_{t-1} W + b)."""
    b, t, h_dim = seq.data.shape
    x = seq.data
    if bias is not None:
        x = x + bias
    mask = seq.mask(x.dtype)
    if reverse:
        x = x[:, ::-1]
        mask = mask[:, ::-1]
    a = get_activation(act)
    init = jnp.zeros((b, h_dim), x.dtype) if h0 is None else h0

    def step(h, inputs):
        x_t, m_t = inputs
        h_new = a(x_t + matmul(h, w_hh))
        m = m_t[:, None]
        h_keep = m * h_new + (1 - m) * h
        return h_keep, m * h_new

    final, hs = lax.scan(step, init, (jnp.moveaxis(x, 1, 0), jnp.moveaxis(mask, 1, 0)), unroll=2)
    hs = jnp.moveaxis(hs, 0, 1)
    if reverse:
        hs = hs[:, ::-1]
    return SequenceBatch(data=hs, length=seq.length), final


@register_op("lstm_unit", n_outputs=2)
def lstm_unit(x_proj, c_prev, forget_bias: float = 0.0):
    """Stateless LSTM cell math (``lstm_unit_op.cc``): x_proj [B, 4H]
    already includes W x + W h; returns (c, h)."""
    i, f, o, j = jnp.split(x_proj, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return c, h


@register_op("gru_unit", n_outputs=1)
def gru_unit(x_proj, h_prev, w_hh, gate_act: str = "sigmoid",
             act: str = "tanh"):
    """Single GRU step given pre-projected input [B, 3H] (``gru_unit_op``)."""
    h_dim = h_prev.shape[-1]
    xu, xr, xc = jnp.split(x_proj, 3, axis=-1)
    gates = matmul(h_prev, w_hh[:, : 2 * h_dim])
    hu, hr = jnp.split(gates, 2, axis=-1)
    ga = get_activation(gate_act)
    ca = get_activation(act)
    u = ga(xu + hu)
    r = ga(xr + hr)
    c = ca(xc + matmul(r * h_prev, w_hh[:, 2 * h_dim:]))
    return u * h_prev + (1.0 - u) * c

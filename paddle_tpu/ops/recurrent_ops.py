"""Recurrent ops: LSTM / GRU / vanilla RNN over padded sequences.

The reference hand-fuses these in CUDA (``hl_cuda_lstm.cu``,
``paddle/gserver/layers/LstmCompute.cu``, ``GruCompute.cu``,
``paddle/operators/math/lstm_compute``) and batches variable-length
sequences per-timestep via length-sorting (``SequenceToBatch.h``,
``sequence2batch.h``).

TPU-first design: the input projection for *all* timesteps is one big
[B*T, 4H] matmul (MXU-saturating); only the small recurrent matmul sits in a
``lax.scan`` over time.  Padding is handled by carrying state through masked
steps unchanged — numerically identical to the reference's no-padding
scheduling, without dynamic shapes.  Peephole ("check") weights follow the
reference LSTM formulation.

Precision: the stacked gate-input tensor and per-step matmuls run in the
policy compute dtype (bf16 by default — read-only data, no accumulation
concern; halves the sequential phase's HBM traffic and keeps the MXU on
the fast path), while the scan CARRIES (h, and the accumulating cell
state c) stay in the policy *output* dtype — fp32 unless the user opts
into ``--bf16_activations``, preserving reference-parity accumulation
numerics by default.  Measured on the benchmark 2×LSTM: 8.8 ms fp32
everywhere → 5.3 ms with full bf16 (flag on).  ``full_precision()``
(checkgrad) keeps everything fp32.  ``unroll=4`` amortizes scan dispatch
without blowing up the program (8 regresses — measured).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dtypes import current_policy, record_op_precision
from ..core.sequence import SequenceBatch
from ..observe import counter
from ..utils.logger import get_logger, warn_once
from .activations import get_activation
from .math_ops import matmul
from .registry import register_op

_log = get_logger("ops.recurrent")


def _fallback_reason(b: int, h: int) -> str:
    """Why a default-activation (B, H) shape is off the fused tiers —
    the structured label shared by the one-time warning and the
    ``rnn_dispatch_total`` counter."""
    from ..utils import FLAGS
    if b % 8:
        return "batch not a multiple of 8 (sublane tiling)"
    if h % 128:
        return "hidden not a multiple of 128 (lane tiling)"
    if h > 512 and not FLAGS.fused_rnn_hblock:
        return ("hidden>512 with the blocked tier disabled "
                "(--fused_rnn_hblock=false)")
    return ("hidden>512 and past even the blocked tier's "
            "streamed-VMEM budget")


def _record_dispatch(kind: str, b: int, h: int, path: str,
                     reason: str = "") -> None:
    """Count one lowering decision.  These ops run at TRACE time, so the
    counter ticks once per compiled program per shape, not once per
    executed step — exactly the "which path did this step take"
    question (one series per (kind, path, reason))."""
    counter(
        "rnn_dispatch_total",
        "RNN lowering decisions by tier (trace-time; reason labels "
        "match the one-time fallback warnings)",
    ).inc(kind=kind, path=path, reason=reason)


def _warn_scan_fallback(kind: str, b: int, h: int) -> str:
    """One-time structured warning when a default-activation sequence
    that WOULD use a fused Pallas kernel falls back to the lax.scan
    path (VERDICT: the old H ≤ 512 VMEM gate used to be silent, hiding
    the un-fused gap at the baseline's own hidden=1280 row — that row
    now runs the round-8 blocked tier, so this warning marks truly
    off-tile shapes or a disabled blocked tier).  Keyed per (kind, B,
    H) so a training loop logs each distinct shape once; returns the
    reason label."""
    reason = _fallback_reason(b, h)
    warn_once(
        f"fused_{kind}_fallback:{b}x{h}",
        "fused_%s_fallback: scan path taken for batch=%d hidden=%d "
        "(%s); throughput is the pre-fusion tier — see "
        "bench.py::bench_lstm_1280 for the measured gap", kind, b, h,
        reason, logger=_log)
    return reason

_UNROLL = 4  # measured sweet spot for the sequential phase (see module doc)


class LstmState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_gate_step(xw: jax.Array, state: LstmState, w_hh: jax.Array,
                   check_i: Optional[jax.Array] = None,
                   check_f: Optional[jax.Array] = None,
                   check_o: Optional[jax.Array] = None,
                   gate_act: str = "sigmoid", cell_act: str = "tanh",
                   out_act: str = "tanh") -> Tuple[LstmState, jax.Array]:
    """One fused LSTM step. xw: [B, 4H] pre-projected input (i,f,c,o order —
    reference gate layout in ``LstmCompute``); returns (new_state, h).
    ``w_hh=None`` skips the recurrent projection (``LstmStepLayer.cpp``
    semantics: the input already contains every contribution)."""
    h_dim = state.h.shape[-1]
    if w_hh is None:
        gates = xw
    else:
        # MXU matmul in the policy compute dtype, result cast to the
        # carry dtype (NOT math_ops.matmul, whose output-dtype cast
        # would destabilize scan carry dtypes)
        cd = current_policy().compute_dtype
        gates = xw + (state.h.astype(cd) @ w_hh.astype(cd)).astype(xw.dtype)
    i, f, c_in, o = jnp.split(gates, 4, axis=-1)
    ga = get_activation(gate_act)
    ca = get_activation(cell_act)
    oa = get_activation(out_act)
    if check_i is not None:
        i = i + state.c * check_i.astype(xw.dtype)
        f = f + state.c * check_f.astype(xw.dtype)
    i = ga(i)
    f = ga(f)
    c = f * state.c + i * ca(c_in)
    if check_o is not None:
        o = o + c * check_o.astype(xw.dtype)
    o = ga(o)
    h = o * oa(c)
    return LstmState(h=h, c=c), h


@register_op("lstm")
def lstm_sequence(seq: SequenceBatch, w_ih, w_hh, bias=None,
                  check_i=None, check_f=None, check_o=None,
                  h0=None, c0=None, reverse: bool = False,
                  gate_act: str = "sigmoid", cell_act: str = "tanh",
                  out_act: str = "tanh", return_cells: bool = False):
    """Run an LSTM over a padded sequence batch.

    seq.data: [B, T, D]; w_ih: [D, 4H]; w_hh: [H, 4H]; bias: [4H] (or
    [7H] with flattened peepholes when check_* are None).
    Returns (hidden SequenceBatch [B, T, H], final state), plus the
    per-step cell SequenceBatch as a third element when
    ``return_cells`` (the framework ``lstm`` op's Cell output).
    """
    b, t, _ = seq.data.shape
    h_dim = w_hh.shape[0]
    pol = current_policy()
    record_op_precision("lstm")
    cd = pol.compute_dtype
    if w_ih is None:  # input already projected to 4H (lstmemory convention)
        xw = seq.data.astype(cd)
    else:
        xw = (seq.data.reshape(b * t, -1).astype(cd)
              @ w_ih.astype(cd)).reshape(b, t, 4 * h_dim)
    if bias is not None:
        xw = xw + bias.astype(cd)
    mask = seq.mask(xw.dtype)  # [B, T]
    if reverse:
        xw = xw[:, ::-1]
        mask = mask[:, ::-1]

    # Fused whole-sequence Pallas kernel (the hl_cuda_lstm tier): one
    # launch carries h/c across T in VMEM with w_hh resident — no
    # per-scan-step XLA fixed costs.  Default activations + tileable
    # shapes only; anything else takes the scan below.  The kernel does
    # its gate math in f32 regardless of the bf16 policy (the VMEM
    # carries are free to keep full precision), so under
    # --bf16_activations it is a strict numerics upgrade over the bf16
    # scan — equivalence in both regimes is pinned by
    # tests/test_pallas_lstm.py.
    def pack(arr):
        """Cast to the policy dtype, undo time reversal, wrap."""
        arr = arr.astype(pol.output_dtype)
        if reverse:
            arr = arr[:, ::-1]
        return SequenceBatch(data=arr, length=seq.length)

    if gate_act == "sigmoid" and cell_act == "tanh" and out_act == "tanh":
        from .pallas_lstm import (fused_ok, fused_tier,
                                  lstm_fused_sequence,
                                  lstm_fused_sequence_blocked)
        # fused_ok (== fused_tier is not None) stays the gate despite
        # the second predicate call below: it is the monkeypatch kill
        # point every equivalence test uses to force the scan reference
        if not fused_ok(b, h_dim):
            _record_dispatch("lstm", b, h_dim, "scan",
                             _warn_scan_fallback("lstm", b, h_dim))
        else:
            tier = fused_tier(b, h_dim) or "fused"
            _record_dispatch("lstm", b, h_dim, tier)
            fn = lstm_fused_sequence_blocked \
                if tier == "fused_blocked" \
                else lstm_fused_sequence
            y, cy, fh, fc = fn(
                xw, mask, w_hh, check_i, check_f, check_o, h0, c0)
            final = LstmState(h=fh.astype(pol.output_dtype),
                              c=fc.astype(pol.output_dtype))
            if return_cells:
                return pack(y), final, pack(cy)
            return pack(y), final
    else:
        _record_dispatch("lstm", b, h_dim, "scan",
                         "non-default activations")

    carry_dt = pol.output_dtype   # fp32 unless --bf16_activations
    init = LstmState(
        h=jnp.zeros((b, h_dim), carry_dt) if h0 is None
        else h0.astype(carry_dt),
        c=jnp.zeros((b, h_dim), carry_dt) if c0 is None
        else c0.astype(carry_dt),
    )

    def step(state: LstmState, inputs):
        xw_t, m_t = inputs
        new_state, h = lstm_gate_step(
            xw_t, state, w_hh, check_i, check_f, check_o,
            gate_act, cell_act, out_act)
        m = m_t[:, None]
        keep = LstmState(h=m * new_state.h + (1 - m) * state.h,
                         c=m * new_state.c + (1 - m) * state.c)
        y = (m * h, m * new_state.c) if return_cells else m * h
        return keep, y

    final, ys = lax.scan(step, init,
                         (jnp.moveaxis(xw, 1, 0), jnp.moveaxis(mask, 1, 0)),
                         unroll=_UNROLL)
    final = LstmState(h=final.h.astype(pol.output_dtype),
                      c=final.c.astype(pol.output_dtype))
    if return_cells:
        return (pack(jnp.moveaxis(ys[0], 0, 1)), final,
                pack(jnp.moveaxis(ys[1], 0, 1)))
    return pack(jnp.moveaxis(ys, 0, 1)), final


@register_op("gru")
def gru_sequence(seq: SequenceBatch, w_ih, w_hh, bias=None, h0=None,
                 reverse: bool = False, gate_act: str = "sigmoid",
                 act: str = "tanh") -> Tuple[SequenceBatch, jax.Array]:
    """GRU over a padded batch (reference ``GruCompute``/``gru_unit_op``).

    Gate layout (u, r, c) matching the reference: w_ih [D, 3H],
    w_hh packs [H, 2H] update/reset and [H, H] candidate.
    """
    b, t, _ = seq.data.shape
    h_dim = w_hh.shape[0]
    pol = current_policy()
    record_op_precision("gru")
    cd = pol.compute_dtype
    if w_ih is None:  # input already projected to 3H (grumemory convention)
        xw = seq.data.astype(cd)
    else:
        xw = (seq.data.reshape(b * t, -1).astype(cd)
              @ w_ih.astype(cd)).reshape(b, t, 3 * h_dim)
    if bias is not None:
        xw = xw + bias.astype(cd)
    mask = seq.mask(xw.dtype)
    if reverse:
        xw = xw[:, ::-1]
        mask = mask[:, ::-1]
    # Fused whole-sequence Pallas kernel (see pallas_lstm.py — same
    # dispatch contract; gate math is f32 regardless of policy)
    if gate_act == "sigmoid" and act == "tanh":
        from .pallas_gru import (fused_ok, fused_tier,
                                 gru_fused_sequence,
                                 gru_fused_sequence_blocked)
        if not fused_ok(b, h_dim):
            _record_dispatch("gru", b, h_dim, "scan",
                             _warn_scan_fallback("gru", b, h_dim))
        else:
            tier = fused_tier(b, h_dim) or "fused"
            _record_dispatch("gru", b, h_dim, tier)
            fn = gru_fused_sequence_blocked \
                if tier == "fused_blocked" \
                else gru_fused_sequence
            y, fh = fn(xw, mask, w_hh[:, :2 * h_dim],
                       w_hh[:, 2 * h_dim:], h0)
            hs = y.astype(pol.output_dtype)
            if reverse:
                hs = hs[:, ::-1]
            return SequenceBatch(data=hs, length=seq.length), \
                fh.astype(pol.output_dtype)
    else:
        _record_dispatch("gru", b, h_dim, "scan",
                         "non-default activations")

    w_gates = w_hh[:, : 2 * h_dim].astype(cd)
    w_cand = w_hh[:, 2 * h_dim:].astype(cd)
    ga = get_activation(gate_act)
    ca = get_activation(act)
    carry_dt = pol.output_dtype   # fp32 unless --bf16_activations
    init = jnp.zeros((b, h_dim), carry_dt) if h0 is None \
        else h0.astype(carry_dt)

    def step(h, inputs):
        xw_t, m_t = inputs
        xu, xr, xc = jnp.split(xw_t, 3, axis=-1)
        gates = (h.astype(cd) @ w_gates).astype(xw_t.dtype)
        hu, hr = jnp.split(gates, 2, axis=-1)
        u = ga(xu + hu)
        r = ga(xr + hr)
        c = ca(xc + ((r * h).astype(cd) @ w_cand).astype(xw_t.dtype))
        # reference GruCompute: h_new = u * h_prev + (1 - u) * c
        h_new = u * h + (1.0 - u) * c
        m = m_t[:, None]
        h_keep = m * h_new + (1 - m) * h
        return h_keep, m * h_new

    final, hs = lax.scan(step, init,
                         (jnp.moveaxis(xw, 1, 0), jnp.moveaxis(mask, 1, 0)),
                         unroll=_UNROLL)
    hs = jnp.moveaxis(hs, 0, 1).astype(pol.output_dtype)
    if reverse:
        hs = hs[:, ::-1]
    return SequenceBatch(data=hs, length=seq.length), \
        final.astype(pol.output_dtype)


@register_op("recurrent")
def simple_rnn(seq: SequenceBatch, w_hh, bias=None, h0=None,
               reverse: bool = False, act: str = "tanh"
               ) -> Tuple[SequenceBatch, jax.Array]:
    """Plain recurrent layer (``RecurrentLayer``): input is already
    projected; h_t = act(x_t + h_{t-1} W + b)."""
    b, t, h_dim = seq.data.shape
    pol = current_policy()
    record_op_precision("recurrent")
    cd = pol.compute_dtype
    x = seq.data.astype(cd)
    if bias is not None:
        x = x + bias.astype(cd)
    mask = seq.mask(x.dtype)
    if reverse:
        x = x[:, ::-1]
        mask = mask[:, ::-1]
    a = get_activation(act)
    w = w_hh.astype(cd)
    carry_dt = pol.output_dtype   # fp32 unless --bf16_activations
    init = jnp.zeros((b, h_dim), carry_dt) if h0 is None \
        else h0.astype(carry_dt)

    def step(h, inputs):
        x_t, m_t = inputs
        # h is the accumulating state: sum+activation in the carry dtype
        h_new = a(x_t.astype(carry_dt)
                  + (h.astype(cd) @ w).astype(carry_dt))
        m = m_t[:, None]
        h_keep = m * h_new + (1 - m) * h
        return h_keep, m * h_new

    final, hs = lax.scan(step, init,
                         (jnp.moveaxis(x, 1, 0), jnp.moveaxis(mask, 1, 0)),
                         unroll=_UNROLL)
    hs = jnp.moveaxis(hs, 0, 1).astype(pol.output_dtype)
    if reverse:
        hs = hs[:, ::-1]
    return SequenceBatch(data=hs, length=seq.length), \
        final.astype(pol.output_dtype)


@register_op("lstm_unit", n_outputs=2)
def lstm_unit(x_proj, c_prev, forget_bias: float = 0.0):
    """Stateless LSTM cell math (``lstm_unit_op.cc``): x_proj [B, 4H]
    already includes W x + W h; returns (c, h)."""
    i, f, o, j = jnp.split(x_proj, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return c, h


@register_op("gru_unit", n_outputs=1)
def gru_unit(x_proj, h_prev, w_hh, gate_act: str = "sigmoid",
             act: str = "tanh"):
    """Single GRU step given pre-projected input [B, 3H] (``gru_unit_op``)."""
    h_dim = h_prev.shape[-1]
    xu, xr, xc = jnp.split(x_proj, 3, axis=-1)
    gates = matmul(h_prev, w_hh[:, : 2 * h_dim])
    hu, hr = jnp.split(gates, 2, axis=-1)
    ga = get_activation(gate_act)
    ca = get_activation(act)
    u = ga(xu + hu)
    r = ga(xr + hr)
    c = ca(xc + matmul(r * h_prev, w_hh[:, 2 * h_dim:]))
    return u * h_prev + (1.0 - u) * c

"""Fused GRU sequence as Pallas TPU kernels.

Companion to :mod:`paddle_tpu.ops.pallas_lstm` — the second half of the
``hl_cuda_lstm.cu`` / ``hl_cuda_gru`` kernel tier SURVEY §7 names.  The
whole time loop runs in one launch: h carried in VMEM f32 scratch, both
recurrent weights (w_gates [H, 2H], w_cand [H, H]) resident, per step
two MXU matmuls (gate and candidate projections) plus the sigmoid/tanh
gate math on the VPU, with the length-masked keep.  Backward is a
reversed-grid BPTT kernel accumulating dW directly in constant-block
output refs.  Gate layout (u, r, c) and the update rule
``h' = u·h + (1−u)·c`` match ``recurrent_ops.gru_sequence`` exactly —
equivalence is pinned by ``tests/test_pallas_gru.py``.

Same dispatch contract as the LSTM kernel: default activations and
tileable shapes only; anything else takes the ``lax.scan`` path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import CompilerParams, _interpret
from .pallas_lstm import fused_ok  # same B/H tiling + VMEM gate


def _sig(x):
    return jax.nn.sigmoid(x)


# --------------------------------------------------------------- forward
def _fwd_kernel(xw_ref, m_ref, wg_ref, wc_ref, h0_ref, hseq_ref,
                gates_ref, h_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_s[:] = h0_ref[...].astype(jnp.float32)

    h_prev = h_s[:]                                     # [B, H] f32
    hd = h_prev.shape[-1]
    xw = xw_ref[0].astype(jnp.float32)                  # [B, 3H]
    xu = xw[:, :hd]
    xr = xw[:, hd:2 * hd]
    xc = xw[:, 2 * hd:]
    g = h_prev @ wg_ref[...].astype(jnp.float32)        # [B, 2H]
    u = _sig(xu + g[:, :hd])
    r = _sig(xr + g[:, hd:])
    c = jnp.tanh(xc + (r * h_prev) @ wc_ref[...].astype(jnp.float32))
    h_new = u * h_prev + (1.0 - u) * c

    m = m_ref[0, 0].astype(jnp.float32)[:, None]        # [B, 1]
    h_keep = m * h_new + (1.0 - m) * h_prev
    h_s[:] = h_keep
    hseq_ref[0] = h_keep.astype(hseq_ref.dtype)
    gates_ref[0] = jnp.concatenate([u, r, c],
                                   axis=-1).astype(gates_ref.dtype)


def _fwd_call(xw, mask, w_gates, w_cand, h0):
    t, b, hd3 = xw.shape
    hd = hd3 // 3
    return pl.pallas_call(
        _fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd3), lambda i: (i, 0, 0)),   # xw
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0)),     # mask
            pl.BlockSpec((hd, 2 * hd), lambda i: (0, 0)),     # w_gates
            pl.BlockSpec((hd, hd), lambda i: (0, 0)),         # w_cand
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # h0
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd), lambda i: (i, 0, 0)),    # H
            pl.BlockSpec((1, b, hd3), lambda i: (i, 0, 0)),   # gates
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd3), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, hd), jnp.float32)],    # h carry
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(xw, mask, w_gates, w_cand, h0)


# -------------------------------------------------------------- backward
def _bwd_kernel(gates_ref, hprev_ref, m_ref, wg_ref, wc_ref, dy_ref,
                dxw_ref, dwg_ref, dwc_ref, dh0_ref, dh_s, *, t_total):
    """Grid step i visits t = T-1-i.  dy is the external cotangent on
    the kept H_t; it joins the carry BEFORE the masked split so the
    (1−m) passthrough mirrors the forward keep."""
    i_rev = pl.program_id(0)

    @pl.when(i_rev == 0)
    def _init():
        dh_s[:] = jnp.zeros_like(dh_s)
        dwg_ref[...] = jnp.zeros_like(dwg_ref)
        dwc_ref[...] = jnp.zeros_like(dwc_ref)

    hd = dh_s.shape[-1]
    gates = gates_ref[0].astype(jnp.float32)
    u = gates[:, :hd]
    r = gates[:, hd:2 * hd]
    c = gates[:, 2 * hd:]
    h_prev = hprev_ref[0].astype(jnp.float32)
    m = m_ref[0, 0].astype(jnp.float32)[:, None]

    dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:]
    dh_new = m * dh_tot                                 # raw-h' share
    du_pre = dh_new * (h_prev - c) * u * (1.0 - u)
    dc_pre = dh_new * (1.0 - u) * (1.0 - c * c)
    drh = dc_pre @ wc_ref[...].astype(jnp.float32).T    # d(r·h_prev)
    dr_pre = drh * h_prev * r * (1.0 - r)
    dg = jnp.concatenate([du_pre, dr_pre], axis=-1)     # [B, 2H]

    dh_prev = (dh_new * u + drh * r
               + dg @ wg_ref[...].astype(jnp.float32).T)
    dh_s[:] = (1.0 - m) * dh_tot + dh_prev
    dwg_ref[...] = dwg_ref[...] + h_prev.T @ dg
    dwc_ref[...] = dwc_ref[...] + (r * h_prev).T @ dc_pre
    dxw_ref[0] = jnp.concatenate([du_pre, dr_pre, dc_pre],
                                 axis=-1).astype(dxw_ref.dtype)

    @pl.when(i_rev == t_total - 1)
    def _flush():
        dh0_ref[...] = dh_s[:].astype(dh0_ref.dtype)


def _bwd_call(gates, h_prev_seq, mask, w_gates, w_cand, dy):
    t, b, hd3 = gates.shape
    hd = hd3 // 3
    rev3 = lambda i: (t - 1 - i, 0, 0)
    kernel = functools.partial(_bwd_kernel, t_total=t)
    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd3), rev3),                  # gates
            pl.BlockSpec((1, b, hd), rev3),                   # H_{t-1}
            pl.BlockSpec((1, 1, b), lambda i: (t - 1 - i, 0, 0)),  # mask
            pl.BlockSpec((hd, 2 * hd), lambda i: (0, 0)),     # w_gates
            pl.BlockSpec((hd, hd), lambda i: (0, 0)),         # w_cand
            pl.BlockSpec((1, b, hd), rev3),                   # dy (dH)
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd3), rev3),                  # dxw
            pl.BlockSpec((hd, 2 * hd), lambda i: (0, 0)),     # dw_gates
            pl.BlockSpec((hd, hd), lambda i: (0, 0)),         # dw_cand
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # dh0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd3), jnp.float32),
            jax.ShapeDtypeStruct((hd, 2 * hd), jnp.float32),
            jax.ShapeDtypeStruct((hd, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, hd), jnp.float32)],    # dh carry
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(gates, h_prev_seq, mask, w_gates, w_cand, dy)


# ------------------------------------------------------------ custom vjp
@jax.custom_vjp
def _gru_core(xw, mask, w_gates, w_cand, h0):
    """xw [T, B, 3H] (input projection + bias applied), mask [T, 1, B],
    w_gates [H, 2H], w_cand [H, H], h0 [B, H].  Returns the kept state
    sequence H [T, B, Hd] in f32."""
    h_seq, _gates = _fwd_call(xw, mask, w_gates, w_cand, h0)
    return h_seq


def _gru_core_fwd(xw, mask, w_gates, w_cand, h0):
    h_seq, gates = _fwd_call(xw, mask, w_gates, w_cand, h0)
    return h_seq, (gates, h_seq, mask, w_gates, w_cand, h0)


def _gru_core_bwd(res, dh_seq):
    gates, h_seq, mask, w_gates, w_cand, h0 = res
    h_prev_seq = jnp.concatenate([h0[None].astype(h_seq.dtype),
                                  h_seq[:-1]], axis=0)
    dxw, dwg, dwc, dh0 = _bwd_call(gates, h_prev_seq, mask, w_gates,
                                   w_cand, dh_seq)
    return (dxw.astype(mask.dtype), jnp.zeros_like(mask), dwg, dwc, dh0)


_gru_core.defvjp(_gru_core_fwd, _gru_core_bwd)


def gru_fused_sequence(xw, mask, w_gates, w_cand, h0):
    """Batch-major wrapper: xw [B, T, 3H] pre-projected (+bias), mask
    [B, T]; returns (y [B, T, H] masked hidden outputs, final_h [B, H])
    in f32 — callers cast per their dtype policy."""
    b, t, hd3 = xw.shape
    hd = hd3 // 3
    h0 = jnp.zeros((b, hd), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    h_seq = _gru_core(
        jnp.moveaxis(xw, 1, 0),
        jnp.moveaxis(mask, 1, 0).astype(xw.dtype)[:, None, :],
        w_gates.astype(jnp.float32), w_cand.astype(jnp.float32), h0)
    y = jnp.moveaxis(h_seq, 0, 1) * mask.astype(jnp.float32)[:, :, None]
    return y, h_seq[-1]

"""Fused GRU sequence as Pallas TPU kernels.

Companion to :mod:`paddle_tpu.ops.pallas_lstm` — the second half of the
``hl_cuda_lstm.cu`` / ``hl_cuda_gru`` kernel tier SURVEY §7 names.  The
whole time loop runs in one launch: h carried in VMEM f32 scratch, both
recurrent weights (w_gates [H, 2H], w_cand [H, H]) resident, per step
two MXU matmuls (gate and candidate projections) plus the sigmoid/tanh
gate math on the VPU, with the length-masked keep.  Backward is a
reversed-grid BPTT kernel accumulating dW directly in constant-block
output refs.  Gate layout (u, r, c) and the update rule
``h' = u·h + (1−u)·c`` match ``recurrent_ops.gru_sequence`` exactly —
equivalence is pinned by ``tests/test_pallas_gru.py``.

Same dispatch contract as the LSTM kernel: default activations and
tileable shapes only; anything else takes the ``lax.scan`` path.

Round 8 adds the hidden-blocked tier for 512 < H (see pallas_lstm.py
for the scheme): because the candidate projection needs the full reset
gate first, each time step runs as TWO phases over the inner grid dim
— grid (T, 2·H/Hb), gate blocks then candidate blocks — with w_gates
and w_cand streamed as column blocks and min/max-pinned index maps so
each weight stream moves exactly its own bytes per step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import CompilerParams, _interpret
from .pallas_lstm import (HBLOCK, _from_gate_blocks, _to_gate_blocks,
                          fused_tier as _lstm_fused_tier)


def fused_tier(b: int, h: int):
    """Same two-tier dispatch as the LSTM kernel, with the GRU's gate
    width (3H: u|r gates 2H + candidate H) in the streamed-block VMEM
    estimate."""
    return _lstm_fused_tier(b, h, n_gates=3)


def fused_ok(b: int, h: int) -> bool:
    """True when either fused tier serves (b, h) — the dispatch kill
    point tests monkeypatch to force the scan reference path."""
    return fused_tier(b, h) is not None


def _sig(x):
    return jax.nn.sigmoid(x)


# --------------------------------------------------------------- forward
def _fwd_kernel(xw_ref, m_ref, wg_ref, wc_ref, h0_ref, hseq_ref,
                gates_ref, h_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_s[:] = h0_ref[...].astype(jnp.float32)

    h_prev = h_s[:]                                     # [B, H] f32
    hd = h_prev.shape[-1]
    xw = xw_ref[0].astype(jnp.float32)                  # [B, 3H]
    xu = xw[:, :hd]
    xr = xw[:, hd:2 * hd]
    xc = xw[:, 2 * hd:]
    g = h_prev @ wg_ref[...].astype(jnp.float32)        # [B, 2H]
    u = _sig(xu + g[:, :hd])
    r = _sig(xr + g[:, hd:])
    c = jnp.tanh(xc + (r * h_prev) @ wc_ref[...].astype(jnp.float32))
    h_new = u * h_prev + (1.0 - u) * c

    m = m_ref[0, 0].astype(jnp.float32)[:, None]        # [B, 1]
    h_keep = m * h_new + (1.0 - m) * h_prev
    h_s[:] = h_keep
    hseq_ref[0] = h_keep.astype(hseq_ref.dtype)
    gates_ref[0] = jnp.concatenate([u, r, c],
                                   axis=-1).astype(gates_ref.dtype)


def _fwd_call(xw, mask, w_gates, w_cand, h0):
    t, b, hd3 = xw.shape
    hd = hd3 // 3
    return pl.pallas_call(
        _fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd3), lambda i: (i, 0, 0)),   # xw
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0)),     # mask
            pl.BlockSpec((hd, 2 * hd), lambda i: (0, 0)),     # w_gates
            pl.BlockSpec((hd, hd), lambda i: (0, 0)),         # w_cand
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # h0
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd), lambda i: (i, 0, 0)),    # H
            pl.BlockSpec((1, b, hd3), lambda i: (i, 0, 0)),   # gates
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd3), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, hd), jnp.float32)],    # h carry
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(xw, mask, w_gates, w_cand, h0)


# -------------------------------------------------------------- backward
def _bwd_kernel(gates_ref, hprev_ref, m_ref, wg_ref, wc_ref, dy_ref,
                dxw_ref, dwg_ref, dwc_ref, dh0_ref, dh_s, *, t_total):
    """Grid step i visits t = T-1-i.  dy is the external cotangent on
    the kept H_t; it joins the carry BEFORE the masked split so the
    (1−m) passthrough mirrors the forward keep."""
    i_rev = pl.program_id(0)

    @pl.when(i_rev == 0)
    def _init():
        dh_s[:] = jnp.zeros_like(dh_s)
        dwg_ref[...] = jnp.zeros_like(dwg_ref)
        dwc_ref[...] = jnp.zeros_like(dwc_ref)

    hd = dh_s.shape[-1]
    gates = gates_ref[0].astype(jnp.float32)
    u = gates[:, :hd]
    r = gates[:, hd:2 * hd]
    c = gates[:, 2 * hd:]
    h_prev = hprev_ref[0].astype(jnp.float32)
    m = m_ref[0, 0].astype(jnp.float32)[:, None]

    dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:]
    dh_new = m * dh_tot                                 # raw-h' share
    du_pre = dh_new * (h_prev - c) * u * (1.0 - u)
    dc_pre = dh_new * (1.0 - u) * (1.0 - c * c)
    drh = dc_pre @ wc_ref[...].astype(jnp.float32).T    # d(r·h_prev)
    dr_pre = drh * h_prev * r * (1.0 - r)
    dg = jnp.concatenate([du_pre, dr_pre], axis=-1)     # [B, 2H]

    dh_prev = (dh_new * u + drh * r
               + dg @ wg_ref[...].astype(jnp.float32).T)
    dh_s[:] = (1.0 - m) * dh_tot + dh_prev
    dwg_ref[...] = dwg_ref[...] + h_prev.T @ dg
    dwc_ref[...] = dwc_ref[...] + (r * h_prev).T @ dc_pre
    dxw_ref[0] = jnp.concatenate([du_pre, dr_pre, dc_pre],
                                 axis=-1).astype(dxw_ref.dtype)

    @pl.when(i_rev == t_total - 1)
    def _flush():
        dh0_ref[...] = dh_s[:].astype(dh0_ref.dtype)


def _bwd_call(gates, h_prev_seq, mask, w_gates, w_cand, dy):
    t, b, hd3 = gates.shape
    hd = hd3 // 3
    rev3 = lambda i: (t - 1 - i, 0, 0)
    kernel = functools.partial(_bwd_kernel, t_total=t)
    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd3), rev3),                  # gates
            pl.BlockSpec((1, b, hd), rev3),                   # H_{t-1}
            pl.BlockSpec((1, 1, b), lambda i: (t - 1 - i, 0, 0)),  # mask
            pl.BlockSpec((hd, 2 * hd), lambda i: (0, 0)),     # w_gates
            pl.BlockSpec((hd, hd), lambda i: (0, 0)),         # w_cand
            pl.BlockSpec((1, b, hd), rev3),                   # dy (dH)
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd3), rev3),                  # dxw
            pl.BlockSpec((hd, 2 * hd), lambda i: (0, 0)),     # dw_gates
            pl.BlockSpec((hd, hd), lambda i: (0, 0)),         # dw_cand
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # dh0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd3), jnp.float32),
            jax.ShapeDtypeStruct((hd, 2 * hd), jnp.float32),
            jax.ShapeDtypeStruct((hd, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, hd), jnp.float32)],    # dh carry
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(gates, h_prev_seq, mask, w_gates, w_cand, dy)


# ------------------------------------------------------------ custom vjp
@jax.custom_vjp
def _gru_core(xw, mask, w_gates, w_cand, h0):
    """xw [T, B, 3H] (input projection + bias applied), mask [T, 1, B],
    w_gates [H, 2H], w_cand [H, H], h0 [B, H].  Returns the kept state
    sequence H [T, B, Hd] in f32."""
    h_seq, _gates = _fwd_call(xw, mask, w_gates, w_cand, h0)
    return h_seq


def _gru_core_fwd(xw, mask, w_gates, w_cand, h0):
    h_seq, gates = _fwd_call(xw, mask, w_gates, w_cand, h0)
    return h_seq, (gates, h_seq, mask, w_gates, w_cand, h0)


def _gru_core_bwd(res, dh_seq):
    gates, h_seq, mask, w_gates, w_cand, h0 = res
    h_prev_seq = jnp.concatenate([h0[None].astype(h_seq.dtype),
                                  h_seq[:-1]], axis=0)
    dxw, dwg, dwc, dh0 = _bwd_call(gates, h_prev_seq, mask, w_gates,
                                   w_cand, dh_seq)
    return (dxw.astype(mask.dtype), jnp.zeros_like(mask), dwg, dwc, dh0)


_gru_core.defvjp(_gru_core_fwd, _gru_core_bwd)


def gru_fused_sequence(xw, mask, w_gates, w_cand, h0):
    """Batch-major wrapper: xw [B, T, 3H] pre-projected (+bias), mask
    [B, T]; returns (y [B, T, H] masked hidden outputs, final_h [B, H])
    in f32 — callers cast per their dtype policy."""
    b, t, hd3 = xw.shape
    hd = hd3 // 3
    h0 = jnp.zeros((b, hd), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    h_seq = _gru_core(
        jnp.moveaxis(xw, 1, 0),
        jnp.moveaxis(mask, 1, 0).astype(xw.dtype)[:, None, :],
        w_gates.astype(jnp.float32), w_cand.astype(jnp.float32), h0)
    y = jnp.moveaxis(h_seq, 0, 1) * mask.astype(jnp.float32)[:, :, None]
    return y, h_seq[-1]


# =================================================================
# Hidden-blocked tier (512 < H) — see pallas_lstm.py for the general
# scheme.  The GRU adds a wrinkle the LSTM doesn't have: the candidate
# projection (r·h_prev) @ w_cand needs the FULL reset gate r before any
# candidate block can run, so one time step is TWO phases over the
# inner grid dim: grid (T, 2·nb), steps p < nb compute gate blocks
# (u_j, r_j) and stage r·h_prev, steps p ≥ nb stream w_cand column
# blocks and finish candidate/update math.  The min/max index-map
# pinning keeps each weight's stream at exactly its own bytes per step
# (w_gates holds its last block through phase 2, w_cand holds block 0
# through phase 1 — an unchanged block index fetches nothing).
# =================================================================
def _fwd_kernel_blocked(xur_ref, xc_ref, m_ref, wg_ref, wc_ref, h0_ref,
                        hseq_ref, urseq_ref, cseq_ref,
                        h_s, u_s, rh_s, hn_s, *, nb, hb):
    """xur/wg/urseq are in block-gate layout (block j = [u_j|r_j]);
    xc/wc/hseq/cseq are natural (w_cand column blocks are already
    contiguous)."""
    t = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when((t == 0) & (p == 0))
    def _init():
        h_s[:] = h0_ref[...].astype(jnp.float32)

    @pl.when(p < nb)
    def _phase_gates():
        col = p * hb
        h_prev = h_s[:]                                 # [B, H] f32
        h_prev_blk = h_s[:, pl.ds(col, hb)]
        xur = xur_ref[0].astype(jnp.float32)            # [B, 2Hb]
        g = h_prev @ wg_ref[...].astype(jnp.float32)    # [B, 2Hb]
        u = _sig(xur[:, :hb] + g[:, :hb])
        r = _sig(xur[:, hb:] + g[:, hb:])
        u_s[:, pl.ds(col, hb)] = u
        rh_s[:, pl.ds(col, hb)] = r * h_prev_blk
        urseq_ref[0] = jnp.concatenate([u, r],
                                       axis=-1).astype(urseq_ref.dtype)

    @pl.when(p >= nb)
    def _phase_cand():
        col = (p - nb) * hb
        h_prev_blk = h_s[:, pl.ds(col, hb)]
        u = u_s[:, pl.ds(col, hb)]
        xc = xc_ref[0].astype(jnp.float32)              # [B, Hb]
        c = jnp.tanh(xc + rh_s[:] @ wc_ref[...].astype(jnp.float32))
        h_new = u * h_prev_blk + (1.0 - u) * c
        m = m_ref[0, 0].astype(jnp.float32)[:, None]
        h_keep = m * h_new + (1.0 - m) * h_prev_blk
        hn_s[:, pl.ds(col, hb)] = h_keep
        hseq_ref[0] = h_keep.astype(hseq_ref.dtype)
        cseq_ref[0] = c.astype(cseq_ref.dtype)

    @pl.when(p == 2 * nb - 1)
    def _commit():
        h_s[:] = hn_s[:]


def _fwd_call_blocked(xur, xc, mask, w_gates, w_cand, h0, hb=HBLOCK):
    t, b, hd = xc.shape
    nb = hd // hb
    kernel = functools.partial(_fwd_kernel_blocked, nb=nb, hb=hb)
    ph1 = lambda i, p: (i, 0, jnp.minimum(p, nb - 1))       # gate phase
    ph2 = lambda i, p: (i, 0, jnp.maximum(p - nb, 0))       # cand phase
    return pl.pallas_call(
        kernel,
        grid=(t, 2 * nb),
        in_specs=[
            pl.BlockSpec((1, b, 2 * hb), ph1),              # xur blk
            pl.BlockSpec((1, b, hb), ph2),                  # xc blk
            pl.BlockSpec((1, 1, b), lambda i, p: (i, 0, 0)),  # mask
            pl.BlockSpec((hd, 2 * hb),
                         lambda i, p: (0, jnp.minimum(p, nb - 1))),
            pl.BlockSpec((hd, hb),
                         lambda i, p: (0, jnp.maximum(p - nb, 0))),
            pl.BlockSpec((b, hd), lambda i, p: (0, 0)),     # h0
        ],
        out_specs=[
            pl.BlockSpec((1, b, hb), ph2),                  # H
            pl.BlockSpec((1, b, 2 * hb), ph1),              # u|r gates
            pl.BlockSpec((1, b, hb), ph2),                  # candidate
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, 2 * hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),               # h carry
            pltpu.VMEM((b, hd), jnp.float32),               # u staging
            pltpu.VMEM((b, hd), jnp.float32),               # r·h staging
            pltpu.VMEM((b, hd), jnp.float32),               # h staging
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(xur, xc, mask, w_gates, w_cand, h0)


def _bwd_kernel_blocked(ur_ref, c_ref, hprev_ref, m_ref, wg_ref, wc_ref,
                        dy_ref, dxur_ref, dxc_ref, dh0_ref,
                        dh_s, du_s, drh_s, dacc_s, *, t_total, nb, hb):
    """Reversed-time BPTT with the forward's two phases mirrored:
    phase A (p < nb) forms du_pre/dc_pre per block and accumulates the
    full-width d(r·h_prev) = Σ_j dc_pre_j @ w_cand_jᵀ; phase B needs
    that complete sum to split dr_pre per block, then accumulates the
    gate pullback into the next dh carry.  dW_gates/dW_cand run as the
    separate constant-block kernel over the residues written here."""
    i_rev = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when((i_rev == 0) & (p == 0))
    def _init():
        dh_s[:] = jnp.zeros_like(dh_s)

    @pl.when(p == 0)
    def _zero_acc():
        drh_s[:] = jnp.zeros_like(drh_s)
        dacc_s[:] = jnp.zeros_like(dacc_s)

    m = m_ref[0, 0].astype(jnp.float32)[:, None]

    @pl.when(p < nb)
    def _phase_a():
        col = p * hb
        ur = ur_ref[0].astype(jnp.float32)              # [B, 2Hb]
        u = ur[:, :hb]
        c = c_ref[0].astype(jnp.float32)                # [B, Hb]
        h_prev_blk = hprev_ref[0].astype(jnp.float32)
        dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:, pl.ds(col, hb)]
        dh_new = m * dh_tot                             # raw-h' share
        du_pre = dh_new * (h_prev_blk - c) * u * (1.0 - u)
        dc_pre = dh_new * (1.0 - u) * (1.0 - c * c)
        du_s[:, pl.ds(col, hb)] = du_pre
        drh_s[:] = drh_s[:] \
            + dc_pre @ wc_ref[...].astype(jnp.float32).T
        dxc_ref[0] = dc_pre.astype(dxc_ref.dtype)

    @pl.when(p >= nb)
    def _phase_b():
        col = (p - nb) * hb
        ur = ur_ref[0].astype(jnp.float32)
        u = ur[:, :hb]
        r = ur[:, hb:]
        h_prev_blk = hprev_ref[0].astype(jnp.float32)
        dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:, pl.ds(col, hb)]
        dh_new = m * dh_tot
        drh = drh_s[:, pl.ds(col, hb)]                  # complete sum
        dr_pre = drh * h_prev_blk * r * (1.0 - r)
        du_pre = du_s[:, pl.ds(col, hb)]
        dg = jnp.concatenate([du_pre, dr_pre], axis=-1)  # [B, 2Hb]
        dacc_s[:] = dacc_s[:] + dg @ wg_ref[...].astype(jnp.float32).T
        dacc_s[:, pl.ds(col, hb)] = dacc_s[:, pl.ds(col, hb)] \
            + (1.0 - m) * dh_tot + dh_new * u + drh * r
        dxur_ref[0] = dg.astype(dxur_ref.dtype)

    @pl.when(p == 2 * nb - 1)
    def _commit():
        dh_s[:] = dacc_s[:]

    @pl.when((i_rev == t_total - 1) & (p == 2 * nb - 1))
    def _flush():
        dh0_ref[...] = dacc_s[:].astype(dh0_ref.dtype)


def _bwd_call_blocked(ur_seq, c_seq, h_prev_seq, mask, w_gates, w_cand,
                      dy, hb=HBLOCK):
    t, b, hd = c_seq.shape
    nb = hd // hb
    kernel = functools.partial(_bwd_kernel_blocked, t_total=t, nb=nb,
                               hb=hb)
    rev = lambda i: t - 1 - i
    # both phases address hidden block p mod nb (phase A: p, phase B:
    # p−nb — same residue)
    both = lambda i, p: (rev(i), 0, p % nb)
    ph_a = lambda i, p: (rev(i), 0, jnp.minimum(p, nb - 1))
    ph_b = lambda i, p: (rev(i), 0, jnp.maximum(p - nb, 0))
    return pl.pallas_call(
        kernel,
        grid=(t, 2 * nb),
        in_specs=[
            pl.BlockSpec((1, b, 2 * hb), both),             # u|r gates
            pl.BlockSpec((1, b, hb), ph_a),                 # candidate
            pl.BlockSpec((1, b, hb), both),                 # H_{t-1}
            pl.BlockSpec((1, 1, b), lambda i, p: (rev(i), 0, 0)),
            pl.BlockSpec((hd, 2 * hb),
                         lambda i, p: (0, jnp.maximum(p - nb, 0))),
            pl.BlockSpec((hd, hb),
                         lambda i, p: (0, jnp.minimum(p, nb - 1))),
            pl.BlockSpec((1, b, hb), both),                 # dy
        ],
        out_specs=[
            pl.BlockSpec((1, b, 2 * hb), ph_b),             # dxur
            pl.BlockSpec((1, b, hb), ph_a),                 # dxc
            pl.BlockSpec((b, hd), lambda i, p: (0, 0)),     # dh0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, 2 * hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),               # dh carry
            pltpu.VMEM((b, hd), jnp.float32),               # du staging
            pltpu.VMEM((b, hd), jnp.float32),               # drh accum
            pltpu.VMEM((b, hd), jnp.float32),               # dh accum
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(ur_seq, c_seq, h_prev_seq, mask, w_gates, w_cand, dy)


def _dw_kernel_blocked(hprev_ref, rh_ref, dg_ref, dcp_ref,
                       dwg_ref, dwc_ref):
    """Grid (nb, T), time innermost: both weight-gradient blocks stay
    resident in their output refs across the T loop (round-7 constant-
    block pattern), so at most [H, 3Hb] of weight gradient is ever in
    VMEM."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        dwg_ref[...] = jnp.zeros_like(dwg_ref)
        dwc_ref[...] = jnp.zeros_like(dwc_ref)

    h_prev = hprev_ref[0].astype(jnp.float32)           # [B, H]
    rh = rh_ref[0].astype(jnp.float32)                  # [B, H]
    dg = dg_ref[0].astype(jnp.float32)                  # [B, 2Hb]
    dcp = dcp_ref[0].astype(jnp.float32)                # [B, Hb]
    dwg_ref[...] = dwg_ref[...] + h_prev.T @ dg
    dwc_ref[...] = dwc_ref[...] + rh.T @ dcp


def _dw_call_blocked(h_prev_seq, rh_seq, dg_seq, dcp_seq, hb=HBLOCK):
    t, b, hd = h_prev_seq.shape
    nb = hd // hb
    return pl.pallas_call(
        _dw_kernel_blocked,
        grid=(nb, t),
        in_specs=[
            pl.BlockSpec((1, b, hd), lambda j, i: (i, 0, 0)),  # H_{t-1}
            pl.BlockSpec((1, b, hd), lambda j, i: (i, 0, 0)),  # r·h
            pl.BlockSpec((1, b, 2 * hb), lambda j, i: (i, 0, j)),  # dg
            pl.BlockSpec((1, b, hb), lambda j, i: (i, 0, j)),  # dc_pre
        ],
        out_specs=[
            pl.BlockSpec((hd, 2 * hb), lambda j, i: (0, j)),   # dw_gates
            pl.BlockSpec((hd, hb), lambda j, i: (0, j)),       # dw_cand
        ],
        out_shape=[
            jax.ShapeDtypeStruct((hd, 2 * hd), jnp.float32),
            jax.ShapeDtypeStruct((hd, hd), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(h_prev_seq, rh_seq, dg_seq, dcp_seq)


@jax.custom_vjp
def _gru_core_blocked(xur, xc, mask, w_gates, w_cand, h0):
    """Blocked-tier core: xur [T, B, 2H] and w_gates [H, 2H] arrive in
    block-gate layout, xc [T, B, H] / w_cand [H, H] natural (the
    wrapper splits and permutes; autodiff transposes the permutes
    around this boundary).  Returns the kept state sequence H
    [T, B, Hd] in f32."""
    h_seq, _ur, _c = _fwd_call_blocked(xur, xc, mask, w_gates, w_cand,
                                       h0)
    return h_seq


def _gru_core_blocked_fwd(xur, xc, mask, w_gates, w_cand, h0):
    h_seq, ur, c = _fwd_call_blocked(xur, xc, mask, w_gates, w_cand, h0)
    return h_seq, (ur, c, h_seq, mask, w_gates, w_cand, h0)


def _gru_core_blocked_bwd(res, dh_seq):
    ur, c, h_seq, mask, w_gates, w_cand, h0 = res
    hd = h_seq.shape[-1]
    h_prev_seq = jnp.concatenate([h0[None].astype(h_seq.dtype),
                                  h_seq[:-1]], axis=0)
    dxur, dxc, dh0 = _bwd_call_blocked(
        ur, c, h_prev_seq, mask, w_gates, w_cand, dh_seq)
    # r·h_prev for the w_cand gradient, recovered from the gate residue
    # (one XLA pass; the dW kernel streams it full-width per step)
    r_seq = _from_gate_blocks(ur, hd, 2)[..., hd:]
    dwg, dwc = _dw_call_blocked(h_prev_seq, r_seq * h_prev_seq,
                                dxur, dxc)
    return (dxur.astype(mask.dtype), dxc.astype(mask.dtype),
            jnp.zeros_like(mask), dwg, dwc, dh0)


_gru_core_blocked.defvjp(_gru_core_blocked_fwd, _gru_core_blocked_bwd)


def gru_fused_sequence_blocked(xw, mask, w_gates, w_cand, h0):
    """Blocked-tier entry — same batch-major contract as
    :func:`gru_fused_sequence`, dispatched by
    ``fused_tier(b, h) == "fused_blocked"``."""
    b, t, hd3 = xw.shape
    hd = hd3 // 3
    h0 = jnp.zeros((b, hd), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    xw_t = jnp.moveaxis(xw, 1, 0)
    xur_blk = _to_gate_blocks(xw_t[..., :2 * hd], hd, 2)
    xc = xw_t[..., 2 * hd:]
    wg_blk = _to_gate_blocks(w_gates.astype(jnp.float32), hd, 2)
    h_seq = _gru_core_blocked(
        xur_blk, xc,
        jnp.moveaxis(mask, 1, 0).astype(xw.dtype)[:, None, :],
        wg_blk, w_cand.astype(jnp.float32), h0)
    y = jnp.moveaxis(h_seq, 0, 1) * mask.astype(jnp.float32)[:, :, None]
    return y, h_seq[-1]

"""Fused conv backward-data + BatchNorm-affine as a Pallas TPU kernel.

The ResNet-class train step is HBM-bound, not MXU-bound (PERF_NOTES:
27 GB/step, bandwidth util ~0.70 while flops util sits at 0.29).  The
largest removable slice of that traffic is the seam between the
BatchNorm backward and the conv backward that consumes its result: XLA
cannot fuse an elementwise producer into a convolution operand (convs
read their inputs from HBM), so the BN backward's apply pass

    dz = scale·inv · (dy − Σdy/N − x̂ · Σ(dy·x̂)/N)

materializes ``dz`` in HBM only for the conv backward-data and
backward-filter kernels to immediately re-read it.  The reference hit
the same wall on GPUs and solved it with fused cuDNN conv/BN entry
points (``hl_cuda_cudnn.cc`` / ``CudnnBatchNormLayer.cpp``); the TPU
analogue of that tier is this module.

Key identity: with A = scale·inv, B = −A·inv·Σ(dy·x̂)/N and
C = A·(inv·m·Σ(dy·x̂) − Σdy)/N (all per-channel scalars computed by one
reduction pass), the BN backward is the **per-channel affine**

    dz = A·dy + B·z + C

of two tensors already resident in HBM (the upstream cotangent dy and
the conv output z, which is saved for the BN backward anyway).  The
Pallas backward-data kernel below streams (dy, z) tiles through VMEM,
forms dz on-chip, and immediately runs the 3×3 backward-data matmuls on
it — writing dx *and* dz in the same pass so the filter-grad conv that
still runs under XLA reads a ready-made dz.  Per fused conv→BN pair
this removes one full read+write of an activation-sized tensor from the
step (the apply pass's dz store and the backward-data conv's dz load),
which is exactly the traffic class PERF_NOTES identified as the
roofline.

Kernel shape: grid = (N,) with one image per step ("arbitrary"
semantics, pallas double-buffers the streaming blocks).  The 3×3
stride-1 backward-data conv is decomposed into 9 shifted [H·W, Cout] @
[Cout, Cin] MXU matmuls over a zero-padded VMEM scratch tile — no halo
exchange, no [T, T]-style intermediate, one HBM read of dy and z and
one write of dx and dz.  The spatially-flipped, I/O-transposed weight
``wT[a, b] = w[2−a, 2−b].T`` stays resident in VMEM (≤ 9.4 MB f32 at
C=512, inside the 16 MB budget with the stage-4 7×7 tiles).

Shapes that don't tile (channels not a multiple of 64, VMEM overflow)
dispatch to the plain ``conv2d`` + ``batch_norm`` composition in
:mod:`paddle_tpu.ops.nn_ops` — same contract, same results.  On
non-TPU backends the kernel runs in Pallas interpret mode so CPU tests
exercise the exact dispatch used on hardware.
"""

from __future__ import annotations

from functools import partial as _partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import CompilerParams, _interpret  # shared gate

# VMEM budget for the gate: tiles + resident weights must fit under the
# 16 MB scoped-vmem cap with headroom for double-buffering.
_VMEM_BUDGET = 12 * 1024 * 1024


def fused_ok(h: int, w: int, cin: int, cout: int) -> bool:
    """Mosaic tiling gate, checked on every backend so interpret-mode
    tests exercise the hardware dispatch.  Channels must land on the
    128-lane minor dimension in at most two tiles (multiples of 64 —
    covers ResNet-50's 3×3 family: 64/128/256/512); the per-image tile
    set (dy, z, dz f32, padded-dz scratch, dx accumulator) plus the
    resident flipped weight must fit the VMEM budget."""
    if cin % 64 or cout % 64 or h < 1 or w < 1:
        return False
    f32 = 4
    tile = h * w * (4 * cout + cin) * f32 \
        + (h + 2) * (w + 2) * cout * f32
    return tile + 9 * cout * cin * f32 <= _VMEM_BUDGET


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def fusable(x_shape, w_shape, stride, padding, dilation, groups,
            data_format) -> bool:
    """Full static dispatch gate for the fused conv→BN path: the 3×3
    stride-1 SAME/pad-1 grouped-less NHWC family whose shapes tile."""
    if data_format != "NHWC" or groups != 1:
        return False
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    if tuple(w_shape[:2]) != (3, 3):
        return False
    if _pair(stride) != (1, 1) or _pair(dilation) != (1, 1):
        return False
    if isinstance(padding, str):
        if padding != "SAME":
            return False
    else:
        pads = [_pair(p) for p in padding] if not isinstance(padding, int) \
            else [(padding, padding)] * 2
        if pads != [(1, 1), (1, 1)]:
            return False
    n, h, w_, _cin = x_shape
    return fused_ok(h, w_, int(w_shape[2]), int(w_shape[3]))


def _conv3x3(x, w):
    """The forward this module's backward belongs to: 3×3 stride-1
    pad-1 NHWC/HWIO conv, stated exactly as ``nn_ops.conv2d`` lowers it
    so the fused op's forward is bit-identical to the unfused path."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=[(1, 1), (1, 1)],
        dimension_numbers=dn)


# ------------------------------------------------------------- dX kernel
def _dx_kernel(g_ref, z_ref, co_ref, wt_ref, dx_ref, dz_ref, pad_s, *,
               hh, ww):
    """One image per grid step: form dz = A·dy + B·z + C in VMEM, write
    it out for the filter-grad conv, then accumulate the 9 shifted
    matmuls of the 3×3 backward-data conv from the zero-padded scratch.
    All compute in f32 (the affine coefficients mix magnitudes; the MXU
    accumulates f32 natively)."""
    g = g_ref[0].astype(jnp.float32)                 # [H, W, Cout]
    z = z_ref[0].astype(jnp.float32)
    co = co_ref[...].astype(jnp.float32)             # [8, Cout]
    dz = co[0] * g + co[1] * z + co[2]               # per-channel affine
    dz_ref[0] = dz.astype(dz_ref.dtype)

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _zero_borders():
        # interior is overwritten every step; borders must read as the
        # implicit SAME zero-padding and only need zeroing once
        pad_s[...] = jnp.zeros_like(pad_s)

    pad_s[1:hh + 1, 1:ww + 1, :] = dz
    wt = wt_ref[...].astype(jnp.float32)             # [3, 3, Cout, Cin]
    cin = wt.shape[-1]
    acc = jnp.zeros((hh * ww, cin), jnp.float32)
    for a in range(3):
        for b in range(3):
            sl = pad_s[a:a + hh, b:b + ww, :].reshape(hh * ww, -1)
            acc = acc + jax.lax.dot_general(
                sl, wt[a, b], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dx_ref[0] = acc.reshape(hh, ww, cin).astype(dx_ref.dtype)


def _dx_call(dy, z, coeffs, w, dx_dtype, dz_dtype):
    """dy, z: [N, H, W, Cout]; coeffs: [8, Cout] f32 (rows 0..2 =
    A/B/C, rest zero); w: [3, 3, Cin, Cout] forward HWIO weights.
    Returns (dx [N, H, W, Cin], dz [N, H, W, Cout])."""
    n, h, ww, cout = dy.shape
    cin = w.shape[2]
    # backward-data kernel: spatial flip + I/O transpose of the forward
    # weights (constant-folded outside the step loop by XLA)
    wt = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)   # [3, 3, Cout, Cin]
    kernel = _partial(_dx_kernel, hh=h, ww=ww)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dy
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # z
            pl.BlockSpec((8, cout), lambda i: (0, 0)),          # coeffs
            pl.BlockSpec((3, 3, cout, cin), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # dx
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dz
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, ww, cin), dx_dtype),
            jax.ShapeDtypeStruct((n, h, ww, cout), dz_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2, ww + 2, cout), jnp.float32),  # padded dz
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(dy, z, coeffs, wt)


# ------------------------------------------------------------ custom vjp
@_partial(jax.custom_vjp, nondiff_argnums=(5,))
def _conv_bn_core(x, w, cb, scale, bias, eps):
    """Training-mode conv(3×3, s1, p1) + per-batch BatchNorm, NHWC.
    x [N,H,W,Cin], w [3,3,Cin,Cout] HWIO, cb/scale/bias [Cout].
    Returns y only; the caller recomputes (m, v) for the running
    averages (XLA CSEs the conv and the reductions with the ones in
    here)."""
    (y, _res) = _core_fwd(x, w, cb, scale, bias, eps)
    return y


def _core_fwd(x, w, cb, scale, bias, eps):
    from .nn_ops import _bn_apply, _bn_stats

    z = _conv3x3(x, w) + cb.astype(x.dtype)
    m, v = _bn_stats(z, (0, 1, 2))
    inv = lax.rsqrt(v + eps)
    y = _bn_apply(z, scale, bias, m, inv, 3)
    return y, (x, w, z, cb, scale, m, inv)


def _core_bwd(eps, res, dy):
    """The fused backward.  One XLA reduction pass over (dy, z) yields
    Σdy and Σdy·x̂ (= dbias, dscale — the BN parameter grads); from
    those the per-channel affine coefficients of dz are scalars, and
    the Pallas kernel produces dx and dz in a single pass over HBM.
    The filter grad runs as XLA's standard backward-filter conv on the
    kernel's dz output; the conv-bias grad Σdz reduces to channel
    scalars analytically (A·Σdy + B·N·m + C·N — no tensor pass).
    Running-average buffers are stop-gradient side-channel state, as
    everywhere else in this codebase."""
    x, w, z, cb, scale, m, inv = res
    cout = z.shape[-1]
    shape = (1, 1, 1, cout)
    nelem = np.prod([z.shape[i] for i in (0, 1, 2)]).astype(np.float32)
    dy_f = dy.astype(jnp.float32)
    xhat = (z.astype(jnp.float32) - m.reshape(shape)) * inv.reshape(shape)
    dbias = jnp.sum(dy_f, axis=(0, 1, 2))
    dscale = jnp.sum(dy_f * xhat, axis=(0, 1, 2))

    a_c = scale.astype(jnp.float32) * inv
    b_c = -a_c * inv * dscale / nelem
    c_c = a_c * (inv * m * dscale - dbias) / nelem
    coeffs = jnp.zeros((8, cout), jnp.float32) \
        .at[0].set(a_c).at[1].set(b_c).at[2].set(c_c)

    dx, dz = _dx_call(dy, z, coeffs, w, x.dtype, z.dtype)
    # filter grad: XLA's native backward-filter conv over the dz the
    # kernel just wrote (jax.vjp emits the canonical transpose conv)
    _, conv_vjp = jax.vjp(lambda w_: _conv3x3(x, w_), w)
    dw, = conv_vjp(dz)
    dcb = a_c * dbias + b_c * (nelem * m) + c_c * nelem
    return (dx, dw.astype(w.dtype), dcb.astype(cb.dtype),
            dscale.astype(scale.dtype), dbias.astype(scale.dtype))


def _core_fwd_rule(x, w, cb, scale, bias, eps):
    y, res = _core_fwd(x, w, cb, scale, bias, eps)
    return y, res


_conv_bn_core.defvjp(_core_fwd_rule, _core_bwd)

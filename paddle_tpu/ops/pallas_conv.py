"""Fused conv + BatchNorm-affine Pallas TPU kernels (both directions).

The ResNet-class train step is HBM-bound, not MXU-bound (PERF_NOTES:
27 GB/step, bandwidth util ~0.70 while flops util sits at 0.29).  The
largest removable slice of that traffic is the seam between BatchNorm
and the convs on either side of it: XLA cannot fuse an elementwise
producer into a convolution operand (convs read their inputs from HBM).

**Backward half (round 6).**  The BN backward's apply pass

    dz = scale·inv · (dy − Σdy/N − x̂ · Σ(dy·x̂)/N)

materializes ``dz`` in HBM only for the conv backward-data and
backward-filter kernels to immediately re-read it.  The reference hit
the same wall on GPUs and solved it with fused cuDNN conv/BN entry
points (``hl_cuda_cudnn.cc`` / ``CudnnBatchNormLayer.cpp``); the TPU
analogue of that tier is this module.

Key identity: with A = scale·inv, B = −A·inv·Σ(dy·x̂)/N and
C = A·(inv·m·Σ(dy·x̂) − Σdy)/N (all per-channel scalars computed by one
reduction pass), the BN backward is the **per-channel affine**

    dz = A·dy + B·z + C

of two tensors already resident in HBM (the upstream cotangent dy and
the conv output z, which is saved for the BN backward anyway).  The
Pallas backward-data kernel below streams (dy, z) tiles through VMEM,
forms dz on-chip, and immediately runs the 3×3 backward-data matmuls on
it — writing dx *and* dz in the same pass so the filter-grad conv that
still runs under XLA reads a ready-made dz.  Per fused conv→BN pair
this removes one full read+write of an activation-sized tensor from the
step (the apply pass's dz store and the backward-data conv's dz load),
which is exactly the traffic class PERF_NOTES identified as the
roofline.

**Forward half (round 7).**  The forward pass pays the same seam tax in
the other direction: every BN normalize+scale+ReLU apply writes a full
activation tensor that the next conv immediately re-reads from HBM.
With A = scale·inv and C = bias − m·A (per-channel scalars from the
stats pass), the normalized activation is ``x = act(A·z + C)`` of the
raw conv output z already in HBM — so the forward conv kernel here
applies that affine (+ReLU) **in its input pipeline**, forming x
tile-by-tile in VMEM and never materializing it in HBM.  Its
``custom_vjp`` keeps the raw z as the residual and *recomputes* the
affine in the backward kernel (mask + x for the filter grad), and the
chain variant (``_chain_core``) composes the forward prologue with the
round-6 fused backward-data kernel so a BN→conv→BN sandwich runs both
affines through one backward kernel pass.

Kernel shape (all kernels): grid = (N,) with one image per step
("arbitrary" semantics, pallas double-buffers the streaming blocks).
The 3×3 stride-1 conv — forward or backward-data — is decomposed into
9 shifted [H·W, Cin] @ [Cin, Cout] (resp. [H·W, Cout] @ [Cout, Cin])
MXU matmuls over a zero-padded VMEM scratch tile — no halo exchange,
no [T, T]-style intermediate, one HBM read of each operand.  For the
backward-data direction the spatially-flipped, I/O-transposed weight
``wT[a, b] = w[2−a, 2−b].T`` stays resident in VMEM (≤ 9.4 MB f32 at
C=512, inside the 16 MB budget with the stage-4 7×7 tiles).

Shapes that don't tile (channels not a multiple of 64, VMEM overflow)
dispatch to the plain ``conv2d`` + ``batch_norm`` composition in
:mod:`paddle_tpu.ops.nn_ops` — same contract, same results.  On
non-TPU backends the kernel runs in Pallas interpret mode so CPU tests
exercise the exact dispatch used on hardware.
"""

from __future__ import annotations

from functools import partial as _partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import CompilerParams, _interpret  # shared gate

# VMEM budget for the gate: tiles + resident weights must fit under the
# 16 MB scoped-vmem cap with headroom for double-buffering.
_VMEM_BUDGET = 12 * 1024 * 1024


def fused_ok(h: int, w: int, cin: int, cout: int) -> bool:
    """Mosaic tiling gate, checked on every backend so interpret-mode
    tests exercise the hardware dispatch.  Channels must land on the
    128-lane minor dimension in at most two tiles (multiples of 64 —
    covers ResNet-50's 3×3 family: 64/128/256/512); the per-image tile
    set (dy, z, dz f32, padded-dz scratch, dx accumulator) plus the
    resident flipped weight must fit the VMEM budget."""
    if cin % 64 or cout % 64 or h < 1 or w < 1:
        return False
    f32 = 4
    tile = h * w * (4 * cout + cin) * f32 \
        + (h + 2) * (w + 2) * cout * f32
    return tile + 9 * cout * cin * f32 <= _VMEM_BUDGET


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _geom3x3_ok(x_shape, w_shape, stride, padding, dilation, groups,
                data_format) -> bool:
    """Static geometry gate shared by the backward (round-6) and
    forward fusion paths: the 3×3 stride-1 SAME/pad-1 groupless NHWC
    family."""
    if data_format != "NHWC" or groups != 1:
        return False
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    if tuple(w_shape[:2]) != (3, 3):
        return False
    if _pair(stride) != (1, 1) or _pair(dilation) != (1, 1):
        return False
    if isinstance(padding, str):
        if padding != "SAME":
            return False
    else:
        pads = [_pair(p) for p in padding] if not isinstance(padding, int) \
            else [(padding, padding)] * 2
        if pads != [(1, 1), (1, 1)]:
            return False
    return True


def fusable(x_shape, w_shape, stride, padding, dilation, groups,
            data_format) -> bool:
    """Full static dispatch gate for the fused conv→BN path: the 3×3
    stride-1 SAME/pad-1 grouped-less NHWC family whose shapes tile."""
    if not _geom3x3_ok(x_shape, w_shape, stride, padding, dilation,
                       groups, data_format):
        return False
    n, h, w_, _cin = x_shape
    return fused_ok(h, w_, int(w_shape[2]), int(w_shape[3]))


def fused_fwd_ok(h: int, w: int, cin: int, cout: int) -> bool:
    """Mosaic tiling gate for the FORWARD fused conv (affine+ReLU input
    pipeline) and its backward twin — same 64-multiple channel rule as
    :func:`fused_ok`; the VMEM estimate covers whichever of the two
    kernels' tile sets is larger (fwd: z + padded-x scratch + out acc;
    bwd: dy + padded-dy scratch + z/du/dz/x tiles + the dA/dC
    accumulator block) plus the resident weights."""
    if cin % 64 or cout % 64 or h < 1 or w < 1:
        return False
    f32 = 4
    fwd = h * w * (2 * cin + 2 * cout) * f32 \
        + (h + 2) * (w + 2) * cin * f32
    bwd = h * w * (4 * cin + 2 * cout) * f32 \
        + (h + 2) * (w + 2) * cout * f32 + 8 * cin * f32
    return max(fwd, bwd) + 9 * cin * cout * f32 <= _VMEM_BUDGET


def fusable_fwd(z_shape, w_shape, stride, padding, dilation, groups,
                data_format) -> bool:
    """Full static dispatch gate for the fused BN(+ReLU)→conv forward
    path (the 3×3 Pallas kernel; the 1×1 GEMM-prologue path has its own
    gate in :mod:`paddle_tpu.ops.nn_ops`)."""
    if not _geom3x3_ok(z_shape, w_shape, stride, padding, dilation,
                       groups, data_format):
        return False
    n, h, w_, _cin = z_shape
    return fused_fwd_ok(h, w_, int(w_shape[2]), int(w_shape[3]))


def fused_chain_ok(h: int, w: int, cin: int, cout: int) -> bool:
    """VMEM gate for the chain kernel (forward affine prologue × round-6
    BN-backward affine in ONE backward-data pass): its backward streams
    (dy, z2, z1) and writes (dz2, dz1, x1) with both affine blocks and
    the padded-dz2 scratch resident."""
    if not fused_fwd_ok(h, w, cin, cout):
        return False
    f32 = 4
    tile = h * w * (4 * cin + 3 * cout) * f32 \
        + (h + 2) * (w + 2) * cout * f32 + 8 * (cin + cout) * f32
    return tile + 9 * cin * cout * f32 <= _VMEM_BUDGET


def _conv3x3(x, w):
    """The forward this module's backward belongs to: 3×3 stride-1
    pad-1 NHWC/HWIO conv, stated exactly as ``nn_ops.conv2d`` lowers it
    so the fused op's forward is bit-identical to the unfused path."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=[(1, 1), (1, 1)],
        dimension_numbers=dn)


# ------------------------------------------------------------- dX kernel
def _dx_kernel(g_ref, z_ref, co_ref, wt_ref, dx_ref, dz_ref, pad_s, *,
               hh, ww):
    """One image per grid step: form dz = A·dy + B·z + C in VMEM, write
    it out for the filter-grad conv, then accumulate the 9 shifted
    matmuls of the 3×3 backward-data conv from the zero-padded scratch.
    All compute in f32 (the affine coefficients mix magnitudes; the MXU
    accumulates f32 natively)."""
    g = g_ref[0].astype(jnp.float32)                 # [H, W, Cout]
    z = z_ref[0].astype(jnp.float32)
    co = co_ref[...].astype(jnp.float32)             # [8, Cout]
    dz = co[0] * g + co[1] * z + co[2]               # per-channel affine
    dz_ref[0] = dz.astype(dz_ref.dtype)

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _zero_borders():
        # interior is overwritten every step; borders must read as the
        # implicit SAME zero-padding and only need zeroing once
        pad_s[...] = jnp.zeros_like(pad_s)

    pad_s[1:hh + 1, 1:ww + 1, :] = dz
    wt = wt_ref[...].astype(jnp.float32)             # [3, 3, Cout, Cin]
    cin = wt.shape[-1]
    acc = jnp.zeros((hh * ww, cin), jnp.float32)
    for a in range(3):
        for b in range(3):
            sl = pad_s[a:a + hh, b:b + ww, :].reshape(hh * ww, -1)
            acc = acc + jax.lax.dot_general(
                sl, wt[a, b], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dx_ref[0] = acc.reshape(hh, ww, cin).astype(dx_ref.dtype)


def _dx_call(dy, z, coeffs, w, dx_dtype, dz_dtype):
    """dy, z: [N, H, W, Cout]; coeffs: [8, Cout] f32 (rows 0..2 =
    A/B/C, rest zero); w: [3, 3, Cin, Cout] forward HWIO weights.
    Returns (dx [N, H, W, Cin], dz [N, H, W, Cout])."""
    n, h, ww, cout = dy.shape
    cin = w.shape[2]
    # backward-data kernel: spatial flip + I/O transpose of the forward
    # weights (constant-folded outside the step loop by XLA)
    wt = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)   # [3, 3, Cout, Cin]
    kernel = _partial(_dx_kernel, hh=h, ww=ww)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dy
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # z
            pl.BlockSpec((8, cout), lambda i: (0, 0)),          # coeffs
            pl.BlockSpec((3, 3, cout, cin), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # dx
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dz
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, ww, cin), dx_dtype),
            jax.ShapeDtypeStruct((n, h, ww, cout), dz_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2, ww + 2, cout), jnp.float32),  # padded dz
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(dy, z, coeffs, wt)


# ------------------------------------------------------------ custom vjp
@_partial(jax.custom_vjp, nondiff_argnums=(5,))
def _conv_bn_core(x, w, cb, scale, bias, eps):
    """Training-mode conv(3×3, s1, p1) + per-batch BatchNorm, NHWC.
    x [N,H,W,Cin], w [3,3,Cin,Cout] HWIO, cb/scale/bias [Cout].
    Returns y only; the caller recomputes (m, v) for the running
    averages (XLA CSEs the conv and the reductions with the ones in
    here)."""
    (y, _res) = _core_fwd(x, w, cb, scale, bias, eps)
    return y


def _core_fwd(x, w, cb, scale, bias, eps):
    from .nn_ops import _bn_apply, _bn_stats

    z = _conv3x3(x, w) + cb.astype(x.dtype)
    m, v = _bn_stats(z, (0, 1, 2))
    inv = lax.rsqrt(v + eps)
    y = _bn_apply(z, scale, bias, m, inv, 3)
    return y, (x, w, z, cb, scale, m, inv)


def _core_bwd(eps, res, dy):
    """The fused backward.  One XLA reduction pass over (dy, z) yields
    Σdy and Σdy·x̂ (= dbias, dscale — the BN parameter grads); from
    those the per-channel affine coefficients of dz are scalars, and
    the Pallas kernel produces dx and dz in a single pass over HBM.
    The filter grad runs as XLA's standard backward-filter conv on the
    kernel's dz output; the conv-bias grad Σdz reduces to channel
    scalars analytically (A·Σdy + B·N·m + C·N — no tensor pass).
    Running-average buffers are stop-gradient side-channel state, as
    everywhere else in this codebase."""
    x, w, z, cb, scale, m, inv = res
    cout = z.shape[-1]
    shape = (1, 1, 1, cout)
    nelem = np.prod([z.shape[i] for i in (0, 1, 2)]).astype(np.float32)
    dy_f = dy.astype(jnp.float32)
    xhat = (z.astype(jnp.float32) - m.reshape(shape)) * inv.reshape(shape)
    dbias = jnp.sum(dy_f, axis=(0, 1, 2))
    dscale = jnp.sum(dy_f * xhat, axis=(0, 1, 2))

    a_c = scale.astype(jnp.float32) * inv
    b_c = -a_c * inv * dscale / nelem
    c_c = a_c * (inv * m * dscale - dbias) / nelem
    coeffs = jnp.zeros((8, cout), jnp.float32) \
        .at[0].set(a_c).at[1].set(b_c).at[2].set(c_c)

    dx, dz = _dx_call(dy, z, coeffs, w, x.dtype, z.dtype)
    # filter grad: XLA's native backward-filter conv over the dz the
    # kernel just wrote (jax.vjp emits the canonical transpose conv)
    _, conv_vjp = jax.vjp(lambda w_: _conv3x3(x, w_), w)
    dw, = conv_vjp(dz)
    dcb = a_c * dbias + b_c * (nelem * m) + c_c * nelem
    return (dx, dw.astype(w.dtype), dcb.astype(cb.dtype),
            dscale.astype(scale.dtype), dbias.astype(scale.dtype))


def _core_fwd_rule(x, w, cb, scale, bias, eps):
    y, res = _core_fwd(x, w, cb, scale, bias, eps)
    return y, res


_conv_bn_core.defvjp(_core_fwd_rule, _core_bwd)


# ====================================================== forward fusion
def _pack_affine(a, c, n):
    """[8, n] f32 block (8 sublanes) carrying the per-channel affine:
    row 0 = scale A, row 1 = offset C, rest zero."""
    return jnp.zeros((8, n), jnp.float32) \
        .at[0].set(a.astype(jnp.float32)) \
        .at[1].set(c.astype(jnp.float32))


# ------------------------------------------------------ forward kernel
def _fwd_kernel(z_ref, ci_ref, w_ref, o_ref, pad_s, *, hh, ww, relu):
    """One image per grid step: form x = act(A·z + C) in VMEM from the
    upstream BN's folded per-channel affine, stage it into the
    zero-padded scratch, and run the 3×3 stride-1 forward conv as 9
    shifted [H·W, Cin] @ [Cin, Cout] MXU matmuls (weights resident) —
    the normalized activation never exists in HBM."""
    z = z_ref[0].astype(jnp.float32)                 # [H, W, Cin]
    ci = ci_ref[...].astype(jnp.float32)             # [8, Cin]
    x = ci[0] * z + ci[1]
    if relu:
        x = jnp.maximum(x, 0.0)

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _zero_borders():
        # interior is overwritten every step; borders must read as the
        # implicit SAME zero-padding and only need zeroing once
        pad_s[...] = jnp.zeros_like(pad_s)

    pad_s[1:hh + 1, 1:ww + 1, :] = x
    w = w_ref[...].astype(jnp.float32)               # [3, 3, Cin, Cout]
    cout = w.shape[-1]
    acc = jnp.zeros((hh * ww, cout), jnp.float32)
    for a in range(3):
        for b in range(3):
            sl = pad_s[a:a + hh, b:b + ww, :].reshape(hh * ww, -1)
            acc = acc + jax.lax.dot_general(
                sl, w[a, b], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[0] = acc.reshape(hh, ww, cout).astype(o_ref.dtype)


def _fwd_call(z, ci, w, out_dtype, relu):
    """z: [N, H, W, Cin]; ci: [8, Cin] f32 (rows A, C); w: [3, 3, Cin,
    Cout] HWIO forward weights.  Returns conv(act(A·z+C), w)."""
    n, h, ww, cin = z.shape
    cout = w.shape[3]
    kernel = _partial(_fwd_kernel, hh=h, ww=ww, relu=relu)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # z
            pl.BlockSpec((8, cin), lambda i: (0, 0)),            # affine
            pl.BlockSpec((3, 3, cin, cout), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, ww, cout), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((h + 2, ww + 2, cin), jnp.float32),   # padded x
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(z, ci, w)


# ----------------------------------------------------- forward backward
def _fwd_bwd_kernel(g_ref, z_ref, ci_ref, wt_ref, dz_ref, x_ref, dac_ref,
                    pad_s, *, hh, ww, relu):
    """Backward of the affine(+ReLU)→conv forward: the 3×3 backward-data
    matmuls over the zero-padded cotangent (flipped weights), then the
    prologue's backward applied on-chip — du = mask·t, dz = A·du — while
    x = act(A·z + C) is RECOMPUTED from the raw residual z and written
    once for the XLA filter-grad conv.  dA/dC accumulate across the
    sequential grid directly in their constant-block output ref (the
    pallas_lstm dW idiom)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        pad_s[...] = jnp.zeros_like(pad_s)
        dac_ref[...] = jnp.zeros_like(dac_ref)

    g = g_ref[0].astype(jnp.float32)                 # [H, W, Cout]
    pad_s[1:hh + 1, 1:ww + 1, :] = g
    wt = wt_ref[...].astype(jnp.float32)             # [3, 3, Cout, Cin]
    cin = wt.shape[-1]
    acc = jnp.zeros((hh * ww, cin), jnp.float32)
    for a in range(3):
        for b in range(3):
            sl = pad_s[a:a + hh, b:b + ww, :].reshape(hh * ww, -1)
            acc = acc + jax.lax.dot_general(
                sl, wt[a, b], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    t = acc.reshape(hh, ww, cin)                     # cotangent wrt x
    z = z_ref[0].astype(jnp.float32)
    ci = ci_ref[...].astype(jnp.float32)
    u = ci[0] * z + ci[1]
    if relu:
        du = jnp.where(u > 0, t, 0.0)
        x = jnp.maximum(u, 0.0)
    else:
        du, x = t, u
    dz_ref[0] = (ci[0] * du).astype(dz_ref.dtype)
    x_ref[0] = x.astype(x_ref.dtype)
    dac_ref[0] = dac_ref[0] + jnp.sum(z * du, axis=(0, 1))
    dac_ref[1] = dac_ref[1] + jnp.sum(du, axis=(0, 1))


def _fwd_bwd_call(dy, z, ci, w, relu):
    """dy: [N, H, W, Cout] conv-output cotangent; z: [N, H, W, Cin] raw
    BN input; ci: [8, Cin]; w: [3, 3, Cin, Cout] forward weights.
    Returns (dz, x, dac[8, Cin] with rows dA/dC)."""
    n, h, ww, cout = dy.shape
    cin = w.shape[2]
    wt = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)   # [3, 3, Cout, Cin]
    kernel = _partial(_fwd_bwd_kernel, hh=h, ww=ww, relu=relu)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dy
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # z
            pl.BlockSpec((8, cin), lambda i: (0, 0)),            # affine
            pl.BlockSpec((3, 3, cout, cin), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # dz
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # x
            pl.BlockSpec((8, cin), lambda i: (0, 0)),             # dA/dC
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, ww, cin), z.dtype),
            jax.ShapeDtypeStruct((n, h, ww, cin), z.dtype),
            jax.ShapeDtypeStruct((8, cin), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2, ww + 2, cout), jnp.float32),  # padded dy
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(dy, z, ci, wt)


# --------------------------------------------- standalone forward core
@_partial(jax.custom_vjp, nondiff_argnums=(4,))
def _affine_conv_core(z, a, c, w, relu):
    """y = conv3×3(act(a·z + c), w) with the affine applied in the VMEM
    input pipeline.  z [N,H,W,Cin]; a/c [Cin] f32 (the upstream BN's
    folded scale/offset); w [3,3,Cin,Cout] HWIO."""
    return _fwd_call(z, _pack_affine(a, c, z.shape[-1]), w, z.dtype, relu)


def _affine_core_fwd(z, a, c, w, relu):
    # residuals are the RAW z (+ the affine scalars): x is recomputed in
    # the backward kernel, never saved — saving it would re-spend the
    # HBM pass the fusion exists to remove
    y = _fwd_call(z, _pack_affine(a, c, z.shape[-1]), w, z.dtype, relu)
    return y, (z, a, c, w)


def _affine_core_bwd(relu, res, dy):
    z, a, c, w = res
    ci = _pack_affine(a, c, z.shape[-1])
    dz, x, dac = _fwd_bwd_call(dy, z, ci, w, relu)
    # filter grad: XLA's native backward-filter conv over the x the
    # kernel just recomputed (jax.vjp emits the canonical transpose)
    _, conv_vjp = jax.vjp(lambda w_: _conv3x3(x, w_), w)
    dw, = conv_vjp(dy.astype(x.dtype))
    return (dz, dac[0].astype(a.dtype), dac[1].astype(c.dtype),
            dw.astype(w.dtype))


_affine_conv_core.defvjp(_affine_core_fwd, _affine_core_bwd)


# ------------------------------------------------- chain backward kernel
def _chain_bwd_kernel(g_ref, z2_ref, co_ref, z1_ref, ci_ref, wt_ref,
                      dz2_ref, dz1_ref, x1_ref, dac_ref, pad_s, *,
                      hh, ww, relu):
    """BOTH affines in one backward-data pass (the composed fwd-fusion ×
    round-6 path): form dz2 = A₂·dy + B₂·z2 + C₂ on-chip (the BN2
    backward, exactly the round-6 input pipeline), run the 9 shifted
    backward-data matmuls on it, then apply the forward prologue's
    backward on the result — du = mask·t, dz1 = A₁·du — recomputing
    x1 = act(A₁·z1 + C₁) for the filter grad, with dA₁/dC₁ accumulating
    in their constant-block output ref."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        pad_s[...] = jnp.zeros_like(pad_s)
        dac_ref[...] = jnp.zeros_like(dac_ref)

    g = g_ref[0].astype(jnp.float32)                 # [H, W, Cout]
    z2 = z2_ref[0].astype(jnp.float32)
    co = co_ref[...].astype(jnp.float32)             # [8, Cout]
    dz2 = co[0] * g + co[1] * z2 + co[2]             # BN2 backward affine
    dz2_ref[0] = dz2.astype(dz2_ref.dtype)

    pad_s[1:hh + 1, 1:ww + 1, :] = dz2
    wt = wt_ref[...].astype(jnp.float32)             # [3, 3, Cout, Cin]
    cin = wt.shape[-1]
    acc = jnp.zeros((hh * ww, cin), jnp.float32)
    for a in range(3):
        for b in range(3):
            sl = pad_s[a:a + hh, b:b + ww, :].reshape(hh * ww, -1)
            acc = acc + jax.lax.dot_general(
                sl, wt[a, b], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    t = acc.reshape(hh, ww, cin)                     # cotangent wrt x1
    z1 = z1_ref[0].astype(jnp.float32)
    ci = ci_ref[...].astype(jnp.float32)             # [8, Cin]
    u = ci[0] * z1 + ci[1]
    if relu:
        du = jnp.where(u > 0, t, 0.0)
        x1 = jnp.maximum(u, 0.0)
    else:
        du, x1 = t, u
    dz1_ref[0] = (ci[0] * du).astype(dz1_ref.dtype)
    x1_ref[0] = x1.astype(x1_ref.dtype)
    dac_ref[0] = dac_ref[0] + jnp.sum(z1 * du, axis=(0, 1))
    dac_ref[1] = dac_ref[1] + jnp.sum(du, axis=(0, 1))


def _chain_bwd_call(dy, z2, co, z1, ci, w, relu):
    """Returns (dz2, dz1, x1, dac) — dz2 materialized for the XLA
    filter-grad conv, x1 recomputed for the same, dz1 for the upstream,
    dac rows = dA₁/dC₁."""
    n, h, ww, cout = dy.shape
    cin = w.shape[2]
    wt = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)
    kernel = _partial(_chain_bwd_kernel, hh=h, ww=ww, relu=relu)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dy
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # z2
            pl.BlockSpec((8, cout), lambda i: (0, 0)),             # BN2
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # z1
            pl.BlockSpec((8, cin), lambda i: (0, 0)),          # prologue
            pl.BlockSpec((3, 3, cout, cin), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, ww, cout), lambda i: (i, 0, 0, 0)),  # dz2
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # dz1
            pl.BlockSpec((1, h, ww, cin), lambda i: (i, 0, 0, 0)),   # x1
            pl.BlockSpec((8, cin), lambda i: (0, 0)),             # dA/dC
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, ww, cout), z2.dtype),
            jax.ShapeDtypeStruct((n, h, ww, cin), z1.dtype),
            jax.ShapeDtypeStruct((n, h, ww, cin), z1.dtype),
            jax.ShapeDtypeStruct((8, cin), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2, ww + 2, cout), jnp.float32),  # padded dz2
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(dy, z2, co, z1, ci, wt)


# ------------------------------------------------------------ chain core
@_partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _chain_core(z1, a1, c1, w, cb, scale, bias, eps, relu):
    """Training-mode act(a1·z1 + c1) → conv(3×3, s1, p1) + cb →
    per-batch BatchNorm, NHWC — the round-6 conv→BN pair with the
    upstream BN's affine(+ReLU) streamed through its input pipeline.
    Returns (y, m, v); the m/v cotangents are dropped in the backward
    (running-average side-channel state with stop-gradient semantics,
    as everywhere else in this codebase)."""
    out, _res = _chain_fwd(z1, a1, c1, w, cb, scale, bias, eps, relu)
    return out


def _chain_fwd(z1, a1, c1, w, cb, scale, bias, eps, relu):
    from .nn_ops import _bn_apply, _bn_stats

    z2 = _fwd_call(z1, _pack_affine(a1, c1, z1.shape[-1]), w, z1.dtype,
                   relu) + cb.astype(z1.dtype)
    m, v = _bn_stats(z2, (0, 1, 2))
    inv = lax.rsqrt(v + eps)
    y = _bn_apply(z2, scale, bias, m, inv, 3)
    return (y, m, v), (z1, a1, c1, w, cb, scale, m, inv, z2)


def _chain_core_fwd_rule(z1, a1, c1, w, cb, scale, bias, eps, relu):
    return _chain_fwd(z1, a1, c1, w, cb, scale, bias, eps, relu)


def _chain_core_bwd(eps, relu, res, cts):
    """One XLA reduction pass over (dy, z2) yields the BN2 parameter
    grads and the dz2 affine scalars (exactly round-6's `_core_bwd`);
    the chain kernel then produces dz2, dz1, x1 and the prologue's
    dA₁/dC₁ in a single pass over HBM.  The filter grad runs as XLA's
    backward-filter conv over (x1, dz2); the conv-bias grad Σdz2
    reduces analytically."""
    dy, _dm, _dv = cts
    z1, a1, c1, w, cb, scale, m, inv, z2 = res
    cout = z2.shape[-1]
    shape = (1, 1, 1, cout)
    nelem = np.prod([z2.shape[i] for i in (0, 1, 2)]).astype(np.float32)
    dy_f = dy.astype(jnp.float32)
    xhat = (z2.astype(jnp.float32) - m.reshape(shape)) * inv.reshape(shape)
    dbias = jnp.sum(dy_f, axis=(0, 1, 2))
    dscale = jnp.sum(dy_f * xhat, axis=(0, 1, 2))

    a_c = scale.astype(jnp.float32) * inv
    b_c = -a_c * inv * dscale / nelem
    c_c = a_c * (inv * m * dscale - dbias) / nelem
    co = jnp.zeros((8, cout), jnp.float32) \
        .at[0].set(a_c).at[1].set(b_c).at[2].set(c_c)

    ci = _pack_affine(a1, c1, z1.shape[-1])
    dz2, dz1, x1, dac = _chain_bwd_call(dy, z2, co, z1, ci, w, relu)
    _, conv_vjp = jax.vjp(lambda w_: _conv3x3(x1, w_), w)
    dw, = conv_vjp(dz2)
    dcb = a_c * dbias + b_c * (nelem * m) + c_c * nelem
    return (dz1, dac[0].astype(a1.dtype), dac[1].astype(c1.dtype),
            dw.astype(w.dtype), dcb.astype(cb.dtype),
            dscale.astype(scale.dtype), dbias.astype(scale.dtype))


_chain_core.defvjp(_chain_core_fwd_rule, _chain_core_bwd)

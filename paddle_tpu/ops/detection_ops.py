"""SSD detection ops: prior boxes, IoU matching, multibox loss, NMS output.

Reference semantics: ``paddle/gserver/layers/DetectionUtil.cpp``
(``jaccardOverlap``, ``encodeBBoxWithVar:112``, ``decodeBBoxWithVar:137``,
``matchBBox:234``, ``generateMatchIndices:329``, ``getDetectionIndices:466``,
``getDetectionOutput:528``) and ``PriorBox.cpp`` / ``MultiBoxLossLayer.cpp``.

TPU-first design: the reference runs all of this on the CPU with dynamic
per-image loops; here everything is fixed-shape jax — ground-truth boxes
arrive as a padded [B, G, 6] tensor with a validity count, matching is a
static-length ``fori_loop`` bipartite pass + vectorized per-prediction pass,
negative mining is a rank mask over sorted scores, and NMS keeps a fixed
``keep_top_k`` with invalid slots marked (image index -1).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op


# --------------------------------------------------------------- priors

def prior_boxes(layer_h: int, layer_w: int, img_h: int, img_w: int,
                min_sizes: Sequence[float],
                max_sizes: Sequence[float],
                aspect_ratios: Sequence[float],
                variances: Sequence[float]) -> np.ndarray:
    """[num_total_priors, 8] (4 clipped corners + 4 variances), row order
    identical to ``PriorBoxLayer::forward`` (cell-major, then per cell:
    min-size box, max-size box, non-unit aspect-ratio boxes)."""
    ratios = [1.0]
    for r in aspect_ratios:
        ratios += [r, 1.0 / r]
    step_w = img_w / layer_w
    step_h = img_h / layer_h
    rows: List[List[float]] = []

    def emit(cx, cy, bw, bh):
        rows.append([(cx - bw / 2.0) / img_w, (cy - bh / 2.0) / img_h,
                     (cx + bw / 2.0) / img_w, (cy + bh / 2.0) / img_h]
                    + list(variances))

    for h in range(layer_h):
        for w in range(layer_w):
            cx = (w + 0.5) * step_w
            cy = (h + 0.5) * step_h
            for mn in min_sizes:
                emit(cx, cy, mn, mn)
                # PriorBox.cpp:119 nests the FULL max-size loop inside
                # each min-size iteration (quirk kept for row-order and
                # weight compatibility): every (min, max) pair emits a
                # sqrt(min*max) box
                for mx in max_sizes:
                    s = math.sqrt(mn * mx)
                    emit(cx, cy, s, s)
            mn = min_sizes[-1]
            for r in ratios:
                if abs(r - 1.0) < 1e-6:
                    continue
                emit(cx, cy, mn * math.sqrt(r), mn / math.sqrt(r))
    out = np.asarray(rows, np.float32)
    out[:, :4] = np.clip(out[:, :4], 0.0, 1.0)
    return out


def num_priors_per_cell(min_sizes, max_sizes, aspect_ratios) -> int:
    return (len(min_sizes) * (1 + len(max_sizes))
            + 2 * len(aspect_ratios))


# ------------------------------------------------------------- geometry

def iou_matrix(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Jaccard overlap between all pairs: a [P,4], b [G,4] -> [P,G]."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)   # [P,1]
    bx1, by1, bx2, by2 = [v[None, :, 0] for v in jnp.split(b, 4, axis=-1)]
    ix1 = jnp.maximum(ax1, bx1)
    iy1 = jnp.maximum(ay1, by1)
    ix2 = jnp.minimum(ax2, bx2)
    iy2 = jnp.minimum(ay2, by2)
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_form(boxes):
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = (boxes[..., 0] + boxes[..., 2]) / 2.0
    cy = (boxes[..., 1] + boxes[..., 3]) / 2.0
    return cx, cy, w, h


@register_op("encode_bbox")
def encode_boxes(priors: jnp.ndarray, variances: jnp.ndarray,
                 gt: jnp.ndarray) -> jnp.ndarray:
    """``encodeBBoxWithVar``: [.,4] corner boxes -> variance-scaled offsets."""
    pcx, pcy, pw, ph = _center_form(priors)
    gcx, gcy, gw, gh = _center_form(gt)
    pw = jnp.maximum(pw, 1e-8)
    ph = jnp.maximum(ph, 1e-8)
    return jnp.stack([
        (gcx - pcx) / pw / variances[..., 0],
        (gcy - pcy) / ph / variances[..., 1],
        jnp.log(jnp.maximum(jnp.abs(gw / pw), 1e-8)) / variances[..., 2],
        jnp.log(jnp.maximum(jnp.abs(gh / ph), 1e-8)) / variances[..., 3],
    ], axis=-1)


@register_op("decode_bbox")
def decode_boxes(priors: jnp.ndarray, variances: jnp.ndarray,
                 loc: jnp.ndarray) -> jnp.ndarray:
    """``decodeBBoxWithVar``: offsets -> corner boxes."""
    pcx, pcy, pw, ph = _center_form(priors)
    cx = variances[..., 0] * loc[..., 0] * pw + pcx
    cy = variances[..., 1] * loc[..., 1] * ph + pcy
    w = jnp.exp(variances[..., 2] * loc[..., 2]) * pw
    h = jnp.exp(variances[..., 3] * loc[..., 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


# ------------------------------------------------------------- matching

def match_priors(prior_corners: jnp.ndarray, gt_boxes: jnp.ndarray,
                 gt_valid: jnp.ndarray, overlap_threshold: float
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``matchBBox``: bipartite pass (each GT claims its best prior) then
    per-prediction pass (priors with IoU >= threshold claim their best GT).

    prior_corners [P,4]; gt_boxes [G,4]; gt_valid [G] bool.
    Returns (match_idx [P] int32, -1 = unmatched; match_overlap [P]).
    """
    P = prior_corners.shape[0]
    G = gt_boxes.shape[0]
    ov = iou_matrix(prior_corners, gt_boxes)          # [P,G]
    ov = jnp.where(gt_valid[None, :], ov, 0.0)
    match_overlap = jnp.max(ov, axis=1)
    best_gt = jnp.argmax(ov, axis=1).astype(jnp.int32)

    def bipartite_step(_, carry):
        ovc, match = carry
        flat = jnp.argmax(ovc)
        p, g = flat // G, flat % G
        valid = ovc[p, g] > 1e-6
        match = jnp.where(valid, match.at[p].set(g.astype(jnp.int32)), match)
        # retire the claimed prior row and GT column
        ovc = jnp.where(valid, ovc.at[p, :].set(-1.0).at[:, g].set(-1.0), ovc)
        return ovc, match

    match = jnp.full((P,), -1, jnp.int32)
    _, match = jax.lax.fori_loop(0, G, bipartite_step, (ov, match))
    # per-prediction pass over the still-unmatched priors
    take = (match < 0) & (match_overlap >= overlap_threshold)
    match = jnp.where(take, best_gt, match)
    return match, match_overlap


@register_op("multibox_loss")
def multibox_loss(conf: jnp.ndarray, loc: jnp.ndarray, priors: jnp.ndarray,
                  gt: jnp.ndarray, gt_count: jnp.ndarray,
                  num_classes: int, overlap_threshold: float = 0.5,
                  neg_overlap: float = 0.5, neg_pos_ratio: float = 3.0,
                  background_id: int = 0) -> jnp.ndarray:
    """SSD loss (``MultiBoxLossLayer``): smooth-L1 on matched offsets +
    softmax CE on matched positives and hard-mined negatives, both
    normalized by the total match count across the batch.

    conf [B,P,C]; loc [B,P,4]; priors [P,8]; gt [B,G,6]
    (class,xmin,ymin,xmax,ymax,difficult) padded, gt_count [B].
    """
    B, P, C = conf.shape
    G = gt.shape[1]
    prior_corners = priors[:, :4]
    prior_vars = priors[:, 4:]
    gt_boxes = gt[..., 1:5]
    gt_class = gt[..., 0].astype(jnp.int32)
    gt_valid = jnp.arange(G)[None, :] < gt_count[:, None]       # [B,G]

    match, match_ov = jax.vmap(
        lambda g, v: match_priors(prior_corners, g, v, overlap_threshold)
    )(gt_boxes, gt_valid)                                        # [B,P]
    pos = match >= 0
    num_pos = jnp.sum(pos)

    # ---- location loss (smooth L1, only matched priors)
    safe_match = jnp.maximum(match, 0)
    gt_for_prior = jnp.take_along_axis(
        gt_boxes, safe_match[..., None], axis=1)                 # [B,P,4]
    target = encode_boxes(prior_corners[None], prior_vars[None], gt_for_prior)
    diff = jnp.abs(loc.astype(jnp.float32) - target)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    loc_loss = jnp.sum(jnp.where(pos[..., None], sl1, 0.0))

    # ---- confidence loss
    logits = conf.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    cls_for_prior = jnp.take_along_axis(gt_class, safe_match, axis=1)  # [B,P]
    pos_ce = -jnp.take_along_axis(logp, cls_for_prior[..., None],
                                  axis=-1)[..., 0]
    neg_ce = -logp[..., background_id]

    # hard negative mining: candidates are unmatched priors whose best
    # overlap is below neg_overlap, ranked by max non-background score
    probs = jax.nn.softmax(logits, axis=-1)
    fg = probs.at[..., background_id].set(0.0) if C > 1 else probs
    mine_score = jnp.max(fg, axis=-1)                            # [B,P]
    cand = (~pos) & (match_ov < neg_overlap)
    n_cand = jnp.sum(cand, axis=1)                               # [B]
    n_pos_img = jnp.sum(pos, axis=1)
    n_neg = jnp.minimum((neg_pos_ratio * n_pos_img).astype(jnp.int32), n_cand)
    scores = jnp.where(cand, mine_score, -jnp.inf)
    order = jnp.argsort(-scores, axis=1)
    rank = jnp.argsort(order, axis=1)                            # rank per prior
    neg = cand & (rank < n_neg[:, None])

    conf_loss = (jnp.sum(jnp.where(pos, pos_ce, 0.0))
                 + jnp.sum(jnp.where(neg, neg_ce, 0.0)))

    denom = jnp.maximum(num_pos, 1).astype(jnp.float32)
    total = (loc_loss + conf_loss) / denom
    return jnp.where(num_pos > 0, total, 0.0)


# ----------------------------------------------------------------- NMS

def _nms_class(boxes: jnp.ndarray, scores: jnp.ndarray, top_k: int,
               conf_threshold: float, nms_threshold: float
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``applyNMSFast`` for one class: returns (keep mask over top_k
    candidates, candidate prior indices [top_k])."""
    k = min(top_k, scores.shape[0])
    top_scores, top_idx = jax.lax.top_k(scores, k)
    cand_boxes = boxes[top_idx]                                  # [k,4]
    ov = iou_matrix(cand_boxes, cand_boxes)                      # [k,k]

    def body(i, keep):
        # candidate i survives if above threshold and not overlapped by a
        # surviving higher-scored candidate
        sup = jnp.any(jnp.where(jnp.arange(k) < i,
                                keep & (ov[i] > nms_threshold), False))
        ok = (top_scores[i] > conf_threshold) & (~sup)
        return keep.at[i].set(ok)

    keep = jax.lax.fori_loop(0, k, body, jnp.zeros((k,), bool))
    return keep, top_idx


@register_op("detection_output")
def detection_output(conf: jnp.ndarray, loc: jnp.ndarray,
                     priors: jnp.ndarray, num_classes: int,
                     background_id: int = 0, conf_threshold: float = 0.01,
                     nms_top_k: int = 400, nms_threshold: float = 0.45,
                     keep_top_k: int = 200) -> jnp.ndarray:
    """``DetectionOutputLayer``: decode + per-class NMS + global top-k.

    Returns fixed-shape [B, keep_top_k, 7] rows
    (image_idx, class, score, xmin, ymin, xmax, ymax); empty slots have
    image_idx = -1.
    """
    B, P, C = conf.shape
    probs = jax.nn.softmax(conf.astype(jnp.float32), axis=-1)

    def per_image(n, probs_n, loc_n):
        boxes = decode_boxes(priors[:, :4], priors[:, 4:], loc_n)  # [P,4]
        all_scores, all_rows = [], []
        for c in range(num_classes):
            if c == background_id:
                continue
            keep, idx = _nms_class(boxes, probs_n[:, c], nms_top_k,
                                   conf_threshold, nms_threshold)
            sc = jnp.where(keep, probs_n[idx, c], -jnp.inf)
            bx = jnp.clip(boxes[idx], 0.0, 1.0)
            rows = jnp.concatenate([
                jnp.full((idx.shape[0], 1), float(n)),
                jnp.full((idx.shape[0], 1), float(c)),
                sc[:, None], bx], axis=1)                         # [k,7]
            all_scores.append(sc)
            all_rows.append(rows)
        scores = jnp.concatenate(all_scores)
        rows = jnp.concatenate(all_rows, axis=0)
        kk = min(keep_top_k, scores.shape[0])
        top_sc, top_i = jax.lax.top_k(scores, kk)
        out = rows[top_i]
        out = jnp.where(jnp.isfinite(top_sc)[:, None], out,
                        jnp.full_like(out, -1.0))
        if kk < keep_top_k:
            out = jnp.pad(out, ((0, keep_top_k - kk), (0, 0)),
                          constant_values=-1.0)
        return out

    return jnp.stack([per_image(n, probs[n], loc[n]) for n in range(B)])

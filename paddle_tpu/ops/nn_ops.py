"""Neural-network ops: conv, pooling, normalization, dropout.

Replaces the reference's cuDNN wrappers (``hl_cuda_cudnn.cc``), the im2col
GEMM conv path (``paddle/function/GemmConvOp``, ``paddle/operators/math/
im2col``), pooling (``hl_cnn``/``pool_op``), batch_norm
(``paddle/operators/batch_norm_op.cc``, ``CudnnBatchNormLayer``), LRN
(``CrossMapNormLayer``/``lrn_op``), dropout, maxout, bilinear interp, prelu.

TPU-first choices: native ``lax.conv_general_dilated`` (XLA maps convs onto
the MXU directly — no im2col materialization), **NHWC layout** (channels on
the 128-lane minor dimension), bf16 compute via the precision policy.  The
reference's NCHW configs are converted at the layer-engine boundary.
"""

from __future__ import annotations

from functools import partial as _partial

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.dtypes import current_policy, record_op_precision
from ..observe import counter
from .registry import register_op

IntOr2 = Union[int, Tuple[int, int]]


def _record_conv_dispatch(op: str, path: str, reason: str = "") -> None:
    """One lowering decision of the fused conv/BN family (trace-time:
    ticks once per compiled program per shape — see the RNN counter in
    ops/recurrent_ops.py for the convention)."""
    counter(
        "conv_dispatch_total",
        "conv+BN lowering decisions by tier (trace-time; reason set "
        "when a fusable-looking call took the unfused composition)",
    ).inc(op=op, path=path, reason=reason)


def _pair(v: IntOr2) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _stem_space_to_depth(x, w, dn_format="NHWC"):
    """Exact reformulation of the 7×7/stride-2/pad-3 stem conv as a
    4×4/stride-1 conv over 2×2 space-to-depth blocks (the MLPerf conv0
    optimization): with C=3 the MXU's 128-deep contraction is ~2% busy;
    at 4C=12 the filter-gradient conv in particular stops being the
    slowest kernel of the step.  Derivation: output row i reads input
    rows 2i−3…2i+3 = block-rows i−2…i+1 → kernel 4, pad (2,1); kernel
    entry (pu,a) holds W[2pu+a−1] (u=−1,7 fall off → zero-pad W to 8².
    Same weights/checkpoint layout — the transform is per-step and XLA
    constant-folds it outside the loop."""
    n, h, w_, c = x.shape
    x2 = x.reshape(n, h // 2, 2, w_ // 2, 2, c) \
        .transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w_ // 2, 4 * c)
    kh, kw, ci, co = w.shape
    w8 = jnp.zeros((8, 8, ci, co), w.dtype).at[1:8, 1:8].set(w)
    w2 = w8.reshape(4, 2, 4, 2, ci, co).transpose(0, 2, 1, 3, 4, 5) \
        .reshape(4, 4, 4 * ci, co)
    dn = lax.conv_dimension_numbers(x2.shape, w2.shape,
                                    (dn_format, "HWIO", dn_format))
    return lax.conv_general_dilated(
        x2, w2, (1, 1), [(2, 1), (2, 1)], dimension_numbers=dn)


@register_op("conv2d")
def conv2d(x, w, stride: IntOr2 = 1, padding="SAME", dilation: IntOr2 = 1,
           groups: int = 1, data_format: str = "NHWC"):
    """2-D convolution.

    x: [N,H,W,C] (NHWC) or [N,C,H,W]; w: [KH,KW,Cin/groups,Cout] (HWIO).
    Reference: ``ExpandConvLayer``/``conv2d op`` — those im2col+GEMM; XLA
    lowers this directly to MXU convolutions.
    """
    pol = current_policy()
    record_op_precision("conv2d")
    x = x.astype(pol.compute_dtype)
    w = w.astype(pol.compute_dtype)
    if isinstance(padding, int):
        padding = [(padding, padding)] * 2
    elif isinstance(padding, (tuple, list)) and isinstance(padding[0], int):
        padding = [(padding[0], padding[0]), (padding[1], padding[1])]
    if (data_format == "NHWC" and groups == 1 and x.ndim == 4
            and w.shape[:2] == (7, 7) and w.shape[2] <= 4
            and _pair(stride) == (2, 2) and _pair(dilation) == (1, 1)
            and padding == [(3, 3), (3, 3)]
            and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0):
        return _stem_space_to_depth(x, w).astype(pol.output_dtype)
    if (data_format == "NHWC" and groups == 1 and x.ndim == 4
            and w.shape[:2] == (1, 1) and _pair(stride) == (1, 1)
            and _pair(dilation) == (1, 1)
            and padding in ("SAME", "VALID", [(0, 0), (0, 0)])):
        # A 1×1 stride-1 conv IS a matmul over the flattened spatial
        # dims; stating it as dot_general gives XLA the plain-GEMM
        # layout space instead of the convolution lowering (half of
        # ResNet-50's convs take this path; measured 2698 → 3065
        # samples/s on the train step).  Stride-2 1×1 was tried as
        # subsample-then-matmul and measured 25% WORSE (the strided
        # slice's backward is a scatter) — those stay on lax.conv.
        n, h, ww, cin = x.shape
        out = (x.reshape(n * h * ww, cin) @ w.reshape(cin, w.shape[3]))
        return out.reshape(n, h, ww, -1).astype(pol.output_dtype)
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        (data_format, "HWIO", data_format))
    # No preferred_element_type here: conv's transpose (grad) rule can't
    # mix a fp32 cotangent with bf16 operands in current jax; the MXU
    # accumulates in fp32 natively, so cast-after is equivalent.
    out = lax.conv_general_dilated(
        x, w, window_strides=_pair(stride), padding=padding,
        rhs_dilation=_pair(dilation), dimension_numbers=dn,
        feature_group_count=groups)
    return out.astype(pol.output_dtype)


@register_op("conv2d_transpose")
def conv2d_transpose(x, w, stride: IntOr2 = 1, padding="SAME",
                     data_format: str = "NHWC"):
    """Transposed conv (``conv2d_transpose_op.cc``). w: [KH,KW,Cout,Cin].

    Explicit padding follows the reference size contract
    out = (i−1)·s + k − 2p, implemented as the scatter-conv identity:
    conv of the stride-dilated input with the spatially-flipped filter
    at padding k−1−p.  (``lax.conv_transpose`` with explicit padding
    center-crops instead — wrong sizes for s > 1.)  String paddings keep
    the lax fast path.
    """
    pol = current_policy()
    x = x.astype(pol.compute_dtype)
    w = w.astype(pol.compute_dtype)
    if isinstance(padding, str):
        out = lax.conv_transpose(
            x, w, strides=_pair(stride), padding=padding,
            dimension_numbers=(data_format, "HWIO", data_format),
            transpose_kernel=True)
        return out.astype(pol.output_dtype)
    if isinstance(padding, int):
        padding = [(padding, padding)] * 2
    kh, kw = w.shape[0], w.shape[1]
    # HWIO with I = Cin (matching x's channels), spatially flipped
    w_flip = jnp.transpose(w, (0, 1, 3, 2))[::-1, ::-1]
    dn = lax.conv_dimension_numbers(x.shape, w_flip.shape,
                                    (data_format, "HWIO", data_format))
    out = lax.conv_general_dilated(
        x, w_flip, window_strides=(1, 1),
        padding=[(kh - 1 - padding[0][0], kh - 1 - padding[0][1]),
                 (kw - 1 - padding[1][0], kw - 1 - padding[1][1])],
        lhs_dilation=_pair(stride), dimension_numbers=dn)
    return out.astype(pol.output_dtype)


@register_op("conv3d")
def conv3d(x, w, stride=1, padding="SAME", data_format: str = "NDHWC"):
    """3-D convolution (``Conv3DLayer``). x: [N,D,H,W,C]; w: [KD,KH,KW,I,O]."""
    pol = current_policy()
    x = x.astype(pol.compute_dtype)
    w = w.astype(pol.compute_dtype)
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    dn = lax.conv_dimension_numbers(x.shape, w.shape, (data_format, "DHWIO", data_format))
    return lax.conv_general_dilated(
        x, w, window_strides=s, padding=padding,
        dimension_numbers=dn).astype(pol.output_dtype)


@register_op("conv3d_transpose")
def conv3d_transpose(x, w, stride=1, padding="SAME",
                     data_format: str = "NDHWC"):
    """Transposed 3-D conv (``DeConv3DLayer``). x: [N,D,H,W,C];
    w: [KD,KH,KW,Cout,Cin] (transpose_kernel layout, like conv2d_transpose)."""
    pol = current_policy()
    x = x.astype(pol.compute_dtype)
    w = w.astype(pol.compute_dtype)
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    out = lax.conv_transpose(
        x, w, strides=s, padding=padding,
        dimension_numbers=(data_format, "DHWIO", data_format),
        transpose_kernel=True)
    return out.astype(pol.output_dtype)


@register_op("pool3d")
def pool3d(x, pool_type: str = "max", window=2, stride=2, padding=0):
    """3-D max/avg pool over NDHWC (``Pool3DLayer``); avg excludes padding
    from the divisor like ``_pool``."""
    kd, kh, kw = (window,) * 3 if isinstance(window, int) else tuple(window)
    sd, sh, sw = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        padding = (padding,) * 3
    pd, ph, pw = padding
    dims, strides = (1, kd, kh, kw, 1), (1, sd, sh, sw, 1)
    pads = [(0, 0), (pd, pd), (ph, ph), (pw, pw), (0, 0)]
    if "max" in pool_type:
        return lax.reduce_window(x, -np.inf, lax.max, dims, strides, pads)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims,
                               strides, pads)
    return summed / counts


def _pool(x, kind: str, window: IntOr2, stride: IntOr2, padding,
          data_format: str = "NHWC"):
    kh, kw = _pair(window)
    sh, sw = _pair(stride)
    if data_format == "NHWC":
        dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
        spatial = [1, 2]
    else:  # NCHW
        dims, strides = (1, 1, kh, kw), (1, 1, sh, sw)
        spatial = [2, 3]
    if isinstance(padding, int):
        pads = [(0, 0)] * 4
        for ax in spatial:
            pads[ax] = (padding, padding)
    elif isinstance(padding, str):
        pads = padding
    else:
        pads = [(0, 0)] * 4
        for ax, p in zip(spatial, padding):
            pads[ax] = _pair(p)
    # init values MUST be python scalars: a device-array init becomes a
    # tracer under jit and jax then can't pattern-match the max/add monoid,
    # leaving a generic reduce_window with no autodiff rule.
    if kind == "max":
        dt = np.dtype(x.dtype)
        # branch on integer (not floating): bf16/fp8 are numpy void types
        init = np.iinfo(dt).min if np.issubdtype(dt, np.integer) \
            else -np.inf
        return lax.reduce_window(x, init, lax.max, dims, strides, pads)
    # avg: exclude padding from the divisor (cuDNN
    # CUDNN_POOLING_AVERAGE_COUNT_EXCLUDE_PADDING — reference default).
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
    return summed / counts


@register_op("pool2d")
def pool2d(x, pool_type: str = "max", window: IntOr2 = 2, stride: IntOr2 = 2,
           padding=0, data_format: str = "NHWC", global_pooling: bool = False):
    if global_pooling:
        axes = (1, 2) if data_format == "NHWC" else (2, 3)
        red = jnp.max if pool_type == "max" else jnp.mean
        return red(x, axis=axes, keepdims=True)
    return _pool(x, pool_type, window, stride, padding, data_format)


@register_op("max_pool2d_with_index", n_outputs=2)
def max_pool2d_with_index(x, window: IntOr2 = 2, stride: IntOr2 = 2,
                          padding: int = 0):
    """Max pool returning flat spatial argmax indices
    (``pool_with_index_op``), NHWC."""
    n, h, w, c = x.shape
    kh, kw = _pair(window)
    sh, sw = _pair(stride)
    pos = jnp.arange(h * w, dtype=jnp.float32).reshape(1, h, w, 1)
    pos = jnp.broadcast_to(pos, x.shape)

    def select(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    pads = [(0, 0), (padding, padding), (padding, padding), (0, 0)]
    (vals, idxs) = lax.reduce_window(
        (x, pos),
        (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1.0)),
        select, (1, kh, kw, 1), (1, sh, sw, 1), pads)
    return vals, idxs.astype(jnp.int32)


@register_op("spp")
def spatial_pyramid_pool(x, pyramid_height: int, pool_type: str = "max"):
    """Spatial pyramid pooling (``SpatialPyramidPoolLayer``), NHWC → [N, F]."""
    n, h, w, c = x.shape
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        # adaptive pooling: split H/W into `bins` regions
        hs = [h * i // bins for i in range(bins + 1)]
        ws = [w * i // bins for i in range(bins + 1)]
        for i in range(bins):
            for j in range(bins):
                region = x[:, hs[i]:hs[i + 1], ws[j]:ws[j + 1], :]
                red = jnp.max if pool_type == "max" else jnp.mean
                outs.append(red(region, axis=(1, 2)))
    return jnp.concatenate(outs, axis=-1).reshape(n, -1)


def _bn_axes(ndim: int, data_format: str) -> Tuple[Tuple[int, ...], int]:
    c_ax = ndim - 1 if data_format.endswith("C") else 1
    return tuple(i for i in range(ndim) if i != c_ax), c_ax


def _bn_apply(x, scale, bias, m, inv, c_ax):
    """One fused multiply-add pass in x's dtype with the per-channel
    scale/offset folded."""
    shape = [1] * x.ndim
    shape[c_ax] = x.shape[c_ax]
    a = (inv * scale).astype(x.dtype).reshape(shape)
    b = (bias - m * inv * scale).astype(x.dtype).reshape(shape)
    return x * a + b


@_partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_train(x, scale, bias, eps, axes, c_ax):
    (y, _stats), _res = _bn_train_fwd(x, scale, bias, eps, axes, c_ax)
    return y


def _bn_stats(x, axes):
    m = jnp.mean(x, axis=axes, dtype=jnp.float32)
    # square in fp32: the upcast happens in-register on the same bf16
    # read, and a bf16 x*x loses all low bits when |mean| >> std,
    # collapsing the E[x²]−E[x]² difference to 0
    m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
    v = jnp.maximum(m2 - m * m, 0.0)
    return m, v


def _bn_train_fwd(x, scale, bias, eps, axes, c_ax):
    m, v = _bn_stats(x, axes)
    inv = lax.rsqrt(v + eps)
    y = _bn_apply(x, scale, bias, m, inv, c_ax)
    return (y, (m, v)), (x, scale, m, inv)


def _bn_train_bwd(eps, axes, c_ax, res, cts):
    """Hand-fused BN backward (the cuDNN ``BatchNormBackward`` formula):

        dbias  = Σ dy
        dscale = Σ dy·x̂
        dx     = scale·inv · (dy − dbias/N − x̂·dscale/N)

    ONE fused reduction pass over (dy, x) for both sums + one apply pass
    — autodiff through the E[x²] stats path emits twice the reduction
    traffic, which profiling showed as ~18% of the ResNet train step
    ("convert_reduce" loop fusions).  Stats cotangents (running-average
    buffers) are dropped: buffers are side-channel state with
    stop-gradient semantics, as in the reference
    (``BatchNormalizationLayer`` never backprops moving averages).
    """
    dy, _ = cts
    x, scale, m, inv = res
    shape = [1] * x.ndim
    shape[c_ax] = x.shape[c_ax]
    n = np.prod([x.shape[i] for i in axes]).astype(np.float32)
    xhat_f = (x.astype(jnp.float32) - m.reshape(shape)) * inv.reshape(shape)
    dy_f = dy.astype(jnp.float32)
    dbias = jnp.sum(dy_f, axis=axes)
    dscale = jnp.sum(dy_f * xhat_f, axis=axes)
    coeff = (scale * inv).astype(jnp.float32).reshape(shape)
    dx = coeff * (dy_f - (dbias / n).reshape(shape)
                  - xhat_f * (dscale / n).reshape(shape))
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(scale.dtype))


def _bn_train_y_fwd(x, scale, bias, eps, axes, c_ax):
    (y, _stats), res = _bn_train_fwd(x, scale, bias, eps, axes, c_ax)
    return y, res


def _bn_train_y_bwd(eps, axes, c_ax, res, dy):
    return _bn_train_bwd(eps, axes, c_ax, res, (dy, None))


_bn_train.defvjp(_bn_train_y_fwd, _bn_train_y_bwd)


@register_op("batch_norm", n_outputs=3)
def batch_norm(x, scale, bias, running_mean, running_var,
               momentum: float = 0.9, eps: float = 1e-5,
               is_training: bool = True, data_format: str = "NHWC"):
    """Batch normalization (``batch_norm_op.cc``, ``BatchNormalizationLayer``).

    Returns (y, new_running_mean, new_running_var).  Stats accumulate in
    fp32 regardless of compute dtype (TPU numerics), but the tensor is
    READ in its own dtype (one pass, E[x²]−E[x]² with fp32 accumulators)
    and the normalization is a single multiply-add in x's dtype with the
    per-channel scale/offset folded — under bf16 activations this halves
    BN's HBM traffic, which dominates ResNet-class steps.  Training mode
    uses a hand-fused custom-VJP backward (see :func:`_bn_train_bwd`).
    """
    axes, c_ax = _bn_axes(x.ndim, data_format)
    if is_training:
        # stats recomputed outside the custom_vjp for the running
        # averages (cheap per-channel math; XLA CSEs the reduction with
        # the one inside _bn_train's forward)
        m, v = _bn_stats(x, axes)
        y = _bn_train(x, scale, bias, eps, axes, c_ax)
        new_rm = momentum * running_mean + (1 - momentum) * m
        new_rv = momentum * running_var + (1 - momentum) * v
        return y, new_rm, new_rv
    inv = lax.rsqrt(running_var + eps)
    y = _bn_apply(x, scale, bias, running_mean, inv, c_ax)
    return y, running_mean, running_var


def _gemm_prologue_ok(x_shape, w_shape, stride, padding, dilation,
                      groups, data_format) -> bool:
    """Static gate for the 1×1 GEMM-prologue path of
    :func:`affine_act_conv2d`: the same family the plain-GEMM ``conv2d``
    fast path accepts (1×1 stride-1 NHWC, groups=1, zero pad)."""
    if data_format != "NHWC" or groups != 1:
        return False
    if len(x_shape) != 4 or len(w_shape) != 4 \
            or tuple(w_shape[:2]) != (1, 1):
        return False
    if _pair(stride) != (1, 1) or _pair(dilation) != (1, 1):
        return False
    if isinstance(padding, str):
        return padding in ("SAME", "VALID")
    if isinstance(padding, int):
        return padding == 0
    pads = [_pair(p) for p in padding]
    return pads == [(0, 0), (0, 0)]


def _affine_apply(z, a, c, act: str):
    """The unfused BN-apply formula: act(a·z + c) in z's dtype — the
    exact composition the fused paths replace (and fall back to)."""
    x = z * a.astype(z.dtype) + c.astype(z.dtype)
    if act == "relu":
        return jax.nn.relu(x)
    if act in ("", "linear"):
        return x
    from . import get_activation

    return get_activation(act)(x)


@_partial(jax.custom_vjp, nondiff_argnums=(4,))
def _affine_conv1x1_core(z, a, c, w, relu):
    """act(a·z + c) @ w — the 1×1 stride-1 GEMM conv with the upstream
    BN's folded affine (+ReLU) as a fused prologue.  Stating the
    elementwise prologue inline hands XLA the GEMM operand to fuse it
    into, and the custom backward recomputes x from the raw z residual
    instead of saving the normalized activation — the same recompute
    discipline as the Pallas 3×3 path."""
    return _affine_1x1_fwd(z, a, c, w, relu)[0]


def _affine_1x1_fwd(z, a, c, w, relu):
    n, h, ww, cin = z.shape
    x = _affine_apply(z, a, c, "relu" if relu else "")
    out = (x.reshape(n * h * ww, cin) @ w.reshape(cin, -1)) \
        .reshape(n, h, ww, -1)
    return out, (z, a, c, w)


def _affine_1x1_bwd(relu, res, dy):
    z, a, c, w = res
    n, h, ww, cin = z.shape
    cout = w.shape[3]
    # mask/x recomputed from z exactly as the forward formed them
    u = z * a.astype(z.dtype) + c.astype(z.dtype)
    x = jax.nn.relu(u) if relu else u
    t = (dy.reshape(n * h * ww, cout) @ w.reshape(cin, cout).T) \
        .reshape(z.shape).astype(jnp.float32)
    du = jnp.where(u > 0, t, 0.0) if relu else t
    dz = (a * du).astype(z.dtype)
    da = jnp.sum(z.astype(jnp.float32) * du, axis=(0, 1, 2))
    dc = jnp.sum(du, axis=(0, 1, 2))
    dw = jax.lax.dot_general(
        x.reshape(n * h * ww, cin), dy.reshape(n * h * ww, cout),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(w.shape)
    return dz, da.astype(a.dtype), dc.astype(c.dtype), dw.astype(w.dtype)


_affine_conv1x1_core.defvjp(_affine_1x1_fwd, _affine_1x1_bwd)


@register_op("affine_act_conv2d")
def affine_act_conv2d(z, a, c, w, conv_bias=None, act: str = "relu",
                      is_training: bool = True, stride: IntOr2 = 1,
                      padding="SAME", dilation: IntOr2 = 1,
                      groups: int = 1, data_format: str = "NHWC"):
    """Fused BN-affine(+act)→conv forward: y = conv(act(a·z + c), w).

    The forward half of the fused conv/BN family: ``a``/``c`` are the
    upstream batch-norm's folded per-channel scale/offset (train-mode
    batch stats or eval-mode running stats — folded identically), and
    the normalized activation never materializes in HBM.  Dispatch:

    - 3×3 stride-1 pad-1 NHWC with 64-multiple channels → the Pallas
      forward kernel (:mod:`paddle_tpu.ops.pallas_conv`), the affine
      applied in its VMEM input pipeline;
    - 1×1 stride-1 NHWC → the plain-GEMM conv path with the affine as
      a fused GEMM prologue (custom backward, raw-z residuals);
    - anything else — eval mode, off-tile channels, stride-2, other
      activations — the exact unfused composition.

    Gradients flow into z, a, c, and w; the caller owns the BN-side
    chain rule from (a, c) back to scale/bias and the batch stats.
    """
    from . import pallas_conv

    pol = current_policy()
    record_op_precision("affine_act_conv2d")
    relu = act == "relu"
    zs, ws = jnp.shape(z), jnp.shape(w)
    fusable_act = act in ("relu", "", "linear")
    if is_training and fusable_act and pallas_conv.fusable_fwd(
            zs, ws, stride, padding, dilation, groups, data_format):
        _record_conv_dispatch("affine_act_conv2d", "pallas3x3")
        out = pallas_conv._affine_conv_core(
            z.astype(pol.compute_dtype), a.astype(jnp.float32),
            c.astype(jnp.float32), w.astype(pol.compute_dtype), relu)
        out = out.astype(pol.output_dtype)
    elif is_training and fusable_act and _gemm_prologue_ok(
            zs, ws, stride, padding, dilation, groups, data_format):
        _record_conv_dispatch("affine_act_conv2d", "gemm1x1")
        out = _affine_conv1x1_core(
            z.astype(pol.compute_dtype), a.astype(jnp.float32),
            c.astype(jnp.float32), w.astype(pol.compute_dtype), relu)
        out = out.astype(pol.output_dtype)
    else:
        _record_conv_dispatch(
            "affine_act_conv2d", "unfused",
            "eval mode" if not is_training
            else "non-fusable activation" if not fusable_act
            else "off-tile shape/stride/layout")
        out = conv2d(_affine_apply(z, a, c, act), w, stride=stride,
                     padding=padding, dilation=dilation, groups=groups,
                     data_format=data_format)
    if conv_bias is not None:
        out = out + conv_bias
    return out


def bn_folded_affine(x, scale, bias, running_mean, running_var,
                     momentum: float = 0.9, eps: float = 1e-5,
                     is_training: bool = True, data_format: str = "NHWC"):
    """The folded per-channel affine of :func:`batch_norm` WITHOUT
    applying it, plus the running-stat update: returns
    ``(a, c, new_rm, new_rv)`` with ``batch_norm(x, ...) ==
    act(a·x + c)`` elementwise.  This is the deferred form consumed by
    :func:`affine_act_conv2d` (forward conv+BN fusion); keeping it next
    to ``batch_norm`` pins both paths to the same stats/eps/momentum
    conventions."""
    axes, _c_ax = _bn_axes(x.ndim, data_format)
    if is_training:
        m, v = _bn_stats(x, axes)
        new_rm = momentum * running_mean + (1 - momentum) * m
        new_rv = momentum * running_var + (1 - momentum) * v
    else:
        m, v = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    inv = lax.rsqrt(v + eps)
    a = (scale * inv).astype(jnp.float32)
    c = (bias - m * a).astype(jnp.float32)
    return a, c, new_rm, new_rv


@register_op("conv2d_bn", n_outputs=3)
def conv2d_bn(x, w, conv_bias, scale, bias, running_mean, running_var,
              momentum: float = 0.9, eps: float = 1e-5,
              is_training: bool = True, stride: IntOr2 = 1,
              padding="SAME", dilation: IntOr2 = 1, groups: int = 1,
              data_format: str = "NHWC", in_affine=None):
    """Fused conv + batch-norm (training): same contract as
    ``conv2d`` (+ optional conv bias) followed by ``batch_norm``, but
    for the 3×3 stride-1 NHWC family the backward runs through the
    Pallas backward-data kernel in :mod:`paddle_tpu.ops.pallas_conv`,
    which applies the BN-backward per-channel affine while streaming
    tiles through VMEM — the dz apply pass and its HBM round-trip
    disappear (the cuDNN fused conv/BN backward of
    ``hl_cuda_cudnn.cc``, rebuilt for TPU).  Shapes outside the fused
    family, eval mode, and non-NHWC layouts take the exact unfused
    composition — same results either way, pinned by
    ``tests/test_pallas_conv.py``.

    ``in_affine=(a, c, act)`` composes the FORWARD fusion into the same
    pair: ``x`` is then the upstream BN's raw input z and the pair
    computes BN(conv(act(a·z + c)) + cb) with the prologue streamed
    through the Pallas kernels' input pipelines in both directions
    (``pallas_conv._chain_core``).  Off-family shapes materialize the
    affine exactly (the unfused BN apply) and continue as a plain pair.

    Returns (y, new_running_mean, new_running_var) like ``batch_norm``.
    """
    from . import pallas_conv

    pol = current_policy()
    record_op_precision("conv2d_bn")
    if in_affine is not None:
        a1, c1, act1 = in_affine
        xs, ws = jnp.shape(x), jnp.shape(w)
        if (is_training and act1 in ("relu", "", "linear")
                and pallas_conv.fusable(xs, ws, stride, padding,
                                        dilation, groups, data_format)
                and pallas_conv.fused_chain_ok(
                    xs[1], xs[2], int(ws[2]), int(ws[3]))):
            _record_conv_dispatch("conv2d_bn", "chain")
            xc = x.astype(pol.compute_dtype)
            wc = w.astype(pol.compute_dtype)
            cb = jnp.zeros((wc.shape[3],), jnp.float32) \
                if conv_bias is None else conv_bias
            y, m, v = pallas_conv._chain_core(
                xc, a1.astype(jnp.float32), c1.astype(jnp.float32), wc,
                cb, scale, bias, eps, act1 == "relu")
            new_rm = momentum * running_mean + (1 - momentum) * m
            new_rv = momentum * running_var + (1 - momentum) * v
            return y.astype(pol.output_dtype), new_rm, new_rv
        # outside the chain family: materialize the affine exactly (the
        # unfused BN apply formula) and continue as a plain conv→BN pair
        x = _affine_apply(x, a1, c1, act1)
    if not (is_training and pallas_conv.fusable(
            jnp.shape(x), jnp.shape(w), stride, padding, dilation,
            groups, data_format)):
        _record_conv_dispatch(
            "conv2d_bn", "unfused",
            "eval mode" if not is_training
            else "off-tile shape/stride/layout")
        z = conv2d(x, w, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
        if conv_bias is not None:
            z = z + conv_bias
        return batch_norm(z, scale, bias, running_mean, running_var,
                          momentum=momentum, eps=eps,
                          is_training=is_training,
                          data_format=data_format)
    _record_conv_dispatch("conv2d_bn", "fused")
    xc = x.astype(pol.compute_dtype)
    wc = w.astype(pol.compute_dtype)
    cb = jnp.zeros((wc.shape[3],), jnp.float32) if conv_bias is None \
        else conv_bias
    y = pallas_conv._conv_bn_core(xc, wc, cb, scale, bias, eps)
    # stats recomputed outside the custom_vjp for the running averages
    # (XLA CSEs the conv and reductions with the ones inside the core)
    z = pallas_conv._conv3x3(xc, wc) + cb.astype(xc.dtype)
    m, v = _bn_stats(z, (0, 1, 2))
    new_rm = momentum * running_mean + (1 - momentum) * m
    new_rv = momentum * running_var + (1 - momentum) * v
    return y.astype(pol.output_dtype), new_rm, new_rv


@register_op("lrn")
def lrn(x, n: int = 5, k: float = 2.0, alpha: float = 1e-4, beta: float = 0.75):
    """Local response normalization across channels, NHWC
    (``lrn_op.cc``, ``CrossMapNormLayer`` — note gserver uses
    ``scale = k + alpha * sum``; op uses same form)."""
    sq = jnp.square(x)
    half = n // 2
    pads = [(0, 0)] * (x.ndim - 1) + [(half, half)]
    sq = jnp.pad(sq, pads)
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + lax.slice_in_dim(sq, i, i + x.shape[-1], axis=-1)
    return x / jnp.power(k + alpha * acc, beta)


@register_op("dropout")
def dropout(x, key, rate: float = 0.5, is_training: bool = True):
    if not is_training or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@register_op("maxout")
def maxout(x, groups: int, data_format: str = "NHWC"):
    """Max over channel groups (``MaxOutLayer``/``hl_maxout``)."""
    if data_format == "NHWC":
        n, h, w, c = x.shape
        return jnp.max(x.reshape(n, h, w, c // groups, groups), axis=-1)
    n, c, h, w = x.shape
    return jnp.max(x.reshape(n, groups, c // groups, h, w), axis=1)


@register_op("prelu")
def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("bilinear_interp")
def bilinear_interp(x, out_h: int, out_w: int):
    """Bilinear upsampling, NHWC (``BilinearInterpLayer``/``hl_bilinear``,
    align_corners-style ratio as the reference computes it)."""
    n, h, w, c = x.shape
    return jax.image.resize(x, (n, out_h, out_w, c), method="bilinear")


@register_op("feature_map_expand")
def feature_map_expand(x, num_filters: int, as_row: bool = True):
    """Tile a [B, D] input into [B, num_filters*D] (``FeatureMapExpandLayer``)."""
    b, d = x.shape
    if as_row:
        return jnp.tile(x[:, None, :], (1, num_filters, 1)).reshape(b, -1)
    return jnp.tile(x[:, :, None], (1, 1, num_filters)).reshape(b, -1)


@register_op("block_expand")
def block_expand(x, block_h: int, block_w: int, stride_h: int, stride_w: int,
                 pad_h: int = 0, pad_w: int = 0):
    """Image → sequence of flattened patches (``BlockExpandLayer``), NHWC in,
    [B, S, block_h*block_w*C] out (S = #patches, row-major)."""
    x = jnp.pad(x, [(0, 0), (pad_h, pad_h), (pad_w, pad_w), (0, 0)])
    patches = lax.conv_general_dilated_patches(
        jnp.moveaxis(x, -1, 1), (block_h, block_w), (stride_h, stride_w),
        padding="VALID")  # [N, C*bh*bw, OH, OW]
    n, f, oh, ow = patches.shape
    return jnp.moveaxis(patches.reshape(n, f, oh * ow), 1, 2)


@register_op("rotate")
def rotate(x, height: int, width: int):
    """Rotate flattened [B, H*W*C] feature maps 90° CCW (``RotateLayer``)."""
    b = x.shape[0]
    c = x.shape[1] // (height * width)
    img = x.reshape(b, height, width, c)
    return jnp.rot90(img, k=1, axes=(1, 2)).reshape(b, -1)


@register_op("switch_order")
def switch_order(x, to: str = "NHWC"):
    """NCHW↔NHWC (``SwitchOrderLayer``, ``paddle/function/SwitchOp``)."""
    if to == "NHWC":
        return jnp.moveaxis(x, 1, -1)
    return jnp.moveaxis(x, -1, 1)

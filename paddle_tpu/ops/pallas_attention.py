"""Flash attention as a Pallas TPU kernel.

The reference hand-wrote its hot kernels in CUDA (``hl_lstm``,
``hl_top_k``); the TPU analogue of that tier is Pallas.  This module
implements blockwise (flash) attention: k/v stream through VMEM one
block per grid step with an online softmax (running max / normalizer
kept in VMEM scratch), so the [T, T] score matrix never exists in HBM
and VMEM holds only O(block²+block·D) — sequence length is bounded by
HBM for q/k/v themselves, not by attention intermediates.

Layout matches :mod:`paddle_tpu.parallel.ring_attention`'s
``full_attention``: q, k, v are ``[B, T, H, D]``; output ``[B, T, H, D]``.

Backward: custom VJP with the standard recomputation formulation — the
saved residuals are (q, k, v, out, per-row logsumexp); gradients are
einsums (XLA/MXU-friendly).  The O(T²) score matrix does get rebuilt in
backward; the forward memory saving (what bounds sequence length at
inference and in activation-checkpointed training) is kept.

On non-TPU backends the kernel runs in Pallas interpret mode so the CPU
test mesh exercises the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _choose_block(t: int, want: int) -> int:
    b = min(want, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
               scale, causal, block_q, block_k, n_kblocks):
    """Grid (B·H, q_blocks, k_blocks); k innermost so the scratch
    accumulators carry the online softmax across k steps."""
    i_k = pl.program_id(2)

    @pl.when(i_k == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    q_off = pl.program_id(1) * block_q
    k_off = i_k * block_k

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, D]
        kb = k_ref[0]                                   # [bk, D]
        vb = v_ref[0]
        s = q @ kb.astype(jnp.float32).T                # [bq, bk]
        if causal:
            qi = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            ki = k_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= ki, s, NEG_INF)
        m_prev = m_s[:]
        l_prev = l_s[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_s[:] = m_new
        l_s[:] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + p @ vb.astype(jnp.float32)

    if causal:
        # blocks fully above the diagonal contribute nothing — skip
        pl.when(k_off <= q_off + block_q - 1)(_step)
    else:
        _step()

    @pl.when(i_k == n_kblocks - 1)
    def _flush():
        o_ref[0] = (acc_s[:] / l_s[:]).astype(o_ref.dtype)
        # lse block is (1, 8, bq) purely for TPU tiling (last two dims
        # must be (8k, 128k) or match the array); row 0 carries the data
        lse_ref[0] = jnp.broadcast_to(
            (m_s[:] + jnp.log(l_s[:]))[:, 0][None, :], (8, block_q))


def _tiling_ok(t: int, bq: int, bk: int) -> bool:
    """Mosaic block constraints: the lse block's last dim (bq) must be a
    multiple of 128 or equal T; the k/v block's penultimate dim (bk)
    must be a multiple of 8 or equal T.  Checked on EVERY backend so
    interpret-mode tests exercise the same dispatch as real TPU."""
    ok_q = bq % 128 == 0 or bq == t
    ok_k = bk % 8 == 0 or bk == t
    return ok_q and ok_k


def _dense_forward(q, k, v, causal):
    """Fallback for shapes the kernel can't tile: plain XLA attention,
    same (out, lse) contract so the shared backward rule applies."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.arange(t)[None, None, :, None]
                      >= jnp.arange(t)[None, None, None, :], s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _fa_forward(q, k, v, causal, block_q, block_k):
    b, t, h, d = q.shape
    bq = _choose_block(t, block_q)
    bk = _choose_block(t, block_k)
    if not _tiling_ok(t, bq, bk):
        return _dense_forward(q, k, v, causal)
    scale = 1.0 / np.sqrt(d)
    # [B, T, H, D] → [B*H, T, D] so one grid row owns one head
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    n_kblocks = t // bk
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk,
                               n_kblocks=n_kblocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // bq, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, 8, bq), lambda i, j, s: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running normalizer
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qh, kh, vh)
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    lse = lse[:, 0, :].reshape(b, h, t)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 512,
                    block_k: int = 512):
    """softmax(q·kᵀ/√d)·v without materializing [T,T] scores in HBM.

    q, k, v: ``[B, T, H, D]``; returns ``[B, T, H, D]`` in q's dtype.
    """
    out, _lse = _fa_forward(q, k, v, causal, block_q, block_k)
    return out


def _fa_fwd_rule(q, k, v, causal, block_q, block_k):
    out, lse = _fa_forward(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd_rule(causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    of = out.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.arange(t)[None, None, :, None]
                      >= jnp.arange(t)[None, None, None, :], s, NEG_INF)
    p = jnp.exp(s - lse[:, :, :, None])                 # softmax weights
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    # delta_i = Σ_d dO_i·O_i (the softmax-backward row term)
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, of)
    ds = p * (dp - delta[:, :, :, None])
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fa_fwd_rule, _fa_bwd_rule)

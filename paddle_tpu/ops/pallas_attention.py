"""Flash attention as a Pallas TPU kernel — block-sparse (splash-style).

The reference hand-wrote its hot kernels in CUDA (``hl_lstm``,
``hl_top_k``); the TPU analogue of that tier is Pallas.  This module
implements blockwise (flash) attention: k/v stream through VMEM one
block per grid step with an online softmax (running max / normalizer
kept in VMEM scratch), so the [T, T] score matrix never exists in HBM
and VMEM holds only O(block²+block·D) — sequence length is bounded by
HBM for q/k/v themselves, not by attention intermediates.

Round 19 makes the kernel truly **block-sparse**: the (q-block,
k-block) iteration space is flattened into a scalar-prefetched *pair
table* that statically drops every block fully above the causal
diagonal (≈half of T²/2 at large T), and per-row dynamic windows
(valid-key lengths, packed segment ranges) clamp the k/v BlockSpec
index maps so dead blocks are **neither DMA'd nor visited** — the old
grid fetched every block and only skipped the compute (``pl.when``),
saving FLOPs but none of the HBM traffic.  The same pair tables and
ONE shared masking helper (:func:`_tile_mask` / element masks,
:func:`_causal_block_live` / block liveness) drive the forward, dq and
dk/dv kernels, so forward and backward sparsity can never diverge.
``--flash_block_sparse=false`` restores the legacy full grid;
``--flash_kernel=false`` restores the dense XLA composition.

Three entry points:

- :func:`flash_attention` — padded batches ([B, T, H, D] + optional
  int32 [B] key lengths), causal or not;
- :func:`flash_attention_packed` — sequence packing / ragged batching:
  mixed-length sequences share one [B, T_total, H, D] layout with an
  int32 segment id per token (−1 = padding; ids non-decreasing along
  the token axis — the packing contract); cross-segment and padding
  blocks do zero work.  ``--attention_packing=false`` upstream
  (layers/attention.py) disables the packed lowering entirely;
- :func:`paged_decode_attention` — the serving decode primitive: a
  small-Tq query batch attends a block-paged KV cache through a
  per-row page table + valid lengths (ROADMAP items 1 and 5's shared
  base; *Ragged Paged Attention*, arxiv 2604.15464).  Inference-only
  (no VJP).

Layout matches :mod:`paddle_tpu.parallel.ring_attention`'s
``full_attention``: q, k, v are ``[B, T, H, D]``; output ``[B, T, H, D]``.

Backward: custom VJP with the standard recomputation formulation — the
saved residuals are (q, k, v, out, per-row logsumexp).  When the shapes
tile, backward runs as TWO Pallas kernels (a dq pass streaming k/v and
a dk/dv pass streaming q/do, each rebuilding p blockwise from the saved
logsumexp) so the [T, T] score matrix never exists in HBM in either
direction; otherwise it falls back to dense einsums.

On non-TPU backends the kernel runs in Pallas interpret mode so the CPU
test mesh exercises the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..observe import counter
from ..utils import enforce
from ..utils.logger import get_logger, warn_once

NEG_INF = -1e30

_log = get_logger("ops.attention")

# jax renamed TPUCompilerParams → CompilerParams (0.5.x); resolve once
# here so every Pallas module runs interpret-mode CI on either version.
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def record_attention_dispatch(path: str, reason: str = "") -> None:
    """Count one attention lowering decision (trace-time: once per
    compiled program per shape — the ``rnn_dispatch_total`` /
    ``conv_dispatch_total`` convention).  ``reason`` is set when a
    flash-capable call took a fallback, with the same labels the
    one-time fallback warnings use."""
    counter(
        "attention_dispatch_total",
        "attention lowering decisions by path (trace-time; reason "
        "labels match the one-time fallback warnings)",
    ).inc(path=path, reason=reason)


def _warn_dense_fallback(reason: str, tq: int, tk: int, bq: int,
                         bk: int) -> None:
    warn_once(
        f"flash_attention_dense_fallback:{reason}:{tq}x{tk}",
        "flash_attention: dense XLA fallback taken for Tq=%d Tk=%d "
        "(blocks %d/%d): %s", tq, tk, bq, bk, reason, logger=_log)


def _choose_block(t: int, want: int) -> int:
    b = min(want, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------- shared mask helpers
def _causal_block_live(q_off, k_off, block_q):
    """Block-level causal liveness: the (q, k) tile contains at least
    one pair on or below the diagonal.  THE shared predicate — the
    static pair tables, the legacy-grid skip conditions and the
    backward kernels all call this one function, so forward and
    backward block sparsity can never diverge.  Works on python ints
    (table build) and traced values (kernels) alike."""
    return k_off <= q_off + block_q - 1


def _tile_mask(q_off, k_off, kv_len, causal, block_q, block_k,
               seg_q=None, seg_k=None):
    """[block_q, block_k] element validity for one tile — THE shared
    masking helper for the forward kernel, both backward kernels and
    the packed variants: key-padding (``kv_len``), causal diagonal,
    and (packed) segment-id equality with −1 = padding."""
    ki = k_off + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = ki < kv_len
    if causal:
        qi = q_off + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = jnp.logical_and(valid, qi >= ki)
    if seg_q is not None:
        valid = jnp.logical_and(valid, seg_q[:, None] == seg_k[None, :])
        valid = jnp.logical_and(valid, seg_q[:, None] >= 0)
    return valid


# ----------------------------------------------------------- pair tables
@functools.lru_cache(maxsize=None)
def _pair_tables(tq: int, tk: int, bq: int, bk: int, causal: bool,
                 slot: int = 0):
    """Static block-sparse iteration tables.

    Returns ``(tab_q, tab_k)`` — int32 ``[4, n_pairs]`` arrays with
    rows ``(q_block, k_block, is_first, is_last)`` — enumerating every
    causally-live (q-block, k-block) pair in q-major order (forward /
    dq kernels: the online-softmax / dq accumulators carry across one
    q block's pairs) and k-major order (dk/dv kernel: the dk/dv
    accumulators carry across one k block's pairs).  Blocks fully
    above the causal diagonal simply do not appear: at causal T=2048
    with 512-blocks that is 6 of 16 pairs gone — neither DMA'd nor
    visited.  Every q block (and, since causal requires Tq == Tk,
    every k block) keeps at least one pair, so outputs always flush.

    ``slot`` (packed layouts only): tokens per packed slot when the
    CALLER guarantees no segment crosses a slot boundary (the layer's
    [B, T] → [1, B·T] flatten: slot = T).  Block pairs in different
    slots are then statically dead and dropped from the table — the
    packed grid has exactly the padded grid's pair count instead of
    the full (B·nq)² cross product.  Only applied when slots are whole
    blocks (slot % bq == slot % bk == 0); 0 disables.
    """
    nq, nk = tq // bq, tk // bk
    if slot and (slot % bq or slot % bk):
        slot = 0                  # blocks straddle slots: hint unusable

    def build(q_major: bool):
        rows = [[], [], [], []]
        outer = range(nq) if q_major else range(nk)
        inner = range(nk) if q_major else range(nq)
        for a in outer:
            members = []
            for c in inner:
                j, s = (a, c) if q_major else (c, a)
                if causal and not _causal_block_live(
                        j * bq, s * bk, bq):
                    continue
                if slot and (j * bq) // slot != (s * bk) // slot:
                    continue
                members.append((j, s))
            for t, (j, s) in enumerate(members):
                rows[0].append(j)
                rows[1].append(s)
                rows[2].append(1 if t == 0 else 0)
                rows[3].append(1 if t == len(members) - 1 else 0)
        return np.asarray(rows, np.int32)

    return build(True), build(False)


def _length_windows(lengths, bsz: int, n_outer: int, bk: int):
    """``(lo, hi)`` int32 [B, n_outer] inclusive windows of live
    k-block indices per (batch row, q block) from valid-key lengths:
    the k/v index maps clamp into the window so blocks wholly inside
    the padding re-fetch the boundary block (a no-op DMA when the
    index repeats) instead of streaming dead data."""
    hi = jnp.maximum((lengths + bk - 1) // bk, 1) - 1       # [B]
    hi = jnp.broadcast_to(hi[:, None], (bsz, n_outer))
    lo = jnp.zeros((bsz, n_outer), jnp.int32)
    return lo, hi.astype(jnp.int32)


def _segment_windows(seg_outer, seg_inner, b_outer: int, b_inner: int):
    """``(lo, hi)`` int32 [B, n_outer] inclusive windows of inner
    blocks whose valid-segment range overlaps each outer block's.
    Relies on the packing contract (valid ids non-decreasing along the
    token axis, −1 padding anywhere) so each block's valid ids form an
    interval and blocks are ordered; an outer block with no valid
    token gets an empty (lo > hi) window."""
    bsz = seg_outer.shape[0]
    n_o = seg_outer.shape[1] // b_outer
    n_i = seg_inner.shape[1] // b_inner
    big = jnp.int32(2 ** 30)
    so = seg_outer.reshape(bsz, n_o, b_outer)
    si = seg_inner.reshape(bsz, n_i, b_inner)
    o_lo = jnp.min(jnp.where(so >= 0, so, big), axis=2)      # [B, n_o]
    o_hi = jnp.max(jnp.where(so >= 0, so, -big), axis=2)
    i_lo = jnp.min(jnp.where(si >= 0, si, big), axis=2)      # [B, n_i]
    i_hi = jnp.max(jnp.where(si >= 0, si, -big), axis=2)
    # inner block s overlaps outer block j iff the segment intervals
    # intersect; all-padding blocks (empty interval) never overlap, and
    # they may sit ANYWHERE between segments, so the window bounds come
    # from the live blocks' indices, not from counting "blocks before"
    live = jnp.logical_and(i_hi[:, None, :] >= o_lo[:, :, None],
                           i_lo[:, None, :] <= o_hi[:, :, None])
    idx = jnp.arange(n_i, dtype=jnp.int32)[None, None, :]
    lo = jnp.min(jnp.where(live, idx, n_i), axis=2)
    hi = jnp.max(jnp.where(live, idx, -1), axis=2)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _pair_live(tab_ref, lo_ref, hi_ref, len_ref, p, b, block_k):
    """Scalar liveness of pair ``p`` for batch row ``b``: inside the
    dynamic window AND the k block holds at least one valid key.
    Shared by the forward and dq kernels (the dk/dv kernel swaps the
    window roles — see ``_bwd_dkv_pair_kernel``)."""
    j = tab_ref[0, p]
    s = tab_ref[1, p]
    live = jnp.logical_and(s >= lo_ref[b, j], s <= hi_ref[b, j])
    return jnp.logical_and(live, s * block_k < len_ref[b])


def _win_clip(idx, lo, hi, n: int):
    """Clamp a block index into a dynamic [lo, hi] window and then the
    array bound (an empty lo > hi window would otherwise produce an
    out-of-range index for a pair that is compute-skipped anyway)."""
    return jnp.clip(jnp.clip(idx, lo, hi), 0, n - 1)


# ------------------------------------------------ pair-grid fwd kernel
def _fa_pair_kernel(*refs, scale, causal, block_q, block_k, n_heads,
                    packed):
    """Grid (B·H, n_pairs) over the q-major pair table: the online
    softmax carries in VMEM scratch across one q block's pairs,
    initialized at its first table entry and flushed at its last.
    Dead pairs (no valid key in the window) skip the compute; their
    DMA was already skipped by the clamped index maps."""
    if packed:
        (len_ref, lo_ref, hi_ref, tab_ref, q_ref, k_ref, v_ref,
         sq_ref, sk_ref, o_ref, lse_ref, m_s, l_s, acc_s) = refs
    else:
        (len_ref, lo_ref, hi_ref, tab_ref, q_ref, k_ref, v_ref,
         o_ref, lse_ref, m_s, l_s, acc_s) = refs
        sq_ref = sk_ref = None
    p = pl.program_id(1)
    b = pl.program_id(0) // n_heads
    kv_len = len_ref[b]
    q_off = tab_ref[0, p] * block_q
    k_off = tab_ref[1, p] * block_k

    @pl.when(tab_ref[2, p] == 1)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, D]
        kb = k_ref[0]                                   # [bk, D]
        vb = v_ref[0]
        s = q @ kb.astype(jnp.float32).T                # [bq, bk]
        valid = _tile_mask(
            q_off, k_off, kv_len, causal, block_q, block_k,
            None if sq_ref is None else sq_ref[0, :, 0],
            None if sk_ref is None else sk_ref[0, :, 0])
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_s[:]
        l_prev = l_s[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # a fully-masked ROW inside a live block (packed: padding
        # queries sharing a block with valid ones) has m_new = NEG_INF;
        # exp(s − m_new) would be exp(0) = 1 and leak mass — clamp the
        # exponent base so those rows underflow to 0 instead (the
        # flush's l_safe then emits exact zeros)
        m_base = jnp.maximum(m_new, NEG_INF / 2)
        pexp = jnp.exp(s - m_base)
        alpha = jnp.exp(m_prev - m_base)
        m_s[:] = m_new
        l_s[:] = l_prev * alpha + pexp.sum(axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + pexp @ vb.astype(jnp.float32)

    pl.when(_pair_live(tab_ref, lo_ref, hi_ref, len_ref, p, b,
                       block_k))(_step)

    @pl.when(tab_ref[3, p] == 1)
    def _flush():
        # guard fully-masked rows (query past a zero-length sequence /
        # padding segment): l = 0 → emit 0 not NaN, and clamp m away
        # from NEG_INF so the backward's p = exp(s − lse) underflows to
        # 0 instead of exp(NEG_INF − NEG_INF) = 1 leaking gradients
        l_safe = jnp.where(l_s[:] == 0.0, 1.0, l_s[:])
        m_safe = jnp.maximum(m_s[:], NEG_INF / 2)
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        # lse block is (1, 8, bq) purely for TPU tiling (last two dims
        # must be (8k, 128k) or match the array); row 0 carries the data
        lse_ref[0] = jnp.broadcast_to(
            (m_safe + jnp.log(l_safe))[:, 0][None, :], (8, block_q))


def _tiling_ok(tq: int, tk: int, bq: int, bk: int) -> bool:
    """Mosaic block constraints: the lse block's last dim (bq) must be a
    multiple of 128 or equal Tq; the k/v block's penultimate dim (bk)
    must be a multiple of 8 or equal Tk.  Checked on EVERY backend so
    interpret-mode tests exercise the same dispatch as real TPU."""
    ok_q = bq % 128 == 0 or bq == tq
    ok_k = bk % 8 == 0 or bk == tk
    return ok_q and ok_k


def packed_tileable(t_total: int, block_q: int, block_k: int) -> bool:
    """Would a packed (flattened, self-attention) layout of
    ``t_total`` tokens hit the Pallas kernels?  The layer pre-checks
    this and reverts an untileable flatten to the padded per-row
    lowering — the op-level dense fallback on a [1, B·T] axis would
    build an O((B·T)²) score matrix."""
    bq = _choose_block(t_total, block_q)
    bk = _choose_block(t_total, block_k)
    return _tiling_ok(t_total, t_total, bq, bk)


def _mask_scores(s, causal, lengths, segments=None):
    """Apply causal / key-padding / packed-segment masks to
    [B, H, Tq, Tk] scores — the dense-path twin of :func:`_tile_mask`
    (same semantics at full-matrix granularity)."""
    tq, tk = s.shape[-2], s.shape[-1]
    if causal:
        s = jnp.where(jnp.arange(tq)[None, None, :, None]
                      >= jnp.arange(tk)[None, None, None, :], s, NEG_INF)
    if lengths is not None:
        valid = jnp.arange(tk)[None, :] < lengths[:, None]   # [B, Tk]
        s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    if segments is not None:
        sq = segments[:, None, :, None]                      # [B,1,Tq,1]
        sk = segments[:, None, None, :]
        s = jnp.where(jnp.logical_and(sq == sk, sq >= 0), s, NEG_INF)
    return s


def _dense_forward(q, k, v, lengths, causal, segments=None):
    """Fallback for shapes the kernel can't tile (and the exact
    unfused reference the kill switches restore): plain XLA attention,
    same (out, lse) contract so the shared backward rule applies."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = _mask_scores(s, causal, lengths, segments)
    m = s.max(axis=-1)
    # fully-masked rows (query past a zero-length sequence): emit 0
    m_safe = jnp.maximum(m, NEG_INF / 2)
    l = jnp.exp(s - m_safe[..., None]).sum(axis=-1)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = m_safe + jnp.log(l_safe)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _heads_first(a, b, t, h, d):
    """[B, T, H, D] → [B·H, T, D] so one grid row owns one head."""
    return a.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _fa_forward_sparse(q, k, v, lengths, causal, bq, bk,
                       segments=None, slot=0):
    """Pair-table (block-sparse) forward: grid (B·H, n_pairs)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    qh = _heads_first(q, b, tq, h, d)
    kh = _heads_first(k, b, tk, h, d)
    vh = _heads_first(v, b, tk, h, d)
    nq, nk = tq // bq, tk // bk
    tab = jnp.asarray(_pair_tables(tq, tk, bq, bk, causal, slot)[0])
    n_pairs = tab.shape[1]
    if segments is None:
        lo, hi = _length_windows(lengths, b, nq, bk)
    else:
        lo, hi = _segment_windows(segments, segments, bq, bk)
    nh = h

    def q_idx(i, p, ln, lo_, hi_, tb):
        return (i, tb[0, p], 0)

    def kv_idx(i, p, ln, lo_, hi_, tb):
        j = tb[0, p]
        return (i, _win_clip(tb[1, p], lo_[i // nh, j],
                             hi_[i // nh, j], nk), 0)

    def sq_idx(i, p, ln, lo_, hi_, tb):
        return (i // nh, tb[0, p], 0)

    def sk_idx(i, p, ln, lo_, hi_, tb):
        j = tb[0, p]
        return (i // nh, _win_clip(tb[1, p], lo_[i // nh, j],
                                   hi_[i // nh, j], nk), 0)

    in_specs = [
        pl.BlockSpec((1, bq, d), q_idx),
        pl.BlockSpec((1, bk, d), kv_idx),
        pl.BlockSpec((1, bk, d), kv_idx),
    ]
    operands = [qh, kh, vh]
    if segments is not None:
        seg3 = segments.astype(jnp.int32).reshape(b, tq, 1)
        in_specs += [pl.BlockSpec((1, bq, 1), sq_idx),
                     pl.BlockSpec((1, bk, 1), sk_idx)]
        operands += [seg3, seg3]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b * h, n_pairs),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), q_idx),
            pl.BlockSpec((1, 8, bq),
                         lambda i, p, ln, lo_, hi_, tb: (i, 0, tb[0, p])),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running normalizer
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
        ],
    )
    kernel = functools.partial(
        _fa_pair_kernel, scale=scale, causal=causal, block_q=bq,
        block_k=bk, n_heads=h, packed=segments is not None)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, tq), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), lo, hi, tab, *operands)
    out = out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    lse = lse[:, 0, :].reshape(b, h, tq)
    return out, lse


# --------------------------------------------------- legacy full grid
def _fa_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s,
               acc_s, *, scale, causal, block_q, block_k, n_kblocks,
               n_heads):
    """Legacy grid (B·H, q_blocks, k_blocks); k innermost so the
    scratch accumulators carry the online softmax across k steps.
    Every k/v block is DMA'd; ``pl.when`` skips only the compute —
    kept byte-for-byte behind ``--flash_block_sparse=false``."""
    i_k = pl.program_id(2)
    kv_len = len_ref[pl.program_id(0) // n_heads]

    @pl.when(i_k == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    q_off = pl.program_id(1) * block_q
    k_off = i_k * block_k

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, D]
        kb = k_ref[0]                                   # [bk, D]
        vb = v_ref[0]
        s = q @ kb.astype(jnp.float32).T                # [bq, bk]
        valid = _tile_mask(q_off, k_off, kv_len, causal, block_q,
                           block_k)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_s[:]
        l_prev = l_s[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_s[:] = m_new
        l_s[:] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + p @ vb.astype(jnp.float32)

    # skip k blocks with no valid key: fully above the causal diagonal
    # or fully inside the padding (compute only — the DMA already ran)
    live = k_off < kv_len
    if causal:
        live = jnp.logical_and(live,
                               _causal_block_live(q_off, k_off, block_q))
    pl.when(live)(_step)

    @pl.when(i_k == n_kblocks - 1)
    def _flush():
        l_safe = jnp.where(l_s[:] == 0.0, 1.0, l_s[:])
        m_safe = jnp.maximum(m_s[:], NEG_INF / 2)
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            (m_safe + jnp.log(l_safe))[:, 0][None, :], (8, block_q))


def _fa_forward_grid(q, k, v, lengths, causal, bq, bk):
    """Legacy full-grid forward (``--flash_block_sparse=false``)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    qh = _heads_first(q, b, tq, h, d)
    kh = _heads_first(k, b, tk, h, d)
    vh = _heads_first(v, b, tk, h, d)
    n_kblocks = tk // bk
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk,
                               n_kblocks=n_kblocks, n_heads=h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, tq // bq, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
            pl.BlockSpec((1, 8, bq), lambda i, j, s, *_: (i, 0, j)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running normalizer
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, tq), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), qh, kh, vh)
    out = out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    lse = lse[:, 0, :].reshape(b, h, tq)
    return out, lse


def _block_sparse() -> bool:
    from ..utils import FLAGS

    return bool(FLAGS.flash_block_sparse)


def _flash_enabled() -> bool:
    from ..utils import FLAGS

    return bool(FLAGS.flash_kernel)


def _fa_forward(q, k, v, lengths, causal, block_q, block_k,
                segments=None, slot=0):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if causal:
        # a causal mask is only meaningful on a shared timeline
        enforce(tq == tk,
                f"causal attention needs Tq == Tk, got {tq}/{tk}")
    bq = _choose_block(tq, block_q)
    bk = _choose_block(tk, block_k)
    if lengths is None:
        lengths = jnp.full((b,), tk, jnp.int32)
    packed = segments is not None
    if packed:
        enforce(tq == tk, "packed attention is self-attention: one "
                          f"segment table, Tq == Tk, got {tq}/{tk}")
    if not _flash_enabled():
        record_attention_dispatch(
            "dense", "kill_switch:flash_kernel")
        return _dense_forward(q, k, v, lengths, causal, segments)
    if not _tiling_ok(tq, tk, bq, bk):
        reason = "untileable shape (lse/kv block constraints)"
        record_attention_dispatch("dense", reason)
        _warn_dense_fallback(reason, tq, tk, bq, bk)
        return _dense_forward(q, k, v, lengths, causal, segments)
    if _block_sparse():
        reason = ""
        if packed and slot and (slot % bq or slot % bk):
            # the slot hint can only drop cross-slot pairs when slots
            # are whole blocks; otherwise the grid keeps the full
            # cross product (windows still skip the compute + DMA,
            # but every pair is a scheduled step — O(B²) grid growth)
            reason = "slot hint unusable (blocks straddle slots)"
            warn_once(
                f"flash_attention_packed_slot:{slot}:{bq}x{bk}",
                "flash_attention_packed: slot hint %d unusable with "
                "blocks %d/%d (not whole blocks per slot); the pair "
                "table keeps the full cross product — prefer blocks "
                "dividing the slot width", slot, bq, bk, logger=_log)
        record_attention_dispatch("packed" if packed
                                   else "block_sparse", reason)
        return _fa_forward_sparse(q, k, v, lengths, causal, bq, bk,
                                  segments, slot)
    if packed:
        # the legacy grid has no segment plumbing: exact dense fallback
        record_attention_dispatch(
            "dense", "kill_switch:flash_block_sparse(packed)")
        return _dense_forward(q, k, v, lengths, causal, segments)
    record_attention_dispatch("legacy_grid",
                               "kill_switch:flash_block_sparse")
    return _fa_forward_grid(q, k, v, lengths, causal, bq, bk)


# ------------------------------------------------------ backward kernels
def _recompute_block(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     q_off, k_off, kv_len, scale, causal, block_q,
                     block_k, seg_q=None, seg_k=None):
    """Rebuild one (q-block, k-block) softmax tile from the saved
    logsumexp and return (p, ds, q, kb, do) in f32 — shared by the dq
    and dk/dv kernels (legacy AND pair-grid) so their masking/scaling
    can never diverge from the forward's :func:`_tile_mask`."""
    q = q_ref[0].astype(jnp.float32)
    kb = k_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)[:, None]         # [bq, 1]
    delta = delta_ref[0, 0].astype(jnp.float32)[:, None]
    s = (q @ kb.T) * scale
    valid = _tile_mask(q_off, k_off, kv_len, causal, block_q, block_k,
                       seg_q, seg_k)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    ds = p * (do @ vb.T - delta)
    return p, ds, q, kb, do


def _bwd_live(q_off, k_off, kv_len, causal, block_q):
    """Legacy-grid skip condition shared by both backward kernels: a
    block with no valid key (padding tail or fully above the causal
    diagonal)."""
    live = k_off < kv_len
    if causal:
        live = jnp.logical_and(live,
                               _causal_block_live(q_off, k_off, block_q))
    return live


def _bwd_dq_pair_kernel(*refs, scale, causal, block_q, block_k,
                        n_heads, packed):
    """Grid (B·H, n_pairs) over the q-major pair table: accumulate dq
    for one q block across its (causally-live) k pairs."""
    if packed:
        (len_ref, lo_ref, hi_ref, tab_ref, q_ref, k_ref, v_ref, do_ref,
         lse_ref, delta_ref, sq_ref, sk_ref, dq_ref, acc_s) = refs
    else:
        (len_ref, lo_ref, hi_ref, tab_ref, q_ref, k_ref, v_ref, do_ref,
         lse_ref, delta_ref, dq_ref, acc_s) = refs
        sq_ref = sk_ref = None
    p = pl.program_id(1)
    b = pl.program_id(0) // n_heads
    kv_len = len_ref[b]
    q_off = tab_ref[0, p] * block_q
    k_off = tab_ref[1, p] * block_k

    @pl.when(tab_ref[2, p] == 1)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    def _step():
        _p, ds, _q, kb, _do = _recompute_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off,
            k_off, kv_len, scale, causal, block_q, block_k,
            None if sq_ref is None else sq_ref[0, :, 0],
            None if sk_ref is None else sk_ref[0, :, 0])
        acc_s[:] = acc_s[:] + ds @ kb * scale

    pl.when(_pair_live(tab_ref, lo_ref, hi_ref, len_ref, p, b,
                       block_k))(_step)

    @pl.when(tab_ref[3, p] == 1)
    def _flush():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _bwd_dkv_pair_kernel(*refs, scale, causal, block_q, block_k,
                         n_heads, packed):
    """Grid (B·H, n_pairs) over the k-major pair table: accumulate
    dk/dv for one k block across its (causally-live) q pairs.  The
    dynamic window here runs over q blocks (packed segments); the
    key-padding liveness keeps the k-block-vs-length test."""
    if packed:
        (len_ref, lo_ref, hi_ref, tab_ref, q_ref, k_ref, v_ref, do_ref,
         lse_ref, delta_ref, sq_ref, sk_ref, dk_ref, dv_ref, dk_s,
         dv_s) = refs
    else:
        (len_ref, lo_ref, hi_ref, tab_ref, q_ref, k_ref, v_ref, do_ref,
         lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s) = refs
        sq_ref = sk_ref = None
    p = pl.program_id(1)
    b = pl.program_id(0) // n_heads
    kv_len = len_ref[b]
    j = tab_ref[0, p]
    s_blk = tab_ref[1, p]
    q_off = j * block_q
    k_off = s_blk * block_k

    @pl.when(tab_ref[2, p] == 1)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    def _step():
        pw, ds, q, _kb, do = _recompute_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off,
            k_off, kv_len, scale, causal, block_q, block_k,
            None if sq_ref is None else sq_ref[0, :, 0],
            None if sk_ref is None else sk_ref[0, :, 0])
        dv_s[:] = dv_s[:] + pw.T @ do
        dk_s[:] = dk_s[:] + ds.T @ q * scale

    live = jnp.logical_and(j >= lo_ref[b, s_blk], j <= hi_ref[b, s_blk])
    live = jnp.logical_and(live, k_off < kv_len)
    pl.when(live)(_step)

    @pl.when(tab_ref[3, p] == 1)
    def _flush():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _bwd_residual_streams(q, k, v, out, do, lse):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qh = _heads_first(q, b, tq, h, d)
    kh = _heads_first(k, b, tk, h, d)
    vh = _heads_first(v, b, tk, h, d)
    doh = _heads_first(do, b, tq, h, d)
    # delta_i = Σ_d dO_i·O_i (softmax-backward row term), [BH, 1, T]
    delta = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                       out.astype(jnp.float32)).reshape(b * h, 1, tq)
    lse3 = lse.reshape(b * h, 1, tq)
    return qh, kh, vh, doh, delta, lse3


def _fa_backward_sparse(q, k, v, lengths, out, lse, do, causal, bq, bk,
                        segments=None, slot=0):
    """Pair-table (block-sparse) backward: two kernels over the shared
    tables — dq over the q-major order, dk/dv over the k-major order —
    so the backward traffic shrinks by exactly the forward's skip
    fraction."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    qh, kh, vh, doh, delta, lse3 = _bwd_residual_streams(
        q, k, v, out, do, lse)
    lengths = lengths.astype(jnp.int32)
    nq, nk = tq // bq, tk // bk
    tab_q, tab_k = _pair_tables(tq, tk, bq, bk, causal, slot)
    tab_q = jnp.asarray(tab_q)
    tab_k = jnp.asarray(tab_k)
    if segments is None:
        lo_q, hi_q = _length_windows(lengths, b, nq, bk)
        lo_k = jnp.zeros((b, nk), jnp.int32)
        hi_k = jnp.full((b, nk), nq - 1, jnp.int32)
    else:
        lo_q, hi_q = _segment_windows(segments, segments, bq, bk)
        lo_k, hi_k = _segment_windows(segments, segments, bk, bq)
    nh = h
    packed = segments is not None

    def q_idx(i, p, ln, lo_, hi_, tb):
        return (i, tb[0, p], 0)

    def kv_idx(i, p, ln, lo_, hi_, tb):
        j = tb[0, p]
        return (i, _win_clip(tb[1, p], lo_[i // nh, j],
                             hi_[i // nh, j], nk), 0)

    def row_idx(i, p, ln, lo_, hi_, tb):
        return (i, 0, tb[0, p])

    def sq_idx(i, p, ln, lo_, hi_, tb):
        return (i // nh, tb[0, p], 0)

    def sk_idx(i, p, ln, lo_, hi_, tb):
        j = tb[0, p]
        return (i // nh, _win_clip(tb[1, p], lo_[i // nh, j],
                                   hi_[i // nh, j], nk), 0)

    common = dict(
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )
    in_specs = [
        pl.BlockSpec((1, bq, d), q_idx),
        pl.BlockSpec((1, bk, d), kv_idx),
        pl.BlockSpec((1, bk, d), kv_idx),
        pl.BlockSpec((1, bq, d), q_idx),
        pl.BlockSpec((1, 1, bq), row_idx),
        pl.BlockSpec((1, 1, bq), row_idx),
    ]
    operands = [qh, kh, vh, doh, lse3, delta]
    if packed:
        seg3 = segments.astype(jnp.int32).reshape(b, tq, 1)
        in_specs += [pl.BlockSpec((1, bq, 1), sq_idx),
                     pl.BlockSpec((1, bk, 1), sk_idx)]
        operands += [seg3, seg3]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_pair_kernel, scale=scale,
                          causal=causal, block_q=bq, block_k=bk,
                          n_heads=h, packed=packed),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(b * h, int(tab_q.shape[1])),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((1, bq, d), q_idx)],
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32)],
        **common,
    )(lengths, lo_q, hi_q, tab_q, *operands)[0]

    # k-major order: q/do/lse/delta stream per pair (their q-block index
    # is the table's, window-clamped in packed mode); k/v/dk/dv are the
    # per-k-block residents
    def q_idx2(i, p, ln, lo_, hi_, tb):
        s_ = tb[1, p]
        return (i, _win_clip(tb[0, p], lo_[i // nh, s_],
                             hi_[i // nh, s_], nq), 0)

    def kv_idx2(i, p, ln, lo_, hi_, tb):
        return (i, tb[1, p], 0)

    def row_idx2(i, p, ln, lo_, hi_, tb):
        s_ = tb[1, p]
        return (i, 0, _win_clip(tb[0, p], lo_[i // nh, s_],
                                hi_[i // nh, s_], nq))

    def sq_idx2(i, p, ln, lo_, hi_, tb):
        s_ = tb[1, p]
        return (i // nh, _win_clip(tb[0, p], lo_[i // nh, s_],
                                   hi_[i // nh, s_], nq), 0)

    def sk_idx2(i, p, ln, lo_, hi_, tb):
        return (i // nh, tb[1, p], 0)

    in_specs2 = [
        pl.BlockSpec((1, bq, d), q_idx2),
        pl.BlockSpec((1, bk, d), kv_idx2),
        pl.BlockSpec((1, bk, d), kv_idx2),
        pl.BlockSpec((1, bq, d), q_idx2),
        pl.BlockSpec((1, 1, bq), row_idx2),
        pl.BlockSpec((1, 1, bq), row_idx2),
    ]
    operands2 = [qh, kh, vh, doh, lse3, delta]
    if packed:
        seg3 = segments.astype(jnp.int32).reshape(b, tq, 1)
        in_specs2 += [pl.BlockSpec((1, bq, 1), sq_idx2),
                      pl.BlockSpec((1, bk, 1), sk_idx2)]
        operands2 += [seg3, seg3]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_pair_kernel, scale=scale,
                          causal=causal, block_q=bq, block_k=bk,
                          n_heads=h, packed=packed),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(b * h, int(tab_k.shape[1])),
            in_specs=in_specs2,
            out_specs=[
                pl.BlockSpec((1, bk, d), kv_idx2),
                pl.BlockSpec((1, bk, d), kv_idx2),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
        ],
        **common,
    )(lengths, lo_k, hi_k, tab_k, *operands2)

    unpack_q = lambda a: a.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    unpack_k = lambda a: a.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    return (unpack_q(dq).astype(q.dtype), unpack_k(dk).astype(k.dtype),
            unpack_k(dv).astype(v.dtype))


def _bwd_dq_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, acc_s, *, scale, causal, block_q,
                   block_k, n_kblocks, n_heads):
    """Legacy grid (B·H, q_blocks, k_blocks), k innermost: accumulate
    dq for one q block while k/v stream through VMEM."""
    i_k = pl.program_id(2)
    kv_len = len_ref[pl.program_id(0) // n_heads]

    @pl.when(i_k == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    q_off = pl.program_id(1) * block_q
    k_off = i_k * block_k

    def _step():
        _p, ds, _q, kb, _do = _recompute_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off,
            k_off, kv_len, scale, causal, block_q, block_k)
        acc_s[:] = acc_s[:] + ds @ kb * scale

    pl.when(_bwd_live(q_off, k_off, kv_len, causal, block_q))(_step)

    @pl.when(i_k == n_kblocks - 1)
    def _flush():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_s, dv_s, *, scale,
                    causal, block_q, block_k, n_qblocks, n_heads):
    """Legacy grid (B·H, k_blocks, q_blocks), q innermost: accumulate
    dk/dv for one k block while q/do stream through VMEM."""
    i_q = pl.program_id(2)
    kv_len = len_ref[pl.program_id(0) // n_heads]

    @pl.when(i_q == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    k_off = pl.program_id(1) * block_k
    q_off = i_q * block_q

    def _step():
        p, ds, q, _kb, do = _recompute_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off,
            k_off, kv_len, scale, causal, block_q, block_k)
        dv_s[:] = dv_s[:] + p.T @ do
        dk_s[:] = dk_s[:] + ds.T @ q * scale

    pl.when(_bwd_live(q_off, k_off, kv_len, causal, block_q))(_step)

    @pl.when(i_q == n_qblocks - 1)
    def _flush():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _fa_backward_pallas(q, k, v, lengths, out, lse, do, causal, bq, bk):
    """Legacy blockwise backward (``--flash_block_sparse=false``):
    (dq, dk, dv) without a [T, T] score matrix in HBM, full grid."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    qh, kh, vh, doh, delta, lse3 = _bwd_residual_streams(
        q, k, v, out, do, lse)
    lengths = lengths.astype(jnp.int32)

    common = dict(
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_kblocks=tk // bk,
                          n_heads=h),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, tq // bq, tk // bk),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, j)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32)],
        **common,
    )(lengths, qh, kh, vh, doh, lse3, delta)[0]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_qblocks=tq // bq,
                          n_heads=h),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, tk // bk, tq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, s)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, s)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
        ],
        **common,
    )(lengths, qh, kh, vh, doh, lse3, delta)

    unpack_q = lambda a: a.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    unpack_k = lambda a: a.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    return (unpack_q(dq).astype(q.dtype), unpack_k(dk).astype(k.dtype),
            unpack_k(dv).astype(v.dtype))


def _dense_backward(q, k, v, lengths, out, lse, do, causal,
                    segments=None):
    """Dense einsum backward — the exact composition the kill switches
    and untileable shapes fall back to."""
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    of = out.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    s = _mask_scores(s, causal, lengths, segments)
    p = jnp.exp(s - lse[:, :, :, None])                 # softmax weights
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    # delta_i = Σ_d dO_i·O_i (the softmax-backward row term)
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, of)
    ds = p * (dp - delta[:, :, :, None])
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


def _fa_backward(q, k, v, lengths, out, lse, do, causal, block_q,
                 block_k, segments=None, slot=0):
    """Backward dispatch — mirrors :func:`_fa_forward` exactly (same
    flags, same tiling gate) so one compiled program's forward and
    backward always take matching paths."""
    tq, tk = q.shape[1], k.shape[1]
    bq = _choose_block(tq, block_q)
    bk = _choose_block(tk, block_k)
    if _flash_enabled() and _tiling_ok(tq, tk, bq, bk):
        if _block_sparse():
            return _fa_backward_sparse(q, k, v, lengths, out, lse, do,
                                       causal, bq, bk, segments, slot)
        if segments is None:
            return _fa_backward_pallas(q, k, v, lengths, out, lse, do,
                                       causal, bq, bk)
    return _dense_backward(q, k, v, lengths, out, lse, do, causal,
                           segments)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, lengths=None, causal: bool = False,
                    block_q: int = 512, block_k: int = 512):
    """softmax(q·kᵀ/√d)·v without materializing [T,T] scores in HBM.

    q, k, v: ``[B, T, H, D]``; returns ``[B, T, H, D]`` in q's dtype.
    ``lengths``: optional int32 [B] valid key lengths for padded batches
    — keys at or past the length are masked out of the softmax, and
    (block-sparse path) k/v blocks wholly past the length are neither
    DMA'd nor visited.
    """
    out, _lse = _fa_forward(q, k, v, lengths, causal, block_q, block_k)
    return out


def _fa_fwd_rule(q, k, v, lengths, causal, block_q, block_k):
    out, lse = _fa_forward(q, k, v, lengths, causal, block_q, block_k)
    return out, (q, k, v, lengths, out, lse)


def _fa_bwd_rule(causal, block_q, block_k, res, do):
    q, k, v, lengths, out, lse = res
    if lengths is None:
        lengths = jnp.full((q.shape[0],), k.shape[1], jnp.int32)
    dq, dk, dv = _fa_backward(q, k, v, lengths, out, lse, do, causal,
                              block_q, block_k)
    return dq, dk, dv, None


flash_attention.defvjp(_fa_fwd_rule, _fa_bwd_rule)


# ------------------------------------------------------ sequence packing
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention_packed(q, k, v, segments, causal: bool = False,
                           block_q: int = 512, block_k: int = 512,
                           slot: int = 0):
    """Packed (ragged-batch) attention: tokens attend only within
    their segment.

    q, k, v: ``[B, T_total, H, D]`` — mixed-length sequences share one
    packed token axis; ``segments``: int32 ``[B, T_total]`` per-token
    segment ids, **non-decreasing** over valid tokens with ``-1``
    marking padding (the packing contract — the dynamic block windows
    rely on it).  Padding tokens produce zero output and zero grads;
    cross-segment and padding blocks are neither DMA'd nor visited on
    the block-sparse path.  ``causal`` applies within segments (packed
    positions are globally ordered, so the global diagonal is the
    per-segment diagonal).  ``slot``: optional static slot width when
    the caller guarantees no segment crosses a slot boundary — pairs
    across slots leave the iteration space entirely (see
    :func:`_pair_tables`).
    """
    out, _lse = _fa_forward(q, k, v, None, causal, block_q, block_k,
                            segments=segments, slot=slot)
    return out


def _fa_packed_fwd_rule(q, k, v, segments, causal, block_q, block_k,
                        slot):
    out, lse = _fa_forward(q, k, v, None, causal, block_q, block_k,
                           segments=segments, slot=slot)
    return out, (q, k, v, segments, out, lse)


def _fa_packed_bwd_rule(causal, block_q, block_k, slot, res, do):
    q, k, v, segments, out, lse = res
    lengths = jnp.full((q.shape[0],), k.shape[1], jnp.int32)
    dq, dk, dv = _fa_backward(q, k, v, lengths, out, lse, do, causal,
                              block_q, block_k, segments=segments,
                              slot=slot)
    return dq, dk, dv, None


flash_attention_packed.defvjp(_fa_packed_fwd_rule, _fa_packed_bwd_rule)


def segments_from_lengths(lengths, batch: int, t: int):
    """Per-token segment ids for a padded ``[B, T]`` batch flattened to
    one packed ``[1, B·T]`` row: valid tokens of row i get id ``i``,
    padding gets ``-1`` (ids non-decreasing — the packing contract)."""
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]            # [1, T]
    row = jnp.arange(batch, dtype=jnp.int32)[:, None]        # [B, 1]
    seg = jnp.where(pos < lengths[:, None], row, -1)         # [B, T]
    return seg.reshape(1, batch * t)


# --------------------------------------------------- paged-KV decode
def _decode_kernel(len_ref, used_ref, pidx_ref, q_ref, k_ref, v_ref,
                   o_ref, m_s, l_s, acc_s, *, scale, page, t_q,
                   n_heads, n_pages_max):
    """Grid (B·H, max_pages_per_row): one query tile (small Tq — the
    decode step's new tokens) attends its row's paged KV cache, one
    physical page per grid step, via the scalar-prefetched page table.
    Pages wholly past the row's length are clamped to the last used
    page (no DMA when the index repeats) and compute-skipped."""
    i = pl.program_id(0)
    p = pl.program_id(1)
    b = i // n_heads
    kv_len = len_ref[b]

    @pl.when(p == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale         # [tq, D]
        kb = k_ref[0]                                    # [page, D]
        vb = v_ref[0]
        s = q @ kb.astype(jnp.float32).T                 # [tq, page]
        ki = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (t_q, page), 1)
        # query r sits at absolute position kv_len - t_q + r: it may
        # attend every key at or before itself (ragged causal tail)
        qpos = kv_len - t_q + jax.lax.broadcasted_iota(
            jnp.int32, (t_q, page), 0)
        valid = ki <= qpos
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_s[:]
        l_prev = l_s[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # fully-masked query rows (0 < length < Tq: the leading rows of
        # a speculative/chunked tile sit at negative positions) have
        # m_new = NEG_INF; clamp the exponent base so exp(s − m) under-
        # flows to 0 instead of exp(−inf − (−inf)) = 1 leaking V mass —
        # same guard as _fa_pair_kernel, flush's l_safe emits zeros
        m_base = jnp.maximum(m_new, NEG_INF / 2)
        pexp = jnp.exp(s - m_base)
        alpha = jnp.exp(m_prev - m_base)
        # Pallas VMEM scratch refs are the kernel's mutable-by-design
        # accumulator API (this kernel is jit-reachable directly, not
        # through a custom_vjp wrapper, so PT-TRACE sees the writes)
        m_s[:] = m_new                          # ptpu: lint-ok[PT-TRACE]
        # ptpu: lint-ok[PT-TRACE]
        l_s[:] = l_prev * alpha + pexp.sum(axis=-1, keepdims=True)
        # ptpu: lint-ok[PT-TRACE]
        acc_s[:] = acc_s[:] * alpha + pexp @ vb.astype(jnp.float32)

    pl.when(p * page < kv_len)(_step)

    @pl.when(p == n_pages_max - 1)
    def _flush():
        l_safe = jnp.where(l_s[:] == 0.0, 1.0, l_s[:])
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_indices, lengths):
    """Decode-step attention over a block-paged KV cache.

    - ``q``: ``[B, Tq, H, D]`` — the row's newest ``Tq`` tokens (Tq is
      small: 1 for plain decode, >1 for speculative/chunked steps);
    - ``k_pages`` / ``v_pages``: ``[P, page_size, H, D]`` physical
      page pools shared by every row;
    - ``page_indices``: int32 ``[B, max_pages]`` per-row page table
      (entries past the row's used pages are ignored);
    - ``lengths``: int32 ``[B]`` valid cached tokens per row — the
      query tile occupies positions ``length - Tq … length - 1``, so
      the current step's K/V must already be written to the pages.

    Returns ``[B, Tq, H, D]``.  Inference-only (no custom VJP): this is
    the serving decode primitive (ROADMAP item 1) exercised standalone.
    """
    b, t_q, h, d = q.shape
    n_pages, page, hp, dp = k_pages.shape
    enforce(hp == h and dp == d,
            f"page pool heads/dim {hp}/{dp} != query {h}/{d}")
    enforce(v_pages.shape == k_pages.shape,
            "k_pages and v_pages shapes differ: "
            f"{k_pages.shape} vs {v_pages.shape}")
    enforce(page_indices.shape[0] == b and lengths.shape == (b,),
            f"page_indices/lengths batch mismatch: "
            f"{page_indices.shape}/{lengths.shape} vs B={b}")
    n_pages_max = page_indices.shape[1]
    record_attention_dispatch("decode")
    scale = 1.0 / np.sqrt(d)
    lengths = lengths.astype(jnp.int32)
    # pages become rows of one [H·P, page, D] pool so a single index
    # computed from (head, page table) addresses a (page, D) block
    kp = k_pages.transpose(2, 0, 1, 3).reshape(h * n_pages, page, d)
    vp = v_pages.transpose(2, 0, 1, 3).reshape(h * n_pages, page, d)
    qh = _heads_first(q, b, t_q, h, d)
    used = jnp.maximum((lengths + page - 1) // page, 1)      # [B]
    nh = h

    def kv_idx(i, p, ln, us, pi):
        bb = i // nh
        slot = jnp.minimum(p, us[bb] - 1)
        return ((i % nh) * n_pages + pi[bb, slot], 0, 0)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, page=page,
                          t_q=t_q, n_heads=h,
                          n_pages_max=n_pages_max),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b * h, n_pages_max),
            in_specs=[
                pl.BlockSpec((1, t_q, d),
                             lambda i, p, ln, us, pi: (i, 0, 0)),
                pl.BlockSpec((1, page, d), kv_idx),
                pl.BlockSpec((1, page, d), kv_idx),
            ],
            out_specs=[
                pl.BlockSpec((1, t_q, d),
                             lambda i, p, ln, us, pi: (i, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((t_q, 1), jnp.float32),
                pltpu.VMEM((t_q, 1), jnp.float32),
                pltpu.VMEM((t_q, d), jnp.float32),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths, used, page_indices.astype(jnp.int32), qh, kp, vp)[0]
    return out.reshape(b, h, t_q, d).transpose(0, 2, 1, 3)


def paged_decode_reference(q, k_pages, v_pages, page_indices, lengths):
    """Dense one-step reference for :func:`paged_decode_attention`
    (tests; also the numerics contract): gather each row's pages into
    a contiguous [B, max_pages·page, H, D] cache and run the dense
    masked attention."""
    b, t_q, h, d = q.shape
    page = k_pages.shape[1]
    n_max = page_indices.shape[1]
    gk = k_pages[page_indices.reshape(-1)].reshape(
        b, n_max * page, h, d)
    gv = v_pages[page_indices.reshape(-1)].reshape(
        b, n_max * page, h, d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   gk.astype(jnp.float32)) / np.sqrt(d)
    ki = jnp.arange(n_max * page, dtype=jnp.int32)
    qpos = (lengths[:, None] - t_q
            + jnp.arange(t_q, dtype=jnp.int32)[None, :])     # [B, Tq]
    valid = ki[None, None, :] <= qpos[:, :, None]            # [B,Tq,K]
    s = jnp.where(valid[:, None, :, :], s, NEG_INF)
    m = jnp.maximum(s.max(axis=-1, keepdims=True), NEG_INF / 2)
    p = jnp.exp(s - m)
    l = p.sum(axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, gv.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_kv_write(k_pages, v_pages, k_new, v_new, page_indices,
                   start_positions, counts):
    """Scatter new K/V tokens into their rows' physical pages — the
    pool-maintenance half of the paged-decode contract ("the current
    step's K/V must already be written to the pages").

    - ``k_new`` / ``v_new``: ``[B, Tn, H, D]`` — each row's newest
      ``Tn`` tokens (``Tn`` = padded prompt length at prefill, 1 per
      decode step);
    - ``page_indices``: int32 ``[B, max_pages]`` per-row page table;
    - ``start_positions``: int32 ``[B]`` absolute position of each
      row's FIRST new token (token ``j`` of row ``b`` lands at
      ``start_positions[b] + j``);
    - ``counts``: int32 ``[B]`` valid new tokens per row — tokens at or
      past the count (prompt padding; inactive batch slots via
      ``counts == 0``) are dropped, not written.

    Returns the updated ``(k_pages, v_pages)``.  Pure jnp scatter (one
    ``.at[].set`` per pool, out-of-range destinations dropped) so XLA
    aliases the update in place when the caller donates the pools.
    """
    n_pages, page, h, d = k_pages.shape
    b, t_n = k_new.shape[0], k_new.shape[1]
    enforce(v_new.shape == k_new.shape,
            f"k_new/v_new shapes differ: {k_new.shape} vs {v_new.shape}")
    enforce(page_indices.shape[0] == b
            and start_positions.shape == (b,) and counts.shape == (b,),
            f"paged_kv_write batch mismatch: page_indices "
            f"{page_indices.shape}, start_positions "
            f"{start_positions.shape}, counts {counts.shape} vs B={b}")
    pos = start_positions.astype(jnp.int32)[:, None] \
        + jnp.arange(t_n, dtype=jnp.int32)[None, :]          # [B, Tn]
    slot = jnp.clip(pos // page, 0, page_indices.shape[1] - 1)
    phys = jnp.take_along_axis(page_indices.astype(jnp.int32), slot,
                               axis=1)                       # [B, Tn]
    dest = phys * page + pos % page
    valid = (jnp.arange(t_n, dtype=jnp.int32)[None, :]
             < counts.astype(jnp.int32)[:, None]) & (pos >= 0)
    # invalid tokens aim past the pool; mode="drop" discards them
    dest = jnp.where(valid, dest, n_pages * page).reshape(-1)
    kf = k_pages.reshape(n_pages * page, h, d).at[dest].set(
        k_new.reshape(b * t_n, h, d).astype(k_pages.dtype), mode="drop")
    vf = v_pages.reshape(n_pages * page, h, d).at[dest].set(
        v_new.reshape(b * t_n, h, d).astype(v_pages.dtype), mode="drop")
    return (kf.reshape(n_pages, page, h, d),
            vf.reshape(n_pages, page, h, d))

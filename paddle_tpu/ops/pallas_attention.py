"""Flash attention as a Pallas TPU kernel.

The reference hand-wrote its hot kernels in CUDA (``hl_lstm``,
``hl_top_k``); the TPU analogue of that tier is Pallas.  This module
implements blockwise (flash) attention: k/v stream through VMEM one
block per grid step with an online softmax (running max / normalizer
kept in VMEM scratch), so the [T, T] score matrix never exists in HBM
and VMEM holds only O(block²+block·D) — sequence length is bounded by
HBM for q/k/v themselves, not by attention intermediates.

Layout matches :mod:`paddle_tpu.parallel.ring_attention`'s
``full_attention``: q, k, v are ``[B, T, H, D]``; output ``[B, T, H, D]``.

Backward: custom VJP with the standard recomputation formulation — the
saved residuals are (q, k, v, out, per-row logsumexp).  When the shapes
tile, backward runs as TWO Pallas kernels (a dq pass streaming k/v and
a dk/dv pass streaming q/do, each rebuilding p blockwise from the saved
logsumexp) so the [T, T] score matrix never exists in HBM in either
direction; otherwise it falls back to dense einsums.

On non-TPU backends the kernel runs in Pallas interpret mode so the CPU
test mesh exercises the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# jax renamed TPUCompilerParams → CompilerParams (0.5.x); resolve once
# here so every Pallas module runs interpret-mode CI on either version.
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _choose_block(t: int, want: int) -> int:
    b = min(want, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fa_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s,
               acc_s, *, scale, causal, block_q, block_k, n_kblocks,
               n_heads):
    """Grid (B·H, q_blocks, k_blocks); k innermost so the scratch
    accumulators carry the online softmax across k steps.  ``len_ref``
    is the scalar-prefetched int32 [B] of valid key lengths (padded
    batches): keys at or past the length are masked to −inf, and k
    blocks entirely inside the padding are skipped outright."""
    i_k = pl.program_id(2)
    kv_len = len_ref[pl.program_id(0) // n_heads]

    @pl.when(i_k == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    q_off = pl.program_id(1) * block_q
    k_off = i_k * block_k

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, D]
        kb = k_ref[0]                                   # [bk, D]
        vb = v_ref[0]
        s = q @ kb.astype(jnp.float32).T                # [bq, bk]
        ki = k_off + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = ki < kv_len
        if causal:
            qi = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, qi >= ki)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_s[:]
        l_prev = l_s[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_s[:] = m_new
        l_s[:] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + p @ vb.astype(jnp.float32)

    # skip k blocks with no valid key: fully above the causal diagonal
    # or fully inside the padding
    live = k_off < kv_len
    if causal:
        live = jnp.logical_and(live, k_off <= q_off + block_q - 1)
    pl.when(live)(_step)

    @pl.when(i_k == n_kblocks - 1)
    def _flush():
        # guard fully-masked rows (query past a zero-length sequence):
        # l = 0 → emit 0 not NaN, and clamp m away from NEG_INF so the
        # backward's p = exp(s − lse) underflows to 0 instead of
        # exp(NEG_INF − NEG_INF) = 1 leaking gradients into padding
        l_safe = jnp.where(l_s[:] == 0.0, 1.0, l_s[:])
        m_safe = jnp.maximum(m_s[:], NEG_INF / 2)
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        # lse block is (1, 8, bq) purely for TPU tiling (last two dims
        # must be (8k, 128k) or match the array); row 0 carries the data
        lse_ref[0] = jnp.broadcast_to(
            (m_safe + jnp.log(l_safe))[:, 0][None, :], (8, block_q))


def _tiling_ok(tq: int, tk: int, bq: int, bk: int) -> bool:
    """Mosaic block constraints: the lse block's last dim (bq) must be a
    multiple of 128 or equal Tq; the k/v block's penultimate dim (bk)
    must be a multiple of 8 or equal Tk.  Checked on EVERY backend so
    interpret-mode tests exercise the same dispatch as real TPU."""
    ok_q = bq % 128 == 0 or bq == tq
    ok_k = bk % 8 == 0 or bk == tk
    return ok_q and ok_k


def _mask_scores(s, causal, lengths):
    """Apply causal and key-padding masks to [B, H, Tq, Tk] scores."""
    tq, tk = s.shape[-2], s.shape[-1]
    if causal:
        s = jnp.where(jnp.arange(tq)[None, None, :, None]
                      >= jnp.arange(tk)[None, None, None, :], s, NEG_INF)
    if lengths is not None:
        valid = jnp.arange(tk)[None, :] < lengths[:, None]   # [B, Tk]
        s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    return s


def _dense_forward(q, k, v, lengths, causal):
    """Fallback for shapes the kernel can't tile: plain XLA attention,
    same (out, lse) contract so the shared backward rule applies."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = _mask_scores(s, causal, lengths)
    m = s.max(axis=-1)
    # fully-masked rows (query past a zero-length sequence): emit 0
    m_safe = jnp.maximum(m, NEG_INF / 2)
    l = jnp.exp(s - m_safe[..., None]).sum(axis=-1)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = m_safe + jnp.log(l_safe)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _fa_forward(q, k, v, lengths, causal, block_q, block_k):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if causal:
        # a causal mask is only meaningful on a shared timeline
        assert tq == tk, f"causal attention needs Tq == Tk, got {tq}/{tk}"
    bq = _choose_block(tq, block_q)
    bk = _choose_block(tk, block_k)
    if lengths is None:
        lengths = jnp.full((b,), tk, jnp.int32)
    if not _tiling_ok(tq, tk, bq, bk):
        return _dense_forward(q, k, v, lengths, causal)
    scale = 1.0 / np.sqrt(d)
    # [B, T, H, D] → [B*H, T, D] so one grid row owns one head
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    n_kblocks = tk // bk
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk,
                               n_kblocks=n_kblocks, n_heads=h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, tq // bq, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
            pl.BlockSpec((1, 8, bq), lambda i, j, s, *_: (i, 0, j)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running normalizer
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, tq), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), qh, kh, vh)
    out = out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    lse = lse[:, 0, :].reshape(b, h, tq)
    return out, lse


# ------------------------------------------------------ backward kernels
def _recompute_block(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     q_off, k_off, kv_len, scale, causal, block_q,
                     block_k):
    """Rebuild one (q-block, k-block) softmax tile from the saved
    logsumexp and return (p, ds, q, kb, do) in f32 — shared by the dq
    and dk/dv kernels so their masking/scaling can never diverge."""
    q = q_ref[0].astype(jnp.float32)
    kb = k_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)[:, None]         # [bq, 1]
    delta = delta_ref[0, 0].astype(jnp.float32)[:, None]
    s = (q @ kb.T) * scale
    ki = k_off + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = ki < kv_len
    if causal:
        qi = q_off + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = jnp.logical_and(valid, qi >= ki)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    ds = p * (do @ vb.T - delta)
    return p, ds, q, kb, do


def _bwd_live(q_off, k_off, kv_len, causal, block_q):
    """Skip condition shared by both backward kernels: a block with no
    valid key (padding tail or fully above the causal diagonal)."""
    live = k_off < kv_len
    if causal:
        live = jnp.logical_and(live, k_off <= q_off + block_q - 1)
    return live


def _bwd_dq_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, acc_s, *, scale, causal, block_q,
                   block_k, n_kblocks, n_heads):
    """Grid (B·H, q_blocks, k_blocks), k innermost: accumulate dq for
    one q block while k/v stream through VMEM."""
    i_k = pl.program_id(2)
    kv_len = len_ref[pl.program_id(0) // n_heads]

    @pl.when(i_k == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    q_off = pl.program_id(1) * block_q
    k_off = i_k * block_k

    def _step():
        _p, ds, _q, kb, _do = _recompute_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off,
            k_off, kv_len, scale, causal, block_q, block_k)
        acc_s[:] = acc_s[:] + ds @ kb * scale

    pl.when(_bwd_live(q_off, k_off, kv_len, causal, block_q))(_step)

    @pl.when(i_k == n_kblocks - 1)
    def _flush():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_s, dv_s, *, scale,
                    causal, block_q, block_k, n_qblocks, n_heads):
    """Grid (B·H, k_blocks, q_blocks), q innermost: accumulate dk/dv
    for one k block while q/do stream through VMEM."""
    i_q = pl.program_id(2)
    kv_len = len_ref[pl.program_id(0) // n_heads]

    @pl.when(i_q == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    k_off = pl.program_id(1) * block_k
    q_off = i_q * block_q

    def _step():
        p, ds, q, _kb, do = _recompute_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off,
            k_off, kv_len, scale, causal, block_q, block_k)
        dv_s[:] = dv_s[:] + p.T @ do
        dk_s[:] = dk_s[:] + ds.T @ q * scale

    pl.when(_bwd_live(q_off, k_off, kv_len, causal, block_q))(_step)

    @pl.when(i_q == n_qblocks - 1)
    def _flush():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _fa_backward_pallas(q, k, v, lengths, out, lse, do, causal, bq, bk):
    """Blockwise backward: (dq, dk, dv) without a [T, T] score matrix
    in HBM.  q/do layouts as in forward ([B, T, H, D])."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    doh = do.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    # delta_i = Σ_d dO_i·O_i (softmax-backward row term), [BH, 1, T]
    delta = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                       out.astype(jnp.float32)).reshape(b * h, 1, tq)
    lse3 = lse.reshape(b * h, 1, tq)
    if lengths is None:
        lengths = jnp.full((b,), tk, jnp.int32)
    lengths = lengths.astype(jnp.int32)

    common = dict(
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_kblocks=tk // bk,
                          n_heads=h),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, tq // bq, tk // bk),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, j)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32)],
        **common,
    )(lengths, qh, kh, vh, doh, lse3, delta)[0]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_qblocks=tq // bq,
                          n_heads=h),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, tk // bk, tq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bq, d), lambda i, j, s, *_: (i, s, 0)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, s)),
                pl.BlockSpec((1, 1, bq), lambda i, j, s, *_: (i, 0, s)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
                pl.BlockSpec((1, bk, d), lambda i, j, s, *_: (i, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
        ],
        **common,
    )(lengths, qh, kh, vh, doh, lse3, delta)

    unpack_q = lambda a: a.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    unpack_k = lambda a: a.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    return (unpack_q(dq).astype(q.dtype), unpack_k(dk).astype(k.dtype),
            unpack_k(dv).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, lengths=None, causal: bool = False,
                    block_q: int = 512, block_k: int = 512):
    """softmax(q·kᵀ/√d)·v without materializing [T,T] scores in HBM.

    q, k, v: ``[B, T, H, D]``; returns ``[B, T, H, D]`` in q's dtype.
    ``lengths``: optional int32 [B] valid key lengths for padded batches
    — keys at or past the length are masked out of the softmax.
    """
    out, _lse = _fa_forward(q, k, v, lengths, causal, block_q, block_k)
    return out


def _fa_fwd_rule(q, k, v, lengths, causal, block_q, block_k):
    out, lse = _fa_forward(q, k, v, lengths, causal, block_q, block_k)
    return out, (q, k, v, lengths, out, lse)


def _fa_bwd_rule(causal, block_q, block_k, res, do):
    q, k, v, lengths, out, lse = res
    d = q.shape[-1]
    tq, tk = q.shape[1], k.shape[1]
    bq = _choose_block(tq, block_q)
    bk = _choose_block(tk, block_k)
    if _tiling_ok(tq, tk, bq, bk):
        dq, dk, dv = _fa_backward_pallas(q, k, v, lengths, out, lse, do,
                                         causal, bq, bk)
        return dq, dk, dv, None
    scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    of = out.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    s = _mask_scores(s, causal, lengths)
    p = jnp.exp(s - lse[:, :, :, None])                 # softmax weights
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    # delta_i = Σ_d dO_i·O_i (the softmax-backward row term)
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, of)
    ds = p * (dp - delta[:, :, :, None])
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None)


flash_attention.defvjp(_fa_fwd_rule, _fa_bwd_rule)

"""Linear-chain CRF and CTC ops.

Replaces ``LinearChainCRF`` (+ ``CRFLayer``, ``CRFDecodingLayer``),
``linear_chain_crf_op.cc``, and the warp-ctc wrapper (``WarpCTCLayer``,
``hl_warpctc_wrap.cc``, ``LinearChainCTC``).

TPU-first: forward algorithm and Viterbi are ``lax.scan`` over time on the
padded layout with log-space arithmetic (reference works per-sequence on CPU
with explicit loops).  CTC uses optax's XLA-native implementation instead of
an external warp-ctc binary.

Transition-parameter layout follows the reference (``LinearChainCRF.cpp``):
``w[0] = a`` (start), ``w[1] = b`` (end), ``w[2:] = T[tag_from, tag_to]``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.sequence import SequenceBatch
from .registry import register_op


def _split_w(w: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return w[0], w[1], w[2:]


@register_op("linear_chain_crf")
def crf_nll(emissions: SequenceBatch, labels: SequenceBatch, w: jax.Array
            ) -> jax.Array:
    """Negative log-likelihood per sequence (``CRFLayer::forward``).

    emissions.data: [B, T, N] unnormalized scores; labels.data: [B, T] int;
    w: [N+2, N] (start row, end row, transitions).
    """
    a, b, trans = _split_w(w)
    x = emissions.data.astype(jnp.float32)
    ids = labels.data.astype(jnp.int32)
    mask = emissions.mask(jnp.float32)  # [B, T]
    B, T, N = x.shape
    if ids.shape[1] < T:  # label buffer may be bucketed shorter
        ids = jnp.pad(ids, [(0, 0), (0, T - ids.shape[1])])
    else:
        ids = ids[:, :T]

    # --- log partition via forward algorithm
    alpha0 = a[None, :] + x[:, 0]  # [B, N]

    def fwd(alpha, inp):
        x_t, m_t = inp
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, from, to]
        new = jax.nn.logsumexp(scores, axis=1) + x_t
        m = m_t[:, None]
        return m * new + (1 - m) * alpha, None

    alpha, _ = lax.scan(
        fwd, alpha0,
        (jnp.moveaxis(x[:, 1:], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0)))
    logz = jax.nn.logsumexp(alpha + b[None, :], axis=-1)

    # --- gold path score
    first_emit = jnp.take_along_axis(x[:, 0], ids[:, :1], axis=-1)[:, 0]
    gold = a[ids[:, 0]] + first_emit

    def gold_step(carry, inp):
        score, prev = carry
        x_t, y_t, m_t = inp
        emit = jnp.take_along_axis(x_t, y_t[:, None], axis=-1)[:, 0]
        tr = trans[prev, y_t]
        score = score + m_t * (emit + tr)
        prev = jnp.where(m_t > 0, y_t, prev)
        return (score, prev), None

    (gold, last), _ = lax.scan(
        gold_step, (gold, ids[:, 0]),
        (jnp.moveaxis(x[:, 1:], 1, 0), jnp.moveaxis(ids[:, 1:], 1, 0),
         jnp.moveaxis(mask[:, 1:], 1, 0)))
    gold = gold + b[last]
    return logz - gold


@register_op("crf_decoding")
def crf_decode(emissions: SequenceBatch, w: jax.Array) -> SequenceBatch:
    """Viterbi decode (``CRFDecodingLayer`` / ``LinearChainCRF::decode``)
    → SequenceBatch of int32 best tags [B, T]."""
    a, b, trans = _split_w(w)
    x = emissions.data.astype(jnp.float32)
    mask = emissions.mask(jnp.float32)
    B, T, N = x.shape
    alpha0 = a[None, :] + x[:, 0]

    def vit(alpha, inp):
        x_t, m_t = inp
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        new = jnp.max(scores, axis=1) + x_t
        m = m_t[:, None]
        alpha_new = m * new + (1 - m) * alpha
        # for masked steps backpointer is identity
        ident = jnp.broadcast_to(jnp.arange(N)[None, :], (B, N))
        bp = jnp.where(m_t[:, None] > 0, best_prev, ident)
        return alpha_new, bp

    alpha, bps = lax.scan(
        vit, alpha0,
        (jnp.moveaxis(x[:, 1:], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0)))
    last_tag = jnp.argmax(alpha + b[None, :], axis=-1)  # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=-1)[:, 0]
        return prev, tag

    first_tag, tags_rev = lax.scan(back, last_tag, bps[::-1])
    # tags_rev = [tag_{T-1} ... tag_1]; the final carry is tag_0
    tags = jnp.concatenate(
        [first_tag[:, None], tags_rev[::-1].transpose(1, 0)], axis=1)  # [B, T]
    return SequenceBatch(data=tags.astype(jnp.int32), length=emissions.length)


@register_op("warpctc", "ctc")
def ctc_loss(logits: SequenceBatch, labels: SequenceBatch,
             blank: int = 0, norm_by_times: bool = False) -> jax.Array:
    """CTC loss per sequence (``WarpCTCLayer``/``CTCLayer``).

    logits.data: [B, T, C] unnormalized; labels.data: [B, L] int.
    Uses optax's XLA-native CTC (log-semiring dynamic program) — the
    TPU replacement for the warp-ctc CUDA dependency.
    """
    import optax

    logit_pad = 1.0 - logits.mask(jnp.float32)
    label_pad = 1.0 - labels.mask(jnp.float32)
    per_seq = optax.ctc_loss(
        logits.data.astype(jnp.float32), logit_pad,
        labels.data.astype(jnp.int32), label_pad, blank_id=blank)
    if norm_by_times:
        per_seq = per_seq / jnp.maximum(logits.length.astype(jnp.float32), 1.0)
    return per_seq

"""Loss / cost ops.

Union of the reference's cost layers (``paddle/gserver/layers/CostLayer.cpp``:
cross-entropy, multi-class CE + selfnorm, huber, rank, lambda-rank, smooth-l1,
sum-of-squares, multi-binary-label CE) and loss ops
(``paddle/operators/cross_entropy_op.cc``, ``softmax_with_cross_entropy``,
``sigmoid_cross_entropy_with_logits``, ``smooth_l1_loss``, ``huber_loss``,
``modified_huber_loss``, ``rank_loss``, ``margin_rank_loss``,
``squared_l2_distance``, ``squared_l2_norm``, ``l1_norm``).

All return **per-example** losses [B] (or [B,1]); reduction to scalar cost is
the trainer's job (matching ``Argument::sum`` over the cost layer output).
Numerically-stable log-softmax formulations are used instead of the
reference's explicit softmax-then-log, for bf16 safety on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("cross_entropy")
def cross_entropy(p, label, soft_label: bool = False, eps: float = 1e-8):
    """CE on probabilities (reference ``cross_entropy_op`` semantics).

    p: [B, C] probabilities; label: [B] int ids or [B, C] soft labels.
    """
    logp = jnp.log(jnp.clip(p.astype(jnp.float32), eps, 1.0))
    if soft_label:
        return -jnp.sum(label * logp, axis=-1)
    return -jnp.take_along_axis(logp, label.reshape(-1, 1).astype(jnp.int32), axis=-1)[:, 0]


@register_op("softmax_with_cross_entropy", "classification_cost")
def softmax_with_cross_entropy(logits, label, soft_label: bool = False):
    """Fused stable log-softmax CE (``softmax_with_cross_entropy_op.cc``)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    if soft_label:
        return -jnp.sum(label * logp, axis=-1)
    return -jnp.take_along_axis(logp, label.reshape(-1, 1).astype(jnp.int32), axis=-1)[:, 0]


@jax.custom_vjp
def softmax_ce_fused(logits, label):
    """Hard-label softmax CE from LOGITS with a hand-fused backward.

    Per-row loss [N] from logits [N, V].  The custom VJP keeps the
    gradient to its textbook single pass — ``dz = (softmax(z) − onehot)
    · dĉ`` — recomputing softmax in-register from the saved bf16 logits
    and writing dz straight back in the logits dtype.  Autodiff through
    the probability-space CE (gather → clip → log) instead materializes
    several full-vocabulary fp32 intermediates (scatter-add of 1/p,
    softmax-backward divide chains) — measured ~20% of the seq2seq
    benchmark step at V=30k before this path existed.
    """
    ce, _ = _softmax_ce_fwd(logits, label)
    return ce


def _softmax_ce_fwd(logits, label):
    """Works on any leading shape: logits [..., V], label [...] ints;
    no flattening — a reshape here forces a full-tensor relayout copy
    of the [B, T, V] decoder logits on TPU (measured)."""
    z = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(z, axis=-1)          # [...]
    lab = label.astype(jnp.int32)[..., None]
    gold = jnp.take_along_axis(z, lab, axis=-1)[..., 0]    # [...]
    return lse - gold, (logits, lab, lse)


def _softmax_ce_bwd(res, dce):
    logits, lab, lse = res
    # p computed in-register from the saved logits; one read + one write
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                       logits.ndim - 1) == lab)
    dz = (p - onehot.astype(jnp.float32)) * dce[..., None]
    return dz.astype(logits.dtype), None


softmax_ce_fused.defvjp(_softmax_ce_fwd, _softmax_ce_bwd)


@register_op("multi_binary_label_cross_entropy")
def multi_binary_label_cross_entropy(p, labels, eps: float = 1e-8):
    """CE with multiple binary labels per example (``CostLayer.cpp``
    MultiBinaryLabelCrossEntropy): labels is dense [B, C] 0/1."""
    p = jnp.clip(p, eps, 1.0 - eps)
    return -jnp.sum(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p), axis=-1)


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(x, label):
    """Stable elementwise sigmoid CE (``sigmoid_cross_entropy_with_logits_op``)."""
    return jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))


@register_op("square_error", "sum_of_squares", "mse_cost")
def square_error(x, label):
    """Sum-of-squares cost (``SumOfSquaresCostLayer``): 0.5 * ||x - y||^2."""
    d = x - label
    return 0.5 * jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=-1)


@register_op("squared_l2_distance")
def squared_l2_distance(x, y):
    d = (x - y).reshape(x.shape[0], -1)
    return jnp.sum(jnp.square(d), axis=-1)


@register_op("squared_l2_norm")
def squared_l2_norm(x):
    return jnp.sum(jnp.square(x))


@register_op("l1_norm")
def l1_norm(x):
    return jnp.sum(jnp.abs(x))


@register_op("smooth_l1_loss", "smooth_l1")
def smooth_l1_loss(x, y, sigma: float = 1.0):
    """Smooth-L1 (``smooth_l1_loss_op.cc``): sigma-scaled Huber, summed per row."""
    s2 = sigma * sigma
    d = jnp.abs(x - y)
    per = jnp.where(d < 1.0 / s2, 0.5 * s2 * jnp.square(d), d - 0.5 / s2)
    return jnp.sum(per.reshape(per.shape[0], -1), axis=-1)


@register_op("huber_loss", "huber_regression_cost")
def huber_loss(x, y, delta: float = 1.0):
    d = jnp.abs(y - x)
    per = jnp.where(d <= delta, 0.5 * jnp.square(d), delta * (d - 0.5 * delta))
    return jnp.sum(per.reshape(per.shape[0], -1), axis=-1)


@register_op("huber_classification_cost")
def huber_classification_cost(x, label):
    """Huber two-class cost (``HuberTwoClassification``): labels {0,1}→{-1,1}."""
    y = 2.0 * label.reshape(-1).astype(x.dtype) - 1.0
    a = x.reshape(-1) * y
    return jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))


@register_op("modified_huber_loss")
def modified_huber_loss(x, label):
    y = 2.0 * label.reshape(-1).astype(x.dtype) - 1.0
    a = x.reshape(-1) * y
    return jnp.where(a < -1.0, -4.0 * a, jnp.square(jnp.maximum(0.0, 1.0 - a)))


@register_op("rank_loss", "rank_cost")
def rank_loss(left, right, label):
    """Pairwise rank cost (``RankingCost``, ``rank_loss_op.cc``):
    CE of sigmoid(left-right) against label in [0,1]."""
    o = left - right
    lab = label.astype(o.dtype).reshape(o.shape)
    # output keeps Left's shape ([B,1]), as rank_loss_op InferShape does
    return jnp.maximum(o, 0) - o * lab + jnp.log1p(jnp.exp(-jnp.abs(o)))


@register_op("margin_rank_loss")
def margin_rank_loss(x1, x2, label, margin: float = 0.0):
    """max(0, -label*(x1-x2) + margin) (``margin_rank_loss_op.cc``);
    output keeps X1's shape ([B,1]) per the op's InferShape."""
    o = x1 - x2
    return jnp.maximum(0.0, -label.astype(o.dtype).reshape(o.shape) * o
                       + margin)


@register_op("lambda_cost")
def lambda_cost(scores, gains, mask, ndcg_num: int = 5):
    """LambdaRank cost over one padded query list (``LambdaCost`` layer).

    scores/gains/mask: [B, L] padded lists.  Returns [B] pseudo-cost whose
    gradient is the NDCG-weighted pairwise lambda, computed per list.
    """
    def one_list(s, g, m):
        valid = m > 0
        # ideal DCG from top-ndcg_num gains
        order = jnp.argsort(jnp.where(valid, -g, jnp.inf))
        sorted_g = g[order]
        pos = jnp.arange(g.shape[0])
        disc = 1.0 / jnp.log2(pos + 2.0)
        take = pos < ndcg_num
        max_dcg = jnp.sum(jnp.where(take, (2.0 ** sorted_g - 1.0) * disc, 0.0))
        inv_max = jnp.where(max_dcg > 0, 1.0 / max_dcg, 0.0)
        sdiff = s[:, None] - s[None, :]
        pair = (g[:, None] > g[None, :]) & valid[:, None] & valid[None, :]
        dg = (2.0 ** g[:, None] - 2.0 ** g[None, :]) * inv_max
        # surrogate whose d/ds matches lambda = |dNDCG| * sigmoid'(sdiff)
        surrogate = jnp.abs(dg) * jnp.log1p(jnp.exp(-sdiff))
        return jnp.sum(jnp.where(pair, surrogate, 0.0))

    return jax.vmap(one_list)(scores, gains, mask)


@register_op("cross_entropy_over_beam")
def cross_entropy_over_beam(beam_scores, gold_in_beam_mask):
    """CE over per-step beam candidates (``CrossEntropyOverBeam`` layer):
    beam_scores [B, K] candidate scores, gold mask [B, K] one-hot-ish."""
    logp = jax.nn.log_softmax(beam_scores, axis=-1)
    return -jnp.sum(gold_in_beam_mask * logp, axis=-1)

"""The op registry.

Replaces the reference's ``OpRegistry``/``REGISTER_OP`` machinery
(``paddle/framework/op_registry.h:150-217``) and the typed-function registry
(``paddle/function/Function.h:205``).  An op here is a **pure jax function**;
there is exactly one implementation per op (XLA compiles it for CPU or TPU),
so the CPU/GPU kernel split of the reference collapses.  Gradients come from
jax autodiff — the hand-written ``*_grad`` kernels and the backward
transpiler's grad-op pairing are replaced by ``jax.vjp`` at whatever
granularity the caller traces (whole-block under the Executor).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, Optional

from ..utils import Registry


@dataclasses.dataclass
class OpDef:
    name: str
    fn: Callable
    doc: str = ""
    n_outputs: int = 1

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.fn(*args, **kwargs)


OPS: Registry = Registry("op")


def register_op(name: str, *aliases: str, n_outputs: int = 1):
    """Decorator: expose a pure function as a named framework op."""

    def deco(fn: Callable) -> Callable:
        OPS.register_value(
            name,
            OpDef(name=name, fn=fn, doc=inspect.getdoc(fn) or "", n_outputs=n_outputs),
            *aliases,
        )
        return fn

    return deco


def get_op(name: str) -> OpDef:
    return OPS.get(name)

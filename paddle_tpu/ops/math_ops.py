"""Dense math ops.

The TPU replacement for the reference's hand-rolled linear algebra:
``paddle/math/Matrix.h`` / ``BaseMatrix`` elementwise+aggregate families,
``paddle/operators`` math ops (mul, matmul, sum, scale, clip, elementwise_*,
reduce_*, transpose, reshape, concat, split, pad, crop, cast, gather,
scatter, top_k, multiplex, …), and ``paddle/function`` Mul/CosSim/Crop/Pad.
Everything lowers to XLA HLO; matmuls go through :func:`matmul` which applies
the bf16 compute policy so they hit the MXU.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dtypes import current_policy, record_op_precision
from .registry import register_op


@register_op("matmul", "mul")
def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False,
           scale: float = 1.0):
    """MXU matmul with mixed-precision policy (bf16 in, f32 accumulate).

    Reference: ``paddle/operators/matmul_op.cc`` / ``Matrix::mul``
    (``paddle/math/Matrix.h``).
    """
    pol = current_policy()
    if jnp.issubdtype(jnp.result_type(x), jnp.floating):
        record_op_precision("matmul")
        x = x.astype(pol.compute_dtype)
        y = y.astype(pol.compute_dtype)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, preferred_element_type=pol.output_dtype)
    if scale != 1.0:
        out = out * scale
    return out


@register_op("einsum")
def einsum(subscripts: str, *operands):
    """MXU einsum under the mixed-precision policy: floating operands
    cast to the compute dtype, accumulation in the output dtype —
    the einsum-shaped counterpart of :func:`matmul`, so contraction
    layers (tensor products, vec-mat cosine) route through
    ``core/dtypes`` instead of silently pinning the operand dtype."""
    pol = current_policy()
    if any(jnp.issubdtype(jnp.result_type(x), jnp.floating)
           for x in operands):
        record_op_precision("einsum")
        operands = tuple(
            x.astype(pol.compute_dtype)
            if jnp.issubdtype(jnp.result_type(x), jnp.floating) else x
            for x in operands)
        return jnp.einsum(subscripts, *operands,
                          preferred_element_type=pol.output_dtype)
    # integer/bool contraction: the policy is a FLOAT compute policy —
    # forcing its output dtype here would silently promote to float
    return jnp.einsum(subscripts, *operands)


@register_op("sum")
def sum_arrays(*xs):
    """Sum N same-shape tensors (``paddle/operators/sum_op.cc``)."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_op("scale")
def scale(x, scale: float = 1.0, bias: float = 0.0):
    return x * scale + bias


@register_op("sign")
def sign(x):
    return jnp.sign(x)


@register_op("clip")
def clip(x, min: float, max: float):
    return jnp.clip(x, min, max)


@register_op("mean")
def mean(x):
    return jnp.mean(x)


@register_op("minus")
def minus(x, y):
    return x - y


@register_op("increment")
def increment(x, step: float = 1.0):
    return x + step


@register_op("elementwise_add")
def elementwise_add(x, y, axis: int = -1):
    return x + _broadcast_to_rank(y, x.ndim, axis)


@register_op("elementwise_sub")
def elementwise_sub(x, y, axis: int = -1):
    return x - _broadcast_to_rank(y, x.ndim, axis)


@register_op("elementwise_mul")
def elementwise_mul(x, y, axis: int = -1):
    return x * _broadcast_to_rank(y, x.ndim, axis)


@register_op("elementwise_div")
def elementwise_div(x, y, axis: int = -1):
    return x / _broadcast_to_rank(y, x.ndim, axis)


def _broadcast_to_rank(y, rank: int, axis: int):
    """Reference broadcast rule (``elementwise_op_function.h``): y's shape
    matches a contiguous slice of x's dims starting at ``axis``."""
    if y.ndim == rank or y.ndim == 0:
        return y
    if axis < 0:
        axis = rank - y.ndim
    shape = [1] * rank
    for i, d in enumerate(y.shape):
        shape[axis + i] = d
    return y.reshape(shape)


@register_op("reduce_sum")
def reduce_sum(x, dim=None, keep_dim: bool = False):
    return jnp.sum(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_mean")
def reduce_mean(x, dim=None, keep_dim: bool = False):
    return jnp.mean(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_max")
def reduce_max(x, dim=None, keep_dim: bool = False):
    return jnp.max(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_min")
def reduce_min(x, dim=None, keep_dim: bool = False):
    return jnp.min(x, axis=dim, keepdims=keep_dim)


@register_op("transpose", "trans")
def transpose(x, axis: Optional[Sequence[int]] = None):
    return jnp.transpose(x, axes=axis)


@register_op("reshape")
def reshape(x, shape: Sequence[int]):
    return jnp.reshape(x, shape)


@register_op("concat")
def concat(*xs, axis: int = 1):
    return jnp.concatenate(xs, axis=axis)


@register_op("split", n_outputs=-1)
def split(x, num_or_sections, axis: int = 1):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    idx = list(jnp.cumsum(jnp.array(num_or_sections))[:-1])
    return jnp.split(x, [int(i) for i in idx], axis=axis)


@register_op("pad")
def pad(x, paddings: Sequence[Tuple[int, int]], pad_value: float = 0.0):
    return jnp.pad(x, paddings, constant_values=pad_value)


@register_op("crop")
def crop(x, offsets: Sequence[int], shape: Sequence[int]):
    return lax.dynamic_slice(x, offsets, shape)


@register_op("cast")
def cast(x, dtype):
    return x.astype(dtype)


@register_op("gather")
def gather(x, index, axis: int = 0):
    return jnp.take(x, index, axis=axis)


@register_op("scatter")
def scatter(ref, index, updates, overwrite: bool = True):
    """Row scatter (``paddle/operators/scatter_op.cc``): functional —
    returns a new array (reference mutates in place)."""
    if overwrite:
        return ref.at[index].set(updates)
    return ref.at[index].add(updates)


@register_op("top_k", n_outputs=2)
def top_k(x, k: int):
    """Values+indices of top-k along last dim (``hl_top_k.cu`` replacement —
    XLA's TopK is already tuned for TPU; no Pallas needed)."""
    return lax.top_k(x, k)


@register_op("multiplex")
def multiplex(index, *xs):
    """Row-wise select among candidate tensors by per-row index
    (``paddle/operators/multiplex_op.cc``)."""
    stacked = jnp.stack(xs, axis=0)  # [N, B, ...]
    idx = index.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


@register_op("fill_constant")
def fill_constant(shape: Sequence[int], value: float, dtype=jnp.float32):
    return jnp.full(shape, value, dtype=dtype)


@register_op("fill_zeros_like")
def fill_zeros_like(x):
    return jnp.zeros_like(x)


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(ref, shape: Sequence[int], value: float,
                                  dtype=jnp.float32, input_dim_idx: int = 0,
                                  output_dim_idx: int = 0):
    shape = list(shape)
    shape[output_dim_idx] = ref.shape[input_dim_idx]
    return jnp.full(shape, value, dtype=dtype)


@register_op("gaussian_random")
def gaussian_random(key, shape: Sequence[int], mean: float = 0.0,
                    std: float = 1.0, dtype=jnp.float32):
    return mean + std * jax.random.normal(key, shape, dtype=dtype)


@register_op("uniform_random")
def uniform_random(key, shape: Sequence[int], min: float = -1.0,
                   max: float = 1.0, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype=dtype, minval=min, maxval=max)


@register_op("cos_sim")
def cos_sim(x, y, scale: float = 1.0, eps: float = 1e-10):
    """Row-wise cosine similarity (``paddle/operators/cos_sim_op.cc``,
    ``CosSimLayer``); y may have one row (broadcast)."""
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + eps)
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1) + eps)
    dot = jnp.sum(x * y, axis=-1)
    return scale * dot / (xn * yn)


@register_op("conv_shift")
def conv_shift(x, y):
    """Circular 1-D convolution of each row of x with kernel row of y
    (``paddle/operators/conv_shift_op.cc``).  Kernel width must be odd."""
    b, m = x.shape
    _, n = y.shape
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, half + 1)[None, :]) % m
    windows = x[:, idx]  # [B, M, N]
    return jnp.einsum("bmn,bn->bm", windows, y)


@register_op("outer_prod")
def outer_prod(x, y):
    """Row-wise outer product flattened (``OuterProdLayer``)."""
    return (x[:, :, None] * y[:, None, :]).reshape(x.shape[0], -1)


@register_op("interpolation")
def interpolation(w, x, y):
    """w*x + (1-w)*y with per-row scalar w (``InterpolationLayer``)."""
    w = w.reshape(-1, 1)
    return w * x + (1.0 - w) * y


@register_op("slope_intercept")
def slope_intercept(x, slope: float = 1.0, intercept: float = 0.0):
    return slope * x + intercept


@register_op("sum_to_one_norm")
def sum_to_one_norm(x, eps: float = 1e-12):
    return x / (jnp.sum(x, axis=-1, keepdims=True) + eps)


@register_op("row_l2_norm")
def row_l2_norm(x, eps: float = 1e-12):
    return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)


@register_op("convex_combination")
def convex_combination(weights, x):
    """Per-row convex combination: weights [..., K], x [..., K*D] →
    [..., D] (``ConvexCombinationLayer``); leading dims (batch, or
    batch×time for sequence inputs) broadcast."""
    k = weights.shape[-1]
    d = x.shape[-1] // k
    return jnp.einsum("...k,...kd->...d", weights,
                      x.reshape(*x.shape[:-1], k, d))

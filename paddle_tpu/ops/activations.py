"""Activation functions.

Covers the union of the reference's activation surfaces: the gserver
activation registry (``paddle/gserver/activations/ActivationFunction.cpp`` —
sigmoid, softmax, sequence_softmax, relu, brelu, tanh, stanh, softrelu, abs,
square, exponential, reciprocal, sqrt, log) and the next-gen activation op
family (``paddle/operators/activation_op.cc`` — 24 ops).  All are elementwise
jax functions that XLA fuses into their producers on TPU; no Pallas needed.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import Registry
from .registry import register_op

ACTIVATIONS: Registry = Registry("activation")


def _act(name: str, *aliases: str):
    def deco(fn):
        ACTIVATIONS.register_value(name, fn, *aliases)
        register_op(name)(fn)
        return fn

    return deco


@_act("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@_act("logsigmoid")
def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


@_act("exp", "exponential")
def exp(x):
    return jnp.exp(x)


@_act("relu")
def relu(x):
    return jax.nn.relu(x)


@_act("tanh")
def tanh(x):
    return jnp.tanh(x)


@_act("tanh_shrink")
def tanh_shrink(x):
    return x - jnp.tanh(x)


@_act("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@_act("abs")
def abs_(x):
    return jnp.abs(x)


@_act("reciprocal")
def reciprocal(x):
    return 1.0 / x


@_act("log")
def log(x):
    return jnp.log(x)


@_act("square")
def square(x):
    return jnp.square(x)


@_act("brelu")
def brelu(x, t_min: float = 0.0, t_max: float = 24.0):
    return jnp.clip(x, t_min, t_max)


@_act("soft_relu", "softrelu")
def soft_relu(x, threshold: float = 40.0):
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


@_act("pow")
def pow_(x, factor: float = 1.0):
    return jnp.power(x, factor)


@_act("stanh")
def stanh(x, scale_a: float = 2.0 / 3.0, scale_b: float = 1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@_act("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@_act("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@_act("leaky_relu")
def leaky_relu(x, alpha: float = 0.02):
    return jnp.where(x >= 0, x, alpha * x)


@_act("elu")
def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha)


@_act("relu6")
def relu6(x, threshold: float = 6.0):
    return jnp.clip(x, 0.0, threshold)


@_act("hard_shrink")
def hard_shrink(x, threshold: float = 0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@_act("softshrink")
def softshrink(x, lambda_: float = 0.5):
    return jnp.where(x > lambda_, x - lambda_, jnp.where(x < -lambda_, x + lambda_, 0.0))


@_act("thresholded_relu")
def thresholded_relu(x, threshold: float = 1.0):
    return jnp.where(x > threshold, x, 0.0)


@_act("hard_sigmoid")
def hard_sigmoid(x, slope: float = 0.2, offset: float = 0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@_act("swish")
def swish(x, beta: float = 1.0):
    return x * jax.nn.sigmoid(beta * x)


@_act("linear", "identity", "")
def linear(x):
    return x


@_act("softmax")
def softmax(x, axis: int = -1):
    # fp32 internally: bf16 exp/normalize loses probability mass
    return jax.nn.softmax(x.astype(jnp.float32), axis=axis).astype(x.dtype)


@_act("sequence_softmax")
def sequence_softmax(x, mask=None):
    """Softmax over the time axis of a padded [B, T] (or [B, T, 1]) batch.

    Reference computes softmax per variable-length sequence
    (``SequenceSoftmaxActivation``); here padding positions are masked to
    -inf so they get zero probability.
    """
    squeeze = False
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
        squeeze = True
    if mask is not None:
        x = jnp.where(mask > 0, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=-1)
    if mask is not None:
        out = jnp.where(mask > 0, out, 0.0)
    if squeeze:
        out = out[..., None]
    return out


def get_activation(name: Optional[str]):
    if name is None:
        return linear
    return ACTIVATIONS.get(name)

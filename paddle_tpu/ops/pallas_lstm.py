"""Fused LSTM sequence as Pallas TPU kernels.

The reference hand-wrote exactly this kernel tier in CUDA
(``paddle/cuda/src/hl_cuda_lstm.cu`` / ``hl_lstm_ops.cuh``: one kernel
per step fusing the gate elementwise math, state kept in registers) —
SURVEY §7 names the fused lstm step as the Pallas candidate for the
latency-bound regime.  This module goes further than the reference: the
ENTIRE time loop runs inside one kernel launch, with h/c carried in VMEM
scratch across a sequential grid over T and the recurrent weight matrix
resident in VMEM, so XLA's per-scan-step fixed costs (loop bookkeeping,
HBM round-trips for the carry) disappear.

Forward kernel (grid = (T,)): per step, gates = xw_t + h @ w_hh (MXU),
peepholes + sigmoid/tanh gate math (VPU), length-masked state keep —
writes the kept state sequences H, C and the activated gates (backward
residual).

Backward kernel (grid = (T,), reversed block maps): standard BPTT with
dh/dc carries and the dW_hh / peephole-grad accumulators in VMEM f32
scratch, one (dgates @ w_hhᵀ) + one (h_prevᵀ @ dgates) MXU matmul per
step.

Layouts are time-major ([T, B, ·]) so each grid step addresses one
contiguous block.  Shapes that don't tile (B % 8, H % 128) or non-default
activations dispatch to the ``lax.scan`` path in
:mod:`paddle_tpu.ops.recurrent_ops` — same contract, same results.
On non-TPU backends the kernels run in Pallas interpret mode so CPU
tests exercise the exact dispatch used on hardware.

Round 8 adds the **hidden-blocked tier** for 512 < H (the baseline's
own hidden=1280 row used to fall off this kernel onto the scan path):
grid (T, H/Hb) with Hb = 128, each inner step streaming one
[H, 4Hb] column block of w_hh through a double-buffered VMEM pipeline
— the flash-attention / ``hl_cuda_lstm.cu`` large-weight treatment —
while the full [B, H] h/c state carries in scratch across both grid
dimensions.  The backward mirrors it; its dW_hh is a separate
constant-block kernel (grid (nb, T), time innermost) so no [H, 4H]
tensor is ever VMEM-resident.  ``fused_tier`` picks the tier;
``--fused_rnn_hblock=false`` kills the blocked tier (round-6 one-flag
revert contract).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from .pallas_attention import CompilerParams, _interpret  # shared gate


# Hidden-block width of the blocked tier.  128 = one lane tile, the
# smallest width that keeps every streamed weight block MXU-shaped; it
# also makes the blocked-tier shape gate coincide with the lane-tiling
# gate (H % 128), so any lane-tileable H > 512 is a blocking candidate.
HBLOCK = 128

# Budget for the dominant VMEM residents of the blocked kernels, kept
# under the 16 MB scoped-vmem window with headroom for Mosaic's own
# spills.  See _blocked_vmem_bytes for the arithmetic.
_BLOCKED_VMEM_CAP = 14 * 1024 * 1024


def _blocked_vmem_bytes(b: int, h: int, n_gates: int) -> int:
    """Dominant VMEM residents of the hidden-blocked kernels, in bytes:
    up to five full-width [B, H] f32 state/accumulator scratches (the
    backward's dh/dc carries plus the cross-block recurrent-pullback
    accumulator) and the double-buffered streamed weight column block
    [H, n_gates·HBLOCK] f32.  At the baseline row (b=128, h=1280,
    LSTM): 5·128·1280·4 ≈ 3.3 MB + 2·1280·512·4 ≈ 5.2 MB ≈ 8.5 MB —
    comfortably inside the cap, where the round-7 single-block kernel
    needed 2×26 MB for the resident w_hh + dW_hh pair."""
    state = 5 * b * h * 4
    w_block_stream = 2 * h * n_gates * HBLOCK * 4
    return state + w_block_stream


def fused_tier(b: int, h: int, n_gates: int = 4):
    """Two-tier Mosaic dispatch predicate, checked on every backend so
    interpret-mode tests exercise the hardware dispatch.

    - ``"fused"`` (h ≤ 512): the round-5 single-block kernels — w_hh
      [H, 4H] f32 fully VMEM-resident (4 MB at H=512) plus the same-
      shape dW_hh accumulator stays inside the 16 MB scoped-vmem
      budget.  Unchanged fast path.
    - ``"fused_blocked"`` (512 < h, h % HBLOCK == 0, VMEM estimate
      under cap): the round-8 hidden-blocked kernels — grid (T, H/Hb)
      streams [H, n_gates·Hb] weight column blocks while the full
      [B, H] state carries live in VMEM scratch, so no [H, n_gates·H]
      tensor is ever resident.  ``--fused_rnn_hblock=false`` disables
      this tier, restoring the round-7 h ≤ 512 gate byte-for-byte.
    - ``None``: the ``lax.scan`` path (dispatch site logs a one-time
      structured warning per shape).
    """
    if b % 8 or h % 128:
        return None
    if h <= 512:
        return "fused"
    from ..utils import FLAGS

    if not FLAGS.fused_rnn_hblock:
        return None
    if h % HBLOCK or _blocked_vmem_bytes(b, h, n_gates) > _BLOCKED_VMEM_CAP:
        return None
    return "fused_blocked"


def fused_ok(b: int, h: int) -> bool:
    """True when either fused tier serves (b, h) — the dispatch kill
    point tests monkeypatch to force the scan reference path."""
    return fused_tier(b, h) is not None


# ------------------------------------------------- block-gate layout
def _to_gate_blocks(a, h: int, n_gates: int, hb: int = HBLOCK):
    """Permute a gate-major last axis (g0|g1|…, each H wide) into the
    block-major layout the blocked kernels stream: block j holds
    [g0_j|g1_j|…] (n_gates·hb columns), so a BlockSpec column block j
    of the permuted array carries every gate's slice of hidden block j
    contiguously.  Pure reshape/transpose — XLA does it in one pass and
    autodiff transposes it for free around the custom_vjp core."""
    nb = h // hb
    lead = a.shape[:-1]
    return a.reshape(*lead, n_gates, nb, hb).swapaxes(-3, -2) \
            .reshape(*lead, n_gates * h)


def _from_gate_blocks(a, h: int, n_gates: int, hb: int = HBLOCK):
    """Inverse of :func:`_to_gate_blocks`."""
    nb = h // hb
    lead = a.shape[:-1]
    return a.reshape(*lead, nb, n_gates, hb).swapaxes(-3, -2) \
            .reshape(*lead, n_gates * h)


def _sig(x):
    return jax.nn.sigmoid(x)


# --------------------------------------------------------------- forward
def _fwd_kernel(xw_ref, m_ref, whh_ref, ck_ref, h0_ref, c0_ref,
                hseq_ref, cseq_ref, gates_ref, h_s, c_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_s[:] = h0_ref[...].astype(jnp.float32)
        c_s[:] = c0_ref[...].astype(jnp.float32)

    h_prev = h_s[:]                                     # [B, H] f32
    c_prev = c_s[:]
    hd = h_prev.shape[-1]
    xw = xw_ref[0].astype(jnp.float32)                  # [B, 4H]
    gates = xw + h_prev @ whh_ref[...].astype(jnp.float32)
    pre_i = gates[:, :hd]
    pre_f = gates[:, hd:2 * hd]
    pre_c = gates[:, 2 * hd:3 * hd]
    pre_o = gates[:, 3 * hd:]
    # peepholes (row 0 = check_i, 1 = check_f, 2 = check_o)
    ck = ck_ref[...].astype(jnp.float32)                # [8, H]
    i = _sig(pre_i + c_prev * ck[0])
    f = _sig(pre_f + c_prev * ck[1])
    g = jnp.tanh(pre_c)
    c = f * c_prev + i * g
    o = _sig(pre_o + c * ck[2])
    h = o * jnp.tanh(c)

    m = m_ref[0, 0].astype(jnp.float32)[:, None]        # [B, 1]
    h_keep = m * h + (1.0 - m) * h_prev
    c_keep = m * c + (1.0 - m) * c_prev
    h_s[:] = h_keep
    c_s[:] = c_keep
    hseq_ref[0] = h_keep.astype(hseq_ref.dtype)
    cseq_ref[0] = c_keep.astype(cseq_ref.dtype)
    gates_ref[0] = jnp.concatenate([i, f, g, o],
                                   axis=-1).astype(gates_ref.dtype)


def _fwd_call(xw, mask, w_hh, checks, h0, c0):
    t, b, hd4 = xw.shape
    hd = hd4 // 4
    return pl.pallas_call(
        _fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd4), lambda i: (i, 0, 0)),   # xw
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0)),     # mask
            pl.BlockSpec((hd, hd4), lambda i: (0, 0)),        # w_hh
            pl.BlockSpec((8, hd), lambda i: (0, 0)),          # checks
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # h0
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # c0
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd), lambda i: (i, 0, 0)),    # H
            pl.BlockSpec((1, b, hd), lambda i: (i, 0, 0)),    # C
            pl.BlockSpec((1, b, hd4), lambda i: (i, 0, 0)),   # gates
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd4), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),                 # h carry
            pltpu.VMEM((b, hd), jnp.float32),                 # c carry
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(xw, mask, w_hh, checks, h0, c0)


# -------------------------------------------------------------- backward
def _bwd_kernel(gates_ref, hprev_ref, cprev_ref, c_ref, m_ref, whh_ref,
                ck_ref, dy_ref, dyc_ref, dxw_ref, dwhh_ref, dck_ref,
                dh0_ref, dc0_ref, dh_s, dc_s, *, t_total):
    """Grid step i visits t = T-1-i (the block index maps reverse time).
    hprev/cprev blocks carry H_{t-1}/C_{t-1} (the wrapper passes the
    state sequences shifted by one with h0/c0 prepended).  dy/dyc are
    the external cotangents on the kept sequences H_t/C_t; they join the
    recurrent carries BEFORE the masked split, so the (1−m) passthrough
    forwards them to earlier steps exactly like the forward keep."""
    i_rev = pl.program_id(0)

    @pl.when(i_rev == 0)
    def _init():
        dh_s[:] = jnp.zeros_like(dh_s)
        dc_s[:] = jnp.zeros_like(dc_s)
        # dW/dck accumulate directly in their (constant-block) output
        # refs — a second VMEM copy as scratch would overflow the 16 MB
        # scoped-vmem budget at H=512
        dwhh_ref[...] = jnp.zeros_like(dwhh_ref)
        dck_ref[...] = jnp.zeros_like(dck_ref)

    hd = dh_s.shape[-1]
    gates = gates_ref[0].astype(jnp.float32)
    g_i = gates[:, :hd]
    g_f = gates[:, hd:2 * hd]
    g_g = gates[:, 2 * hd:3 * hd]
    g_o = gates[:, 3 * hd:]
    h_prev = hprev_ref[0].astype(jnp.float32)
    c_prev = cprev_ref[0].astype(jnp.float32)
    c = c_ref[0].astype(jnp.float32)
    ck = ck_ref[...].astype(jnp.float32)
    m = m_ref[0, 0].astype(jnp.float32)[:, None]

    tanh_c = jnp.tanh(c)
    # total cotangents on the kept states H_t / C_t
    dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:]
    dc_tot = dyc_ref[0].astype(jnp.float32) + dc_s[:]
    dh = m * dh_tot                                     # raw-h share
    do_pre = dh * tanh_c * g_o * (1.0 - g_o)
    dc = m * dc_tot + dh * g_o * (1.0 - tanh_c * tanh_c) \
        + do_pre * ck[2]                                # raw-c share
    di_pre = dc * g_g * g_i * (1.0 - g_i)
    df_pre = dc * c_prev * g_f * (1.0 - g_f)
    dg_pre = dc * g_i * (1.0 - g_g * g_g)
    dgates = jnp.concatenate([di_pre, df_pre, dg_pre, do_pre], axis=-1)

    dh_prev = dgates @ whh_ref[...].astype(jnp.float32).T
    dc_prev = dc * g_f + di_pre * ck[0] + df_pre * ck[1]

    dh_s[:] = (1.0 - m) * dh_tot + dh_prev
    dc_s[:] = (1.0 - m) * dc_tot + dc_prev
    dwhh_ref[...] = dwhh_ref[...] + h_prev.T @ dgates
    dck_ref[0] = dck_ref[0] + jnp.sum(di_pre * c_prev, axis=0)
    dck_ref[1] = dck_ref[1] + jnp.sum(df_pre * c_prev, axis=0)
    dck_ref[2] = dck_ref[2] + jnp.sum(do_pre * c, axis=0)
    dxw_ref[0] = dgates.astype(dxw_ref.dtype)

    @pl.when(i_rev == t_total - 1)
    def _flush():
        dh0_ref[...] = dh_s[:].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_s[:].astype(dc0_ref.dtype)


def _bwd_call(gates, h_prev_seq, c_prev_seq, c_seq, mask, w_hh, checks,
              dy, dyc):
    t, b, hd4 = gates.shape
    hd = hd4 // 4
    rev3 = lambda i: (t - 1 - i, 0, 0)
    kernel = functools.partial(_bwd_kernel, t_total=t)
    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd4), rev3),                  # gates
            pl.BlockSpec((1, b, hd), rev3),                   # H_{t-1}
            pl.BlockSpec((1, b, hd), rev3),                   # C_{t-1}
            pl.BlockSpec((1, b, hd), rev3),                   # C_t
            pl.BlockSpec((1, 1, b), lambda i: (t - 1 - i, 0, 0)),  # mask
            pl.BlockSpec((hd, hd4), lambda i: (0, 0)),        # w_hh
            pl.BlockSpec((8, hd), lambda i: (0, 0)),          # checks
            pl.BlockSpec((1, b, hd), rev3),                   # dy (dH)
            pl.BlockSpec((1, b, hd), rev3),                   # dyc (dC)
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd4), rev3),                  # dxw
            pl.BlockSpec((hd, hd4), lambda i: (0, 0)),        # dw_hh
            pl.BlockSpec((8, hd), lambda i: (0, 0)),          # dchecks
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # dh0
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # dc0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd4), jnp.float32),
            jax.ShapeDtypeStruct((hd, hd4), jnp.float32),
            jax.ShapeDtypeStruct((8, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),                 # dh carry
            pltpu.VMEM((b, hd), jnp.float32),                 # dc carry
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(gates, h_prev_seq, c_prev_seq, c_seq, mask, w_hh, checks, dy, dyc)


# ------------------------------------------------------------ custom vjp
@jax.custom_vjp
def _lstm_core(xw, mask, w_hh, checks, h0, c0):
    """xw [T, B, 4H] (input projection + bias already applied), mask
    [T, B], w_hh [H, 4H], checks [8, H] (rows 0..2 = peephole i/f/o,
    rest zero), h0/c0 [B, H].  Returns kept-state sequences
    (H [T, B, Hd], C [T, B, Hd]) in f32."""
    h_seq, c_seq, _gates = _fwd_call(xw, mask, w_hh, checks, h0, c0)
    return h_seq, c_seq


def _lstm_core_fwd(xw, mask, w_hh, checks, h0, c0):
    h_seq, c_seq, gates = _fwd_call(xw, mask, w_hh, checks, h0, c0)
    return (h_seq, c_seq), (gates, h_seq, c_seq, mask, w_hh, checks,
                            h0, c0)


def _lstm_core_bwd(res, cts):
    gates, h_seq, c_seq, mask, w_hh, checks, h0, c0 = res
    dh_seq, dc_seq = cts
    # state sequences shifted one step back, boot state prepended
    h_prev_seq = jnp.concatenate([h0[None].astype(h_seq.dtype),
                                  h_seq[:-1]], axis=0)
    c_prev_seq = jnp.concatenate([c0[None].astype(c_seq.dtype),
                                  c_seq[:-1]], axis=0)
    dxw, dw_hh, dck, dh0, dc0 = _bwd_call(
        gates, h_prev_seq, c_prev_seq, c_seq, mask, w_hh, checks,
        dh_seq, dc_seq)
    # mask was cast to xw's dtype in the wrapper, so it carries the
    # input dtype for the cotangent cast
    return (dxw.astype(mask.dtype), jnp.zeros_like(mask), dw_hh,
            dck, dh0, dc0)


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def lstm_fused_sequence(xw, mask, w_hh, check_i, check_f, check_o,
                        h0, c0):
    """Batch-major wrapper: xw [B, T, 4H] pre-projected (+bias), mask
    [B, T]; returns (y [B, T, H] masked hidden outputs, cy [B, T, H]
    masked cell outputs, final_h [B, H], final_c [B, H]) in f32 —
    callers cast per their dtype policy.  XLA dead-code-eliminates the
    cy mask-multiply when the caller drops it.
    """
    b, t, hd4 = xw.shape
    hd = hd4 // 4
    checks = jnp.zeros((8, hd), jnp.float32)
    if check_i is not None:
        checks = checks.at[0].set(check_i.astype(jnp.float32))
        checks = checks.at[1].set(check_f.astype(jnp.float32))
    if check_o is not None:
        checks = checks.at[2].set(check_o.astype(jnp.float32))
    h0 = jnp.zeros((b, hd), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    c0 = jnp.zeros((b, hd), jnp.float32) if c0 is None \
        else c0.astype(jnp.float32)
    h_seq, c_seq = _lstm_core(
        jnp.moveaxis(xw, 1, 0),
        jnp.moveaxis(mask, 1, 0).astype(xw.dtype)[:, None, :],
        w_hh.astype(jnp.float32), checks, h0, c0)
    m = mask.astype(jnp.float32)[:, :, None]
    y = jnp.moveaxis(h_seq, 0, 1) * m
    cy = jnp.moveaxis(c_seq, 0, 1) * m
    return y, cy, h_seq[-1], c_seq[-1]


# =================================================================
# Hidden-blocked tier (512 < H): grid (T, H/Hb) streams weight column
# blocks instead of keeping w_hh resident.  The full [B, H] h/c state
# (0.7 MB f32 at b=128/H=1280 — cheap) carries in VMEM scratch across
# BOTH grid dimensions; per inner step the MXU sees one
# [B, H] @ [H, 4Hb] matmul against the streamed block.  All dynamic
# scratch column offsets are j·Hb with Hb = 128, i.e. lane-tile
# aligned — the Mosaic-friendly dynamic-slice case.
# =================================================================
def _fwd_kernel_blocked(xw_ref, m_ref, whh_ref, ck_ref, h0_ref, c0_ref,
                        hseq_ref, cseq_ref, gates_ref,
                        h_s, c_s, hn_s, cn_s, *, nb, hb):
    """Grid (T, nb), hidden blocks innermost.  Every block of step t
    reads the step-(t-1) state from h_s/c_s and writes its kept slice
    into the staging scratches hn_s/cn_s; the last block commits the
    staged state so no block of step t ever sees a partial update.
    xw/whh/gates are in block-gate layout (see _to_gate_blocks)."""
    t = pl.program_id(0)
    j = pl.program_id(1)
    col = j * hb

    @pl.when((t == 0) & (j == 0))
    def _init():
        h_s[:] = h0_ref[...].astype(jnp.float32)
        c_s[:] = c0_ref[...].astype(jnp.float32)

    h_prev = h_s[:]                                     # [B, H] f32
    h_prev_blk = h_s[:, pl.ds(col, hb)]                 # [B, Hb]
    c_prev_blk = c_s[:, pl.ds(col, hb)]
    xw = xw_ref[0].astype(jnp.float32)                  # [B, 4Hb]
    gates = xw + h_prev @ whh_ref[...].astype(jnp.float32)
    pre_i = gates[:, :hb]
    pre_f = gates[:, hb:2 * hb]
    pre_c = gates[:, 2 * hb:3 * hb]
    pre_o = gates[:, 3 * hb:]
    ck = ck_ref[...].astype(jnp.float32)                # [8, Hb]
    i = _sig(pre_i + c_prev_blk * ck[0])
    f = _sig(pre_f + c_prev_blk * ck[1])
    g = jnp.tanh(pre_c)
    c = f * c_prev_blk + i * g
    o = _sig(pre_o + c * ck[2])
    h = o * jnp.tanh(c)

    m = m_ref[0, 0].astype(jnp.float32)[:, None]        # [B, 1]
    h_keep = m * h + (1.0 - m) * h_prev_blk
    c_keep = m * c + (1.0 - m) * c_prev_blk
    hn_s[:, pl.ds(col, hb)] = h_keep
    cn_s[:, pl.ds(col, hb)] = c_keep
    hseq_ref[0] = h_keep.astype(hseq_ref.dtype)
    cseq_ref[0] = c_keep.astype(cseq_ref.dtype)
    gates_ref[0] = jnp.concatenate([i, f, g, o],
                                   axis=-1).astype(gates_ref.dtype)

    @pl.when(j == nb - 1)
    def _commit():
        h_s[:] = hn_s[:]
        c_s[:] = cn_s[:]


def _fwd_call_blocked(xw, mask, w_hh, checks, h0, c0, hb=HBLOCK):
    t, b, hd4 = xw.shape
    hd = hd4 // 4
    nb = hd // hb
    kernel = functools.partial(_fwd_kernel_blocked, nb=nb, hb=hb)
    return pl.pallas_call(
        kernel,
        grid=(t, nb),
        in_specs=[
            pl.BlockSpec((1, b, 4 * hb), lambda i, j: (i, 0, j)),  # xw
            pl.BlockSpec((1, 1, b), lambda i, j: (i, 0, 0)),       # mask
            pl.BlockSpec((hd, 4 * hb), lambda i, j: (0, j)),       # w_hh
            pl.BlockSpec((8, hb), lambda i, j: (0, j)),            # checks
            pl.BlockSpec((b, hd), lambda i, j: (0, 0)),            # h0
            pl.BlockSpec((b, hd), lambda i, j: (0, 0)),            # c0
        ],
        out_specs=[
            pl.BlockSpec((1, b, hb), lambda i, j: (i, 0, j)),      # H
            pl.BlockSpec((1, b, hb), lambda i, j: (i, 0, j)),      # C
            pl.BlockSpec((1, b, 4 * hb), lambda i, j: (i, 0, j)),  # gates
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd4), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),                # h carry
            pltpu.VMEM((b, hd), jnp.float32),                # c carry
            pltpu.VMEM((b, hd), jnp.float32),                # h staging
            pltpu.VMEM((b, hd), jnp.float32),                # c staging
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(xw, mask, w_hh, checks, h0, c0)


def _bwd_kernel_blocked(gates_ref, cprev_ref, c_ref, m_ref, whh_ref,
                        ck_ref, dy_ref, dyc_ref,
                        dxw_ref, dh0_ref, dc0_ref,
                        dh_s, dc_s, dacc_s, dcn_s, *, t_total, nb, hb):
    """Reversed-time BPTT, grid (T, nb).  The gate math is elementwise
    in the hidden index, so each block computes its own dgates slice
    from the carried dh_s/dc_s; the one cross-block coupling — the
    recurrent pullback dgates @ w_hhᵀ, full [B, H] wide — accumulates
    over the inner block loop in dacc_s, and the last block commits the
    next step's carries.  The weight gradient does NOT ride along: a
    revisited [H, 4Hb] dW block would flush/refill per step, so dW_hh
    runs as its own constant-block kernel (_dw_call_blocked) over the
    dgates residue this kernel writes out as dxw."""
    i_rev = pl.program_id(0)
    j = pl.program_id(1)
    col = j * hb

    @pl.when((i_rev == 0) & (j == 0))
    def _init():
        dh_s[:] = jnp.zeros_like(dh_s)
        dc_s[:] = jnp.zeros_like(dc_s)

    @pl.when(j == 0)
    def _zero_acc():
        dacc_s[:] = jnp.zeros_like(dacc_s)

    gates = gates_ref[0].astype(jnp.float32)            # [B, 4Hb]
    g_i = gates[:, :hb]
    g_f = gates[:, hb:2 * hb]
    g_g = gates[:, 2 * hb:3 * hb]
    g_o = gates[:, 3 * hb:]
    c_prev = cprev_ref[0].astype(jnp.float32)           # [B, Hb]
    c = c_ref[0].astype(jnp.float32)
    ck = ck_ref[...].astype(jnp.float32)                # [8, Hb]
    m = m_ref[0, 0].astype(jnp.float32)[:, None]

    tanh_c = jnp.tanh(c)
    dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:, pl.ds(col, hb)]
    dc_tot = dyc_ref[0].astype(jnp.float32) + dc_s[:, pl.ds(col, hb)]
    dh = m * dh_tot                                     # raw-h share
    do_pre = dh * tanh_c * g_o * (1.0 - g_o)
    dc = m * dc_tot + dh * g_o * (1.0 - tanh_c * tanh_c) \
        + do_pre * ck[2]                                # raw-c share
    di_pre = dc * g_g * g_i * (1.0 - g_i)
    df_pre = dc * c_prev * g_f * (1.0 - g_f)
    dg_pre = dc * g_i * (1.0 - g_g * g_g)
    dgates = jnp.concatenate([di_pre, df_pre, dg_pre, do_pre], axis=-1)

    # cross-block recurrent pullback: every gate block contributes a
    # full-width [B, H] term
    dacc_s[:] = dacc_s[:] + dgates @ whh_ref[...].astype(jnp.float32).T
    # block-local pieces join the accumulator at this block's columns
    dacc_s[:, pl.ds(col, hb)] = dacc_s[:, pl.ds(col, hb)] \
        + (1.0 - m) * dh_tot
    dc_prev = dc * g_f + di_pre * ck[0] + df_pre * ck[1]
    dcn_s[:, pl.ds(col, hb)] = (1.0 - m) * dc_tot + dc_prev
    dxw_ref[0] = dgates.astype(dxw_ref.dtype)

    @pl.when(j == nb - 1)
    def _commit():
        dh_s[:] = dacc_s[:]
        dc_s[:] = dcn_s[:]

    @pl.when((i_rev == t_total - 1) & (j == nb - 1))
    def _flush():
        dh0_ref[...] = dacc_s[:].astype(dh0_ref.dtype)
        dc0_ref[...] = dcn_s[:].astype(dc0_ref.dtype)


def _bwd_call_blocked(gates, c_prev_seq, c_seq, mask, w_hh, checks,
                      dy, dyc, hb=HBLOCK):
    t, b, hd4 = gates.shape
    hd = hd4 // 4
    nb = hd // hb
    rev_blk = lambda i, j: (t - 1 - i, 0, j)
    kernel = functools.partial(_bwd_kernel_blocked, t_total=t, nb=nb,
                               hb=hb)
    return pl.pallas_call(
        kernel,
        grid=(t, nb),
        in_specs=[
            pl.BlockSpec((1, b, 4 * hb), rev_blk),                # gates
            pl.BlockSpec((1, b, hb), rev_blk),                    # C_{t-1}
            pl.BlockSpec((1, b, hb), rev_blk),                    # C_t
            pl.BlockSpec((1, 1, b), lambda i, j: (t - 1 - i, 0, 0)),
            pl.BlockSpec((hd, 4 * hb), lambda i, j: (0, j)),      # w_hh
            pl.BlockSpec((8, hb), lambda i, j: (0, j)),           # checks
            pl.BlockSpec((1, b, hb), rev_blk),                    # dy
            pl.BlockSpec((1, b, hb), rev_blk),                    # dyc
        ],
        out_specs=[
            pl.BlockSpec((1, b, 4 * hb), rev_blk),                # dxw
            pl.BlockSpec((b, hd), lambda i, j: (0, 0)),           # dh0
            pl.BlockSpec((b, hd), lambda i, j: (0, 0)),           # dc0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd4), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),               # dh carry
            pltpu.VMEM((b, hd), jnp.float32),               # dc carry
            pltpu.VMEM((b, hd), jnp.float32),               # dh accum
            pltpu.VMEM((b, hd), jnp.float32),               # dc staging
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(gates, c_prev_seq, c_seq, mask, w_hh, checks, dy, dyc)


def _dw_kernel_blocked(hprev_ref, dgates_ref, dwhh_ref):
    """Grid (nb, T), time innermost: dW block j stays resident in its
    output ref across the whole T loop (the round-7 constant-block
    pattern — the block index map ignores the inner grid dim), so the
    only VMEM-resident weight-gradient tensor is one [H, 4Hb] block,
    never the full [H, 4H] accumulator."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        dwhh_ref[...] = jnp.zeros_like(dwhh_ref)

    h_prev = hprev_ref[0].astype(jnp.float32)           # [B, H]
    dgates = dgates_ref[0].astype(jnp.float32)          # [B, 4Hb]
    dwhh_ref[...] = dwhh_ref[...] + h_prev.T @ dgates


def _dw_call_blocked(h_prev_seq, dgates, hb=HBLOCK):
    t, b, hd4 = dgates.shape
    hd = hd4 // 4
    nb = hd // hb
    return pl.pallas_call(
        _dw_kernel_blocked,
        grid=(nb, t),
        in_specs=[
            pl.BlockSpec((1, b, hd), lambda j, i: (i, 0, 0)),     # H_{t-1}
            pl.BlockSpec((1, b, 4 * hb), lambda j, i: (i, 0, j)),  # dgates
        ],
        out_specs=pl.BlockSpec((hd, 4 * hb), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((hd, hd4), jnp.float32),
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(h_prev_seq, dgates)


@jax.custom_vjp
def _lstm_core_blocked(xw, mask, w_hh, checks, h0, c0):
    """Blocked-tier core: same contract as :func:`_lstm_core` except xw
    [T, B, 4H] and w_hh [H, 4H] arrive in block-gate layout (the
    wrapper permutes; autodiff transposes the permute around this
    boundary).  Returns kept-state sequences in natural layout."""
    h_seq, c_seq, _gates = _fwd_call_blocked(xw, mask, w_hh, checks,
                                             h0, c0)
    return h_seq, c_seq


def _lstm_core_blocked_fwd(xw, mask, w_hh, checks, h0, c0):
    h_seq, c_seq, gates = _fwd_call_blocked(xw, mask, w_hh, checks,
                                            h0, c0)
    return (h_seq, c_seq), (gates, h_seq, c_seq, mask, w_hh, checks,
                            h0, c0)


def _lstm_core_blocked_bwd(res, cts):
    gates, h_seq, c_seq, mask, w_hh, checks, h0, c0 = res
    dh_seq, dc_seq = cts
    hd = h_seq.shape[-1]
    h_prev_seq = jnp.concatenate([h0[None].astype(h_seq.dtype),
                                  h_seq[:-1]], axis=0)
    c_prev_seq = jnp.concatenate([c0[None].astype(c_seq.dtype),
                                  c_seq[:-1]], axis=0)
    dxw, dh0, dc0 = _bwd_call_blocked(
        gates, c_prev_seq, c_seq, mask, w_hh, checks, dh_seq, dc_seq)
    dw_hh = _dw_call_blocked(h_prev_seq, dxw)
    # peephole grads are an O(H) reduction over residues already in
    # HBM (the dgates residue is dxw) — plain XLA, no VMEM pressure
    dxw_n = _from_gate_blocks(dxw, hd, 4)
    dck = jnp.zeros((8, hd), jnp.float32)
    dck = dck.at[0].set(jnp.sum(dxw_n[..., :hd] * c_prev_seq,
                                axis=(0, 1)))
    dck = dck.at[1].set(jnp.sum(dxw_n[..., hd:2 * hd] * c_prev_seq,
                                axis=(0, 1)))
    dck = dck.at[2].set(jnp.sum(dxw_n[..., 3 * hd:] * c_seq,
                                axis=(0, 1)))
    return (dxw.astype(mask.dtype), jnp.zeros_like(mask), dw_hh,
            dck, dh0, dc0)


_lstm_core_blocked.defvjp(_lstm_core_blocked_fwd, _lstm_core_blocked_bwd)


def lstm_fused_sequence_blocked(xw, mask, w_hh, check_i, check_f,
                                check_o, h0, c0):
    """Blocked-tier entry — same batch-major contract as
    :func:`lstm_fused_sequence`, dispatched by
    ``fused_tier(b, h) == "fused_blocked"``."""
    b, t, hd4 = xw.shape
    hd = hd4 // 4
    checks = jnp.zeros((8, hd), jnp.float32)
    if check_i is not None:
        checks = checks.at[0].set(check_i.astype(jnp.float32))
        checks = checks.at[1].set(check_f.astype(jnp.float32))
    if check_o is not None:
        checks = checks.at[2].set(check_o.astype(jnp.float32))
    h0 = jnp.zeros((b, hd), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    c0 = jnp.zeros((b, hd), jnp.float32) if c0 is None \
        else c0.astype(jnp.float32)
    xw_blk = _to_gate_blocks(jnp.moveaxis(xw, 1, 0), hd, 4)
    whh_blk = _to_gate_blocks(w_hh.astype(jnp.float32), hd, 4)
    h_seq, c_seq = _lstm_core_blocked(
        xw_blk,
        jnp.moveaxis(mask, 1, 0).astype(xw.dtype)[:, None, :],
        whh_blk, checks, h0, c0)
    m = mask.astype(jnp.float32)[:, :, None]
    y = jnp.moveaxis(h_seq, 0, 1) * m
    cy = jnp.moveaxis(c_seq, 0, 1) * m
    return y, cy, h_seq[-1], c_seq[-1]

"""Fused LSTM sequence as Pallas TPU kernels.

The reference hand-wrote exactly this kernel tier in CUDA
(``paddle/cuda/src/hl_cuda_lstm.cu`` / ``hl_lstm_ops.cuh``: one kernel
per step fusing the gate elementwise math, state kept in registers) —
SURVEY §7 names the fused lstm step as the Pallas candidate for the
latency-bound regime.  This module goes further than the reference: the
ENTIRE time loop runs inside one kernel launch, with h/c carried in VMEM
scratch across a sequential grid over T and the recurrent weight matrix
resident in VMEM, so XLA's per-scan-step fixed costs (loop bookkeeping,
HBM round-trips for the carry) disappear.

Forward kernel (grid = (T,)): per step, gates = xw_t + h @ w_hh (MXU),
peepholes + sigmoid/tanh gate math (VPU), length-masked state keep —
writes the kept state sequences H, C and the activated gates (backward
residual).

Backward kernel (grid = (T,), reversed block maps): standard BPTT with
dh/dc carries and the dW_hh / peephole-grad accumulators in VMEM f32
scratch, one (dgates @ w_hhᵀ) + one (h_prevᵀ @ dgates) MXU matmul per
step.

Layouts are time-major ([T, B, ·]) so each grid step addresses one
contiguous block.  Shapes that don't tile (B % 8, H % 128) or non-default
activations dispatch to the ``lax.scan`` path in
:mod:`paddle_tpu.ops.recurrent_ops` — same contract, same results.
On non-TPU backends the kernels run in Pallas interpret mode so CPU
tests exercise the exact dispatch used on hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from .pallas_attention import CompilerParams, _interpret  # shared gate


def fused_ok(b: int, h: int) -> bool:
    """Mosaic tiling gate, checked on every backend so interpret-mode
    tests exercise the hardware dispatch.  H is capped so the backward
    kernel's resident f32 w_hh [H, 4H] (H·4H·4 B = 4 MB at H=512) plus
    the dW_hh output accumulator (another 4 MB) plus the streamed
    double-buffered blocks stay inside the 16 MB scoped-vmem budget.
    A False here is no longer silent: the dispatch site
    (ops/recurrent_ops.py::_warn_scan_fallback) logs the scan fallback
    once per shape, and bench.py's hidden=1280 row measures it."""
    return b % 8 == 0 and h % 128 == 0 and h <= 512


def _sig(x):
    return jax.nn.sigmoid(x)


# --------------------------------------------------------------- forward
def _fwd_kernel(xw_ref, m_ref, whh_ref, ck_ref, h0_ref, c0_ref,
                hseq_ref, cseq_ref, gates_ref, h_s, c_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_s[:] = h0_ref[...].astype(jnp.float32)
        c_s[:] = c0_ref[...].astype(jnp.float32)

    h_prev = h_s[:]                                     # [B, H] f32
    c_prev = c_s[:]
    hd = h_prev.shape[-1]
    xw = xw_ref[0].astype(jnp.float32)                  # [B, 4H]
    gates = xw + h_prev @ whh_ref[...].astype(jnp.float32)
    pre_i = gates[:, :hd]
    pre_f = gates[:, hd:2 * hd]
    pre_c = gates[:, 2 * hd:3 * hd]
    pre_o = gates[:, 3 * hd:]
    # peepholes (row 0 = check_i, 1 = check_f, 2 = check_o)
    ck = ck_ref[...].astype(jnp.float32)                # [8, H]
    i = _sig(pre_i + c_prev * ck[0])
    f = _sig(pre_f + c_prev * ck[1])
    g = jnp.tanh(pre_c)
    c = f * c_prev + i * g
    o = _sig(pre_o + c * ck[2])
    h = o * jnp.tanh(c)

    m = m_ref[0, 0].astype(jnp.float32)[:, None]        # [B, 1]
    h_keep = m * h + (1.0 - m) * h_prev
    c_keep = m * c + (1.0 - m) * c_prev
    h_s[:] = h_keep
    c_s[:] = c_keep
    hseq_ref[0] = h_keep.astype(hseq_ref.dtype)
    cseq_ref[0] = c_keep.astype(cseq_ref.dtype)
    gates_ref[0] = jnp.concatenate([i, f, g, o],
                                   axis=-1).astype(gates_ref.dtype)


def _fwd_call(xw, mask, w_hh, checks, h0, c0):
    t, b, hd4 = xw.shape
    hd = hd4 // 4
    return pl.pallas_call(
        _fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd4), lambda i: (i, 0, 0)),   # xw
            pl.BlockSpec((1, 1, b), lambda i: (i, 0, 0)),     # mask
            pl.BlockSpec((hd, hd4), lambda i: (0, 0)),        # w_hh
            pl.BlockSpec((8, hd), lambda i: (0, 0)),          # checks
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # h0
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # c0
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd), lambda i: (i, 0, 0)),    # H
            pl.BlockSpec((1, b, hd), lambda i: (i, 0, 0)),    # C
            pl.BlockSpec((1, b, hd4), lambda i: (i, 0, 0)),   # gates
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd), jnp.float32),
            jax.ShapeDtypeStruct((t, b, hd4), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),                 # h carry
            pltpu.VMEM((b, hd), jnp.float32),                 # c carry
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(xw, mask, w_hh, checks, h0, c0)


# -------------------------------------------------------------- backward
def _bwd_kernel(gates_ref, hprev_ref, cprev_ref, c_ref, m_ref, whh_ref,
                ck_ref, dy_ref, dyc_ref, dxw_ref, dwhh_ref, dck_ref,
                dh0_ref, dc0_ref, dh_s, dc_s, *, t_total):
    """Grid step i visits t = T-1-i (the block index maps reverse time).
    hprev/cprev blocks carry H_{t-1}/C_{t-1} (the wrapper passes the
    state sequences shifted by one with h0/c0 prepended).  dy/dyc are
    the external cotangents on the kept sequences H_t/C_t; they join the
    recurrent carries BEFORE the masked split, so the (1−m) passthrough
    forwards them to earlier steps exactly like the forward keep."""
    i_rev = pl.program_id(0)

    @pl.when(i_rev == 0)
    def _init():
        dh_s[:] = jnp.zeros_like(dh_s)
        dc_s[:] = jnp.zeros_like(dc_s)
        # dW/dck accumulate directly in their (constant-block) output
        # refs — a second VMEM copy as scratch would overflow the 16 MB
        # scoped-vmem budget at H=512
        dwhh_ref[...] = jnp.zeros_like(dwhh_ref)
        dck_ref[...] = jnp.zeros_like(dck_ref)

    hd = dh_s.shape[-1]
    gates = gates_ref[0].astype(jnp.float32)
    g_i = gates[:, :hd]
    g_f = gates[:, hd:2 * hd]
    g_g = gates[:, 2 * hd:3 * hd]
    g_o = gates[:, 3 * hd:]
    h_prev = hprev_ref[0].astype(jnp.float32)
    c_prev = cprev_ref[0].astype(jnp.float32)
    c = c_ref[0].astype(jnp.float32)
    ck = ck_ref[...].astype(jnp.float32)
    m = m_ref[0, 0].astype(jnp.float32)[:, None]

    tanh_c = jnp.tanh(c)
    # total cotangents on the kept states H_t / C_t
    dh_tot = dy_ref[0].astype(jnp.float32) + dh_s[:]
    dc_tot = dyc_ref[0].astype(jnp.float32) + dc_s[:]
    dh = m * dh_tot                                     # raw-h share
    do_pre = dh * tanh_c * g_o * (1.0 - g_o)
    dc = m * dc_tot + dh * g_o * (1.0 - tanh_c * tanh_c) \
        + do_pre * ck[2]                                # raw-c share
    di_pre = dc * g_g * g_i * (1.0 - g_i)
    df_pre = dc * c_prev * g_f * (1.0 - g_f)
    dg_pre = dc * g_i * (1.0 - g_g * g_g)
    dgates = jnp.concatenate([di_pre, df_pre, dg_pre, do_pre], axis=-1)

    dh_prev = dgates @ whh_ref[...].astype(jnp.float32).T
    dc_prev = dc * g_f + di_pre * ck[0] + df_pre * ck[1]

    dh_s[:] = (1.0 - m) * dh_tot + dh_prev
    dc_s[:] = (1.0 - m) * dc_tot + dc_prev
    dwhh_ref[...] = dwhh_ref[...] + h_prev.T @ dgates
    dck_ref[0] = dck_ref[0] + jnp.sum(di_pre * c_prev, axis=0)
    dck_ref[1] = dck_ref[1] + jnp.sum(df_pre * c_prev, axis=0)
    dck_ref[2] = dck_ref[2] + jnp.sum(do_pre * c, axis=0)
    dxw_ref[0] = dgates.astype(dxw_ref.dtype)

    @pl.when(i_rev == t_total - 1)
    def _flush():
        dh0_ref[...] = dh_s[:].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_s[:].astype(dc0_ref.dtype)


def _bwd_call(gates, h_prev_seq, c_prev_seq, c_seq, mask, w_hh, checks,
              dy, dyc):
    t, b, hd4 = gates.shape
    hd = hd4 // 4
    rev3 = lambda i: (t - 1 - i, 0, 0)
    kernel = functools.partial(_bwd_kernel, t_total=t)
    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, hd4), rev3),                  # gates
            pl.BlockSpec((1, b, hd), rev3),                   # H_{t-1}
            pl.BlockSpec((1, b, hd), rev3),                   # C_{t-1}
            pl.BlockSpec((1, b, hd), rev3),                   # C_t
            pl.BlockSpec((1, 1, b), lambda i: (t - 1 - i, 0, 0)),  # mask
            pl.BlockSpec((hd, hd4), lambda i: (0, 0)),        # w_hh
            pl.BlockSpec((8, hd), lambda i: (0, 0)),          # checks
            pl.BlockSpec((1, b, hd), rev3),                   # dy (dH)
            pl.BlockSpec((1, b, hd), rev3),                   # dyc (dC)
        ],
        out_specs=[
            pl.BlockSpec((1, b, hd4), rev3),                  # dxw
            pl.BlockSpec((hd, hd4), lambda i: (0, 0)),        # dw_hh
            pl.BlockSpec((8, hd), lambda i: (0, 0)),          # dchecks
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # dh0
            pl.BlockSpec((b, hd), lambda i: (0, 0)),          # dc0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hd4), jnp.float32),
            jax.ShapeDtypeStruct((hd, hd4), jnp.float32),
            jax.ShapeDtypeStruct((8, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hd), jnp.float32),                 # dh carry
            pltpu.VMEM((b, hd), jnp.float32),                 # dc carry
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(gates, h_prev_seq, c_prev_seq, c_seq, mask, w_hh, checks, dy, dyc)


# ------------------------------------------------------------ custom vjp
@jax.custom_vjp
def _lstm_core(xw, mask, w_hh, checks, h0, c0):
    """xw [T, B, 4H] (input projection + bias already applied), mask
    [T, B], w_hh [H, 4H], checks [8, H] (rows 0..2 = peephole i/f/o,
    rest zero), h0/c0 [B, H].  Returns kept-state sequences
    (H [T, B, Hd], C [T, B, Hd]) in f32."""
    h_seq, c_seq, _gates = _fwd_call(xw, mask, w_hh, checks, h0, c0)
    return h_seq, c_seq


def _lstm_core_fwd(xw, mask, w_hh, checks, h0, c0):
    h_seq, c_seq, gates = _fwd_call(xw, mask, w_hh, checks, h0, c0)
    return (h_seq, c_seq), (gates, h_seq, c_seq, mask, w_hh, checks,
                            h0, c0)


def _lstm_core_bwd(res, cts):
    gates, h_seq, c_seq, mask, w_hh, checks, h0, c0 = res
    dh_seq, dc_seq = cts
    # state sequences shifted one step back, boot state prepended
    h_prev_seq = jnp.concatenate([h0[None].astype(h_seq.dtype),
                                  h_seq[:-1]], axis=0)
    c_prev_seq = jnp.concatenate([c0[None].astype(c_seq.dtype),
                                  c_seq[:-1]], axis=0)
    dxw, dw_hh, dck, dh0, dc0 = _bwd_call(
        gates, h_prev_seq, c_prev_seq, c_seq, mask, w_hh, checks,
        dh_seq, dc_seq)
    # mask was cast to xw's dtype in the wrapper, so it carries the
    # input dtype for the cotangent cast
    return (dxw.astype(mask.dtype), jnp.zeros_like(mask), dw_hh,
            dck, dh0, dc0)


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def lstm_fused_sequence(xw, mask, w_hh, check_i, check_f, check_o,
                        h0, c0):
    """Batch-major wrapper: xw [B, T, 4H] pre-projected (+bias), mask
    [B, T]; returns (y [B, T, H] masked hidden outputs, cy [B, T, H]
    masked cell outputs, final_h [B, H], final_c [B, H]) in f32 —
    callers cast per their dtype policy.  XLA dead-code-eliminates the
    cy mask-multiply when the caller drops it.
    """
    b, t, hd4 = xw.shape
    hd = hd4 // 4
    checks = jnp.zeros((8, hd), jnp.float32)
    if check_i is not None:
        checks = checks.at[0].set(check_i.astype(jnp.float32))
        checks = checks.at[1].set(check_f.astype(jnp.float32))
    if check_o is not None:
        checks = checks.at[2].set(check_o.astype(jnp.float32))
    h0 = jnp.zeros((b, hd), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    c0 = jnp.zeros((b, hd), jnp.float32) if c0 is None \
        else c0.astype(jnp.float32)
    h_seq, c_seq = _lstm_core(
        jnp.moveaxis(xw, 1, 0),
        jnp.moveaxis(mask, 1, 0).astype(xw.dtype)[:, None, :],
        w_hh.astype(jnp.float32), checks, h0, c0)
    m = mask.astype(jnp.float32)[:, :, None]
    y = jnp.moveaxis(h_seq, 0, 1) * m
    cy = jnp.moveaxis(c_seq, 0, 1) * m
    return y, cy, h_seq[-1], c_seq[-1]

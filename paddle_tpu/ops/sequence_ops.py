"""Sequence ops over padded :class:`SequenceBatch`.

Re-expresses the reference's offset-vector sequence machinery on dense
padded layouts: ``SequencePoolLayer``/``sequence_pool_op``,
``SequenceLastInstanceLayer``, ``ExpandLayer``/``seq_expand_op``,
``SequenceConcatLayer``, ``SequenceSliceLayer``, ``SequenceReshapeLayer``,
``ContextProjection`` (``paddle/function/ContextProjectionOp``),
``sequence_conv_op`` + ``paddle/operators/math/context_project.h``,
``KmaxSeqScoreLayer``, ``MaxIdLayer``.  Masking replaces the reference's
per-sequence loops — the ops stay static-shaped so XLA can fuse them.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.sequence import SequenceBatch
from ..utils import PaddleTpuError
from .registry import register_op


@register_op("sequence_pool")
def sequence_pool(seq: SequenceBatch, pool_type: str = "average") -> jax.Array:
    """Pool [B, T, D] over valid timesteps → [B, D].

    pool types: average, sum, sqrt (sum/sqrt(len)), max, last, first.
    Reference: ``SequencePoolLayer`` subclasses + ``sequence_pool_op``.
    """
    x = seq.data
    mask = seq.mask(x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    denom = jnp.maximum(seq.length.astype(x.dtype), 1.0)
    denom = denom.reshape((-1,) + (1,) * (x.ndim - 2))
    if pool_type in ("average", "avg", "mean"):
        return jnp.sum(x * mask, axis=1) / denom
    if pool_type == "sum":
        return jnp.sum(x * mask, axis=1)
    if pool_type == "sqrt":
        return jnp.sum(x * mask, axis=1) / jnp.sqrt(denom)
    if pool_type == "max":
        neg = jnp.asarray(-jnp.inf, x.dtype)
        pooled = jnp.max(jnp.where(mask > 0, x, neg), axis=1)
        # all-empty sequences pool to 0 (reference leaves them zeroed)
        return jnp.where(denom > 0, pooled, 0.0)
    if pool_type == "last":
        return seq.last_valid()
    if pool_type == "first":
        return seq.first_valid()
    raise PaddleTpuError(f"unknown pool type {pool_type!r}")


@register_op("seq_expand", "expand")
def seq_expand(x: jax.Array, like: SequenceBatch) -> SequenceBatch:
    """Broadcast per-sequence rows [B, D] across time of ``like`` → [B, T, D]
    (``ExpandLayer`` non-seq→seq mode, ``seq_expand_op``)."""
    t = like.max_len
    data = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    return SequenceBatch(data=data, length=like.length)


@register_op("sequence_concat")
def sequence_concat(a: SequenceBatch, b: SequenceBatch) -> SequenceBatch:
    """Concatenate each pair of sequences in time (``SequenceConcatLayer``).

    Implemented with a roll-based shift so shapes stay static: b's valid
    prefix is placed right after a's valid prefix.
    """
    ta, tb = a.max_len, b.max_len
    d = a.data.shape[2:]
    out_t = ta + tb
    pad_a = jnp.pad(a.data, [(0, 0), (0, tb)] + [(0, 0)] * len(d))
    pad_b = jnp.pad(b.data, [(0, 0), (0, ta)] + [(0, 0)] * len(d))

    def shift(row, n):
        return jnp.roll(row, n, axis=0)

    shifted_b = jax.vmap(shift)(pad_b, a.length)
    t_idx = jnp.arange(out_t, dtype=jnp.int32)
    in_a = t_idx[None, :] < a.length[:, None]
    in_b = (t_idx[None, :] >= a.length[:, None]) & (
        t_idx[None, :] < (a.length + b.length)[:, None])
    sel_a = in_a.reshape(in_a.shape + (1,) * len(d))
    sel_b = in_b.reshape(in_b.shape + (1,) * len(d))
    data = jnp.where(sel_a, pad_a, jnp.where(sel_b, shifted_b, 0))
    return SequenceBatch(data=data, length=a.length + b.length)


@register_op("sequence_slice")
def sequence_slice(seq: SequenceBatch, offset, length) -> SequenceBatch:
    """Per-sequence subsequence [offset, offset+length) (``SequenceSliceLayer``).

    offset/length: [B] int arrays (or scalars).  Output keeps T static.
    """
    b, t = seq.data.shape[:2]
    offset = jnp.broadcast_to(jnp.asarray(offset, jnp.int32), (b,))
    length = jnp.broadcast_to(jnp.asarray(length, jnp.int32), (b,))
    length = jnp.minimum(length, seq.length - offset)

    def shift(row, n):
        return jnp.roll(row, -n, axis=0)

    data = jax.vmap(shift)(seq.data, offset)
    return SequenceBatch(data=data, length=jnp.maximum(length, 0))


@register_op("sequence_reshape")
def sequence_reshape(seq: SequenceBatch, new_dim: int) -> SequenceBatch:
    """Refactor [B, T, D] → [B, T*D/new_dim, new_dim] preserving valid counts
    (``SequenceReshapeLayer``).  Valid lengths must divide evenly at runtime
    (the reference enforces the same)."""
    b, t, d = seq.data.shape
    data = seq.data.reshape(b, t * d // new_dim, new_dim)
    length = seq.length * d // new_dim
    return SequenceBatch(data=data, length=length)


@register_op("context_projection")
def context_projection(seq: SequenceBatch, context_start: int,
                       context_length: int,
                       padding_weights: Optional[jax.Array] = None) -> SequenceBatch:
    """Concatenate a sliding window of neighbor rows per timestep
    → [B, T, context_length*D].

    Reference: ``ContextProjection`` (``paddle/function/ContextProjectionOp``)
    — out-of-range rows are zeros, or trainable begin/end padding rows when
    ``padding_weights`` ([begin_pad+end_pad, D]) is given.
    """
    b, t, d = seq.data.shape
    begin_pad = max(0, -context_start)
    cols = []
    for k in range(context_length):
        off = context_start + k
        rolled = jnp.roll(seq.data, -off, axis=1)
        t_idx = jnp.arange(t, dtype=jnp.int32)[None, :]
        src = t_idx + off
        valid = (src >= 0) & (src < seq.length[:, None])
        col = jnp.where(valid[..., None], rolled, 0.0)
        if padding_weights is not None:
            if off < 0:
                # positions before the sequence use begin-pad row (begin_pad+off ... )
                pad_row = padding_weights[begin_pad + off]
                col = jnp.where((src < 0)[..., None], pad_row, col)
            elif off > 0:
                # positions past the end use end-pad rows indexed by overflow-1
                overflow = jnp.clip(src - seq.length[:, None], 0, off)
                pad_idx = begin_pad + overflow - 1
                pad_val = padding_weights[jnp.clip(pad_idx, 0, padding_weights.shape[0] - 1)]
                use_pad = (src >= seq.length[:, None]) & (t_idx < seq.length[:, None])
                col = jnp.where(use_pad[..., None], pad_val, col)
        cols.append(col)
    out = jnp.concatenate(cols, axis=-1)
    return SequenceBatch(data=out, length=seq.length)


@register_op("sequence_conv")
def sequence_conv(seq: SequenceBatch, w, context_start: int,
                  context_length: int) -> SequenceBatch:
    """Context window + projection (``sequence_conv_op``): w is
    [context_length*D, Dout]."""
    ctx = context_projection(seq, context_start, context_length)
    from .math_ops import matmul

    return SequenceBatch(data=matmul(ctx.data, w), length=seq.length)


@register_op("kmax_seq_score")
def kmax_seq_score(scores: SequenceBatch, beam_size: int) -> jax.Array:
    """Indices of the top-k scores within each sequence
    (``KmaxSeqScoreLayer``) → [B, beam_size] int32, -1 past seq end."""
    s = scores.data
    if s.ndim == 3:
        s = s[..., 0]
    masked = jnp.where(scores.bool_mask(), s, -jnp.inf)
    vals, idx = lax.top_k(masked, beam_size)
    k_in_range = jnp.arange(beam_size)[None, :] < scores.length[:, None]
    return jnp.where(k_in_range, idx, -1)


@register_op("max_id")
def max_id(x: jax.Array, beam_size: int = 1):
    """Per-row argmax ids (``MaxIdLayer``); beam_size>1 → top-k ids."""
    if beam_size == 1:
        return jnp.argmax(x, axis=-1).astype(jnp.int32)
    _, idx = lax.top_k(x, beam_size)
    return idx.astype(jnp.int32)


@register_op("sub_seq")
def sub_seq(seq: SequenceBatch, offsets, sizes) -> SequenceBatch:
    """Alias of sequence_slice with explicit offset/size inputs
    (``SubSequenceLayer``)."""
    return sequence_slice(seq, offsets, sizes)


@register_op("sequence_last_instance")
def sequence_last_instance(seq: SequenceBatch) -> jax.Array:
    return seq.last_valid()


@register_op("sequence_first_instance")
def sequence_first_instance(seq: SequenceBatch) -> jax.Array:
    return seq.first_valid()


@register_op("row_conv")
def row_conv(seq: SequenceBatch, w) -> SequenceBatch:
    """Lookahead row convolution (``RowConvLayer``/``row_conv op``):
    w [future_context, D]; out[t] = sum_k w[k] * x[t+k]."""
    k = w.shape[0]
    acc = jnp.zeros_like(seq.data)
    t_idx = jnp.arange(seq.max_len, dtype=jnp.int32)[None, :]
    for i in range(k):
        rolled = jnp.roll(seq.data, -i, axis=1)
        valid = (t_idx + i) < seq.length[:, None]
        acc = acc + jnp.where(valid[..., None], rolled * w[i], 0.0)
    return SequenceBatch(data=acc, length=seq.length)

"""Functional op library (pure jax; the framework's kernel layer).

Importing this package populates the op registry with the full inventory
(SURVEY §2.2 appendix + gserver layer math).
"""

from . import (  # noqa: F401  (import for registration side effects)
    activations,
    crf_ops,
    embedding_ops,
    loss_ops,
    math_ops,
    nn_ops,
    recurrent_ops,
    sequence_ops,
)
from .activations import ACTIVATIONS, get_activation
from .registry import OPS, get_op, register_op

__all__ = ["ACTIVATIONS", "OPS", "get_activation", "get_op", "register_op"]

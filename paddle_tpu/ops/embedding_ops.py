"""Embedding / sparse-parameter ops.

Replaces ``lookup_table_op`` (+ its SelectedRows sparse gradient),
``TableProjection``, ``NCELayer`` (+ ``MultinomialSampler``),
``HierarchicalSigmoidLayer`` (+ ``MatrixBitCode``), ``SelectiveFullyConnectedLayer``.

TPU-first: lookups are one-hot-free ``take`` gathers; sparse gradients are
expressed as dense-shaped scatter-adds (XLA turns them into efficient
dynamic-update-slices) or, for sharded giant tables, the fixed-capacity
row-gather in :mod:`paddle_tpu.parallel.sparse`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.dtypes import record_op_precision
from .math_ops import matmul
from .registry import register_op


@register_op("lookup_table", "embedding")
def lookup_table(table: jax.Array, ids: jax.Array,
                 padding_idx: Optional[int] = None) -> jax.Array:
    """table [V, D], ids [...] int → [..., D].

    ``padding_idx`` rows read as zeros; the mask is folded into the
    gather itself (padding ids are routed one past the table and the
    fill value supplies the zeros) rather than a full-width ``where``
    over the [..., D] output.
    """
    record_op_precision("lookup_table")
    ids32 = ids.astype(jnp.int32)
    if padding_idx is not None:
        ids32 = jnp.where(ids == padding_idx, table.shape[0], ids32)
    return jnp.take(table, ids32, axis=0, mode="fill", fill_value=0)


@register_op("nce")
def nce_loss(x, labels, w, b, sample_ids, sample_probs,
             num_true: int = 1) -> jax.Array:
    """Noise-contrastive estimation cost (``NCELayer``).

    x: [B, D]; labels: [B] int; w: [V, D]; b: [V];
    sample_ids: [B, S] pre-drawn negative ids; sample_probs: [B, S] their
    noise probabilities (the reference samples from a multinomial over word
    frequency — sampling happens host-side / with jax.random upstream).
    """
    def logits_for(ids):
        wi = jnp.take(w, ids, axis=0)  # [B, K, D]
        bi = jnp.take(b, ids, axis=0)  # [B, K]
        return jnp.einsum("bd,bkd->bk", x, wi) + bi

    pos = logits_for(labels.reshape(-1, 1).astype(jnp.int32))  # [B, 1]
    neg = logits_for(sample_ids.astype(jnp.int32))  # [B, S]
    # P(true) = sigmoid(logit); NCE binary CE against 1 for true, 0 for noise
    pos_loss = jnp.maximum(pos, 0) - pos + jnp.log1p(jnp.exp(-jnp.abs(pos)))
    neg_loss = jnp.maximum(neg, 0) + jnp.log1p(jnp.exp(-jnp.abs(neg)))
    return pos_loss[:, 0] + jnp.sum(neg_loss, axis=-1)


def _bit_codes(labels: jax.Array, num_classes: int, depth: int
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Reference bit-code scheme (``MatrixBitCode.cpp``): code(c) = c +
    num_classes, walking /2 until 1; node index = code/2 - 1, bit = code&1."""
    code = labels.astype(jnp.int32) + num_classes
    nodes, bits, valid = [], [], []
    for _ in range(depth):
        nodes.append(code // 2 - 1)
        bits.append(code & 1)
        valid.append(code > 1)
        code = code // 2
    return (jnp.stack(nodes, -1), jnp.stack(bits, -1),
            jnp.stack(valid, -1))


@register_op("hsigmoid")
def hierarchical_sigmoid(x, labels, w, bias, num_classes: int) -> jax.Array:
    """Hierarchical sigmoid cost (``HierarchicalSigmoidLayer``).

    x: [B, D]; w: [num_classes-1, D]; bias: [num_classes-1].
    Cost = sum over the label's tree path of binary CE at each inner node.
    """
    depth = max(1, int(num_classes - 1).bit_length())
    nodes, bits, valid = _bit_codes(labels, num_classes, depth)
    nodes = jnp.clip(nodes, 0, w.shape[0] - 1)
    wn = jnp.take(w, nodes, axis=0)  # [B, depth, D]
    bn = jnp.take(bias, nodes, axis=0)  # [B, depth]
    logits = jnp.einsum("bd,btd->bt", x, wn) + bn
    # bit==1 → target 1 (reference: pred = sigmoid(sum), cost −log pred for
    # one-bits, −log(1−pred) for zero-bits)
    tgt = bits.astype(logits.dtype)
    ce = jnp.maximum(logits, 0) - logits * tgt + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(jnp.where(valid, ce, 0.0), axis=-1)


@register_op("selective_fc")
def selective_fc(x, w, bias, select_ids: Optional[jax.Array] = None,
                 act: str = "softmax"):
    """Selective fully-connected (``SelectiveFullyConnectedLayer``): compute
    output columns only for ``select_ids`` [B, K]; others are 0/-inf.

    w: [D, V] full table.  With select_ids None it's a plain FC.
    """
    from .activations import get_activation

    if select_ids is None:
        out = matmul(x, w)
        if bias is not None:
            out = out + bias
        return get_activation(act)(out)
    wk = jnp.take(w, select_ids.astype(jnp.int32), axis=1)  # [D, B, K] -> careful
    wk = jnp.moveaxis(wk, 1, 0)  # [B, D, K]
    out = jnp.einsum("bd,bdk->bk", x, wk)
    if bias is not None:
        out = out + jnp.take(bias, select_ids.astype(jnp.int32), axis=0)
    return get_activation(act)(out)


@register_op("sampling_id")
def sampling_id(key, probs: jax.Array) -> jax.Array:
    """Sample one id per row from a probability matrix (``SamplingIdLayer``)."""
    return jax.random.categorical(key, jnp.log(jnp.clip(probs, 1e-20, 1.0)),
                                  axis=-1).astype(jnp.int32)

"""Device and mesh management.

TPU-native replacement for the reference's device layer
(``paddle/platform/place.h:24-71`` CPUPlace/GPUPlace,
``paddle/platform/device_context.h:38-72``, ``paddle/cuda`` device mgmt):
on TPU the unit of execution is not "a device" but a **mesh** of devices that
one jit-compiled program spans.  ``get_mesh()`` builds the process-global
``jax.sharding.Mesh`` from ``FLAGS.mesh_shape`` (or all local devices on a
``data`` axis), and the named-sharding helpers below are what layers and the
trainer use instead of per-device placement.

Axis conventions (used across paddle_tpu.parallel):
  ``data``  — batch (data parallel / DP)
  ``model`` — weight sharding (tensor parallel / sparse table sharding)
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import FLAGS, PaddleTpuError, get_logger

log = get_logger("device")

DATA_AXIS = "data"
MODEL_AXIS = "model"

_mesh: Optional[Mesh] = None


def parse_mesh_shape(spec: str) -> Dict[str, int]:
    """Parse ``'data=4,model=2'`` into an ordered axis→size dict."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise PaddleTpuError(f"bad mesh_shape component {part!r}")
        k, v = part.split("=", 1)
        out[k.strip()] = int(v)
    return out


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if not axes:
        axes = {DATA_AXIS: len(devices)}
    n = int(np.prod(list(axes.values())))
    if n > len(devices):
        raise PaddleTpuError(
            f"mesh {axes} needs {n} devices, have {len(devices)}"
        )
    dev_array = np.array(devices[:n]).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def get_mesh(refresh: bool = False) -> Mesh:
    global _mesh
    if _mesh is None or refresh:
        axes = parse_mesh_shape(FLAGS.mesh_shape) if FLAGS.mesh_shape else None
        _mesh = build_mesh(axes)
        log.info("mesh: %s over %d %s device(s)",
                 dict(zip(_mesh.axis_names, _mesh.devices.shape)),
                 _mesh.devices.size, _mesh.devices.flat[0].platform)
    return _mesh


def set_mesh(mesh: Mesh) -> None:
    global _mesh
    _mesh = mesh


def data_sharding(mesh: Optional[Mesh] = None, rank: int = 2) -> NamedSharding:
    """Batch-dim sharded over ``data``, rest replicated."""
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(DATA_AXIS, *(None,) * (rank - 1)))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape.get(DATA_AXIS, 1)


def default_backend() -> str:
    return jax.default_backend()


def is_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")

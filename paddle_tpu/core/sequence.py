"""Variable-length (and nested) sequence representation.

The reference threads sequences through the whole engine as a flat value
matrix plus start-offset vectors — ``Argument.sequenceStartPositions`` /
``subSequenceStartPositions`` (``paddle/parameter/Argument.h:84-90``), later
generalized as ``LoDTensor`` (``paddle/framework/lod_tensor.h:57-80``).
Offsets imply dynamic shapes, which XLA cannot compile efficiently.

TPU-first re-design: a :class:`SequenceBatch` is a **dense padded** array
``data[B, T, ...]`` plus an int32 ``length[B]`` vector, a static pytree that
jit/scan/shard_map handle natively.  Masks and segment ids are derived inside
the compiled program (free — they fuse into neighbors).  Nested sequences
(sequence-of-subsequence, LoD level 2) are ``data[B, S, T, ...]`` with
``num_subseq[B]`` and ``sub_length[B, S]``.

Host-side, :func:`pad_batch` converts ragged Python/numpy data into a padded
batch (optionally bucketing T to reduce recompilation), and
:func:`lod_to_lengths` / :func:`lengths_to_lod` translate to and from the
reference's offset convention so v1/v2-style data providers keep working.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import PaddleTpuError, enforce


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SequenceBatch:
    """Padded batch of variable-length sequences (LoD level 1).

    data:   [B, T, ...] padded values (padding contents are arbitrary).
    length: [B] int32 valid lengths, 0 <= length <= T.
    """

    data: jax.Array
    length: jax.Array

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, T] 1.0 where valid."""
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return (t[None, :] < self.length[:, None]).astype(dtype)

    def bool_mask(self) -> jax.Array:
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return t[None, :] < self.length[:, None]

    def masked_data(self, fill: float = 0.0) -> jax.Array:
        m = self.bool_mask()
        m = m.reshape(m.shape + (1,) * (self.data.ndim - 2))
        return jnp.where(m, self.data, jnp.asarray(fill, self.data.dtype))

    def total_tokens(self) -> jax.Array:
        return jnp.sum(self.length)

    def with_data(self, data: jax.Array) -> "SequenceBatch":
        return SequenceBatch(data=data, length=self.length)

    def last_valid(self) -> jax.Array:
        """[B, ...] value at position length-1 of each sequence."""
        idx = jnp.maximum(self.length - 1, 0)
        return jnp.take_along_axis(
            self.data, idx.reshape(-1, 1, *(1,) * (self.data.ndim - 2)), axis=1
        ).squeeze(1)

    def first_valid(self) -> jax.Array:
        return self.data[:, 0]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NestedSequenceBatch:
    """Padded nested sequences (LoD level 2).

    data:       [B, S, T, ...]
    num_subseq: [B]    int32 — valid subsequences per sequence.
    sub_length: [B, S] int32 — valid tokens per subsequence.
    """

    data: jax.Array
    num_subseq: jax.Array
    sub_length: jax.Array

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    def subseq_mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, S] valid-subsequence mask."""
        s = jnp.arange(self.data.shape[1], dtype=jnp.int32)
        return (s[None, :] < self.num_subseq[:, None]).astype(dtype)

    def token_mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, S, T] valid-token mask."""
        t = jnp.arange(self.data.shape[2], dtype=jnp.int32)
        tok = (t[None, None, :] < self.sub_length[:, :, None]).astype(dtype)
        return tok * self.subseq_mask(dtype)[:, :, None]

    def flatten_to_subseq(self) -> SequenceBatch:
        """View the inner level as a flat [B*S, T, ...] SequenceBatch."""
        b, s = self.data.shape[:2]
        data = self.data.reshape((b * s,) + self.data.shape[2:])
        length = (self.sub_length * self.subseq_mask(jnp.int32)).reshape(b * s)
        return SequenceBatch(data=data, length=length)

    def outer(self) -> SequenceBatch:
        """The outer level as a sequence of subsequence-slots."""
        return SequenceBatch(data=self.data, length=self.num_subseq)


SeqOrArray = Union[jax.Array, SequenceBatch, NestedSequenceBatch]


def value_of(x: SeqOrArray) -> jax.Array:
    return x.data if isinstance(x, (SequenceBatch, NestedSequenceBatch)) else x


def like(template: SeqOrArray, data: jax.Array) -> SeqOrArray:
    """Re-wrap ``data`` with the sequence metadata of ``template``."""
    if isinstance(template, SequenceBatch):
        return SequenceBatch(data=data, length=template.length)
    if isinstance(template, NestedSequenceBatch):
        return NestedSequenceBatch(
            data=data,
            num_subseq=template.num_subseq,
            sub_length=template.sub_length,
        )
    return data


# ---------------------------------------------------------------- host side

def bucket_length(n: int, buckets: Optional[Sequence[int]] = None,
                  multiple: int = 8) -> int:
    """Round a max-length up to a bucket to bound recompilation count."""
    if buckets:
        for b in sorted(buckets):
            if n <= b:
                return b
        return max(buckets)
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def pad_batch(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
              dtype=None, pad_value: float = 0,
              buckets: Optional[Sequence[int]] = None) -> SequenceBatch:
    """Pad a ragged list of [t_i, ...] arrays into a SequenceBatch."""
    seqs = [np.asarray(s) for s in seqs]
    lengths = np.array([s.shape[0] for s in seqs], dtype=np.int32)
    t = max_len or bucket_length(int(lengths.max(initial=1)), buckets)
    trailing = seqs[0].shape[1:] if seqs else ()
    dtype = dtype or (seqs[0].dtype if seqs else np.float32)
    out = np.full((len(seqs), t) + trailing, pad_value, dtype=dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], t)
        out[i, :n] = s[:n]
    return SequenceBatch(data=jnp.asarray(out),
                         length=jnp.asarray(np.minimum(lengths, t)))


def pad_nested_batch(seqs: Sequence[Sequence[np.ndarray]],
                     max_sub: Optional[int] = None,
                     max_len: Optional[int] = None,
                     dtype=None, pad_value: float = 0) -> NestedSequenceBatch:
    """Pad list-of-list-of-arrays into a NestedSequenceBatch."""
    b = len(seqs)
    num_sub = np.array([len(s) for s in seqs], dtype=np.int32)
    s_dim = max_sub or max(1, int(num_sub.max(initial=1)))
    all_sub = [np.asarray(x) for seq in seqs for x in seq]
    t_dim = max_len or bucket_length(
        max((x.shape[0] for x in all_sub), default=1))
    trailing = all_sub[0].shape[1:] if all_sub else ()
    dtype = dtype or (all_sub[0].dtype if all_sub else np.float32)
    data = np.full((b, s_dim, t_dim) + trailing, pad_value, dtype=dtype)
    sub_len = np.zeros((b, s_dim), dtype=np.int32)
    for i, seq in enumerate(seqs):
        for j, x in enumerate(seq[:s_dim]):
            x = np.asarray(x)
            n = min(x.shape[0], t_dim)
            data[i, j, :n] = x[:n]
            sub_len[i, j] = n
    return NestedSequenceBatch(
        data=jnp.asarray(data),
        num_subseq=jnp.asarray(np.minimum(num_sub, s_dim)),
        sub_length=jnp.asarray(sub_len),
    )


def lod_to_lengths(offsets: Sequence[int]) -> np.ndarray:
    """Reference start-offset vector [0, n1, n1+n2, ...] → lengths."""
    offs = np.asarray(offsets, dtype=np.int64)
    enforce(offs.ndim == 1 and offs[0] == 0, "LoD offsets must start at 0")
    return np.diff(offs).astype(np.int32)


def lengths_to_lod(lengths: Sequence[int]) -> np.ndarray:
    """Lengths → reference start-offset vector (Argument.h convention)."""
    return np.concatenate(
        [[0], np.cumsum(np.asarray(lengths, dtype=np.int64))]
    )


def flat_to_padded(flat: np.ndarray, offsets: Sequence[int],
                   max_len: Optional[int] = None) -> SequenceBatch:
    """Reference flat-matrix+offsets layout → padded SequenceBatch."""
    lengths = lod_to_lengths(offsets)
    seqs = [flat[offsets[i]:offsets[i + 1]] for i in range(len(lengths))]
    return pad_batch(seqs, max_len=max_len)


def padded_to_flat(batch: SequenceBatch) -> Tuple[np.ndarray, np.ndarray]:
    """Padded SequenceBatch → (flat matrix, offsets) on host."""
    data = np.asarray(batch.data)
    lengths = np.asarray(batch.length)
    flat = np.concatenate([data[i, : lengths[i]] for i in range(len(lengths))]
                          ) if len(lengths) else data.reshape((0,) + data.shape[2:])
    return flat, lengths_to_lod(lengths)

from .device import (
    DATA_AXIS,
    MODEL_AXIS,
    build_mesh,
    data_sharding,
    get_mesh,
    is_tpu,
    num_data_shards,
    replicated,
    set_mesh,
)
from .dtypes import Policy, current_policy, full_precision, policy_scope
from .sequence import (
    NestedSequenceBatch,
    SequenceBatch,
    flat_to_padded,
    lengths_to_lod,
    like,
    lod_to_lengths,
    pad_batch,
    pad_nested_batch,
    padded_to_flat,
    value_of,
)

"""Precision policy.

The reference computes in fp32 (fp64 behind ``WITH_DOUBLE``); on TPU the MXU
wants bfloat16 inputs with fp32 accumulation.  The policy object carries the
three dtypes modern mixed-precision uses (param/compute/output) and is what
layers consult instead of hard-coding dtypes.  ``checkgrad`` mode forces full
fp32 so finite-difference tolerances hold (SURVEY §7 hard parts).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator

import jax.numpy as jnp

from ..utils import FLAGS


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32

    def cast_compute(self, *xs):
        out = tuple(
            x.astype(self.compute_dtype)
            if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x
            for x in xs
        )
        return out if len(out) != 1 else out[0]

    def cast_output(self, x):
        if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.output_dtype)
        return x


_f32 = Policy(jnp.float32, jnp.float32, jnp.float32)
_bf16 = Policy(jnp.float32, jnp.bfloat16, jnp.float32)
# Full-bf16 activations: layer outputs stay bf16, halving activation HBM
# traffic (the usual TPU bottleneck).  Params and losses remain fp32;
# numerically-sensitive ops (softmax, log, batch-norm stats) compute in
# fp32 internally regardless.  Enabled with --bf16_activations.
_bf16_act = Policy(jnp.float32, jnp.bfloat16, jnp.bfloat16)

_override: list = []


def current_policy() -> Policy:
    if _override:
        return _override[-1]
    if not FLAGS.use_bf16:
        return _f32
    return _bf16_act if FLAGS.bf16_activations else _bf16


@contextlib.contextmanager
def policy_scope(policy: Policy) -> Iterator[None]:
    _override.append(policy)
    try:
        yield
    finally:
        _override.pop()


@contextlib.contextmanager
def full_precision() -> Iterator[None]:
    """fp32 everywhere — used by the gradient checker."""
    with policy_scope(_f32):
        yield

"""Precision policy.

The reference computes in fp32 (fp64 behind ``WITH_DOUBLE``); on TPU the MXU
wants bfloat16 inputs with fp32 accumulation.  The policy object carries the
three dtypes modern mixed-precision uses (param/compute/output) and is what
layers consult instead of hard-coding dtypes.  ``checkgrad`` mode forces full
fp32 so finite-difference tolerances hold (SURVEY §7 hard parts).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ..utils import FLAGS

# ---------------------------------------------------------------------
# Canonical dtype-name <-> numpy mapping (the DataType proto equivalent).
#
# bfloat16 is the one name plain numpy cannot parse (``np.dtype("bfloat16")``
# raises — the type lives in ml_dtypes, re-exported as ``jnp.bfloat16``),
# so every boundary that round-trips dtypes BY NAME — DataFeeder feeds,
# serving manifests (``serving/export._feed_spec`` / ``loader``),
# checkpoint var metadata — resolves through this table instead of
# ``np.dtype(name)`` directly.
_NP_DTYPES: Dict[str, np.dtype] = {
    name: np.dtype(t) for name, t in {
        "float32": np.float32, "float64": np.float64,
        "float16": np.float16, "bfloat16": jnp.bfloat16,
        "int8": np.int8, "int16": np.int16,
        "int32": np.int32, "int64": np.int64,
        "uint8": np.uint8, "bool": np.bool_,
    }.items()
}


def np_dtype(name) -> np.dtype:
    """Dtype name (or dtype-like) → numpy dtype, bfloat16 included."""
    if isinstance(name, str) and name in _NP_DTYPES:
        return _NP_DTYPES[name]
    return np.dtype(name)


def dtype_name(dt) -> str:
    """Canonical string name of a (numpy/jax) dtype — the inverse of
    :func:`np_dtype`; ``str(np.dtype)`` already yields "bfloat16" for
    the ml_dtypes extension type."""
    return str(np.dtype(dt))


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32

    def cast_compute(self, *xs):
        out = tuple(
            x.astype(self.compute_dtype)
            if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x
            for x in xs
        )
        return out if len(out) != 1 else out[0]

    def cast_output(self, x):
        if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.output_dtype)
        return x


_f32 = Policy(jnp.float32, jnp.float32, jnp.float32)
_bf16 = Policy(jnp.float32, jnp.bfloat16, jnp.float32)
# Full-bf16 activations: layer outputs stay bf16, halving activation HBM
# traffic (the usual TPU bottleneck).  Params and losses remain fp32;
# numerically-sensitive ops (softmax, log, batch-norm stats) compute in
# fp32 internally regardless.  Enabled with --bf16_activations.
_bf16_act = Policy(jnp.float32, jnp.bfloat16, jnp.bfloat16)

_override: list = []


def resolve_precision(opt_config=None) -> str:
    """The active end-to-end precision policy name: "fp32" | "bf16".

    An explicit ``OptimizationConfig.precision`` wins; empty inherits
    the ``--precision`` flag (default fp32).  This is the ONE resolution
    point the trainer, the op-level policy, and the bench stamp share.
    """
    prec = getattr(opt_config, "precision", "") or FLAGS.precision
    if prec not in ("fp32", "bf16"):
        raise ValueError(
            f"precision must be 'fp32' or 'bf16', got {prec!r}")
    return prec


def policy_for(precision: str) -> Policy:
    """Op-dispatch policy of a named precision: bf16 = bf16 compute
    with fp32 accumulation/outputs (bf16 activation storage only when
    --bf16_activations additionally opts in); fp32 = full fp32."""
    if precision == "bf16":
        return _bf16_act if FLAGS.bf16_activations else _bf16
    return _f32


def current_policy() -> Policy:
    if _override:
        return _override[-1]
    if FLAGS.precision == "bf16":
        # the one-flag mixed-precision policy overrides the legacy knobs
        return policy_for("bf16")
    if not FLAGS.use_bf16:
        return _f32
    return _bf16_act if FLAGS.bf16_activations else _bf16


@contextlib.contextmanager
def policy_scope(policy: Policy) -> Iterator[None]:
    # the override stack is a TRACE-TIME construct by design: ops read
    # it while the jaxpr is built, and the finally rebalances it even
    # when tracing aborts — no state leaks into the compiled program
    _override.append(policy)   # ptpu: lint-ok[PT-TRACE] trace-time stack
    try:
        yield
    finally:
        _override.pop()        # ptpu: lint-ok[PT-TRACE] trace-time stack


@contextlib.contextmanager
def full_precision() -> Iterator[None]:
    """fp32 everywhere — used by the gradient checker."""
    with policy_scope(_f32):
        yield


def record_op_precision(op: str) -> None:
    """Tick ``precision_dispatch_total{op,dtype}``: which compute dtype
    an op family actually dispatched with.  Ops run at TRACE time under
    jit, so this counts once per compiled program per shape — the same
    convention as ``rnn_dispatch_total``/``conv_dispatch_total`` — and
    the artifact/test answer to "did the bf16 policy actually reach
    this kernel" no longer rests on reading the lowering."""
    from ..observe import counter  # lazy: keeps core import-light

    counter(
        "precision_dispatch_total",
        "op dispatches by resolved compute dtype (trace-time: one tick "
        "per compiled program per shape, labels op + policy compute "
        "dtype)",
    ).inc(op=op, dtype=dtype_name(current_policy().compute_dtype))


def dispatch_dtypes(opt_config=None) -> Dict[str, str]:
    """Resolved per-op-tier dtypes of the active policy — the
    self-describing precision stamp bench.py attaches to every JSON
    line (the round-8 ``path``-field pattern, applied to dtype)."""
    prec = resolve_precision(opt_config)
    pol = policy_for(prec) if prec == "bf16" else current_policy()
    cd, od = dtype_name(pol.compute_dtype), dtype_name(pol.output_dtype)
    return {
        "policy": prec,
        "matmul": cd, "conv": cd, "rnn_gates": cd, "attention": cd,
        # accumulator/carry tiers are pinned fp32 by construction:
        # BN stats (ops/nn_ops._bn_stats), Pallas RNN VMEM gate math,
        # flash-attention accumulators — regardless of compute dtype
        "bn_stats": "float32", "fused_rnn_state": "float32",
        "attention_accum": "float32",
        "scan_carry": od, "activations": od,
        "master_params": "float32", "optimizer_state": "float32",
    }

"""Model-file interop tools: merged single-file models, config dumping,
and reference ``Parameter`` raw-buffer I/O.

Reference surfaces:

- ``paddle_merge_model`` (``paddle/trainer/MergeModel.cpp``): fuse config
  + trained parameters into ONE deployable file — ``int64 config_size``,
  serialized config, then every parameter in declaration order, each as a
  ``Parameter::save`` stream.
- ``Parameter::save/load`` raw buffers
  (``paddle/parameter/Parameter.h:60,263-267``): per-parameter binary file
  ``{int32 format; uint32 valueSize; uint64 size}`` header + fp32 data —
  the format of every ``pass-%05d/<param_name>`` file a reference-trained
  job writes (``ParamUtil.cpp:71-92``).  We read and write this layout
  bit-compatibly, so reference-trained models import directly.
- ``dump_config`` / ``show_pb``
  (``python/paddle/utils/dump_config.py``): print the parsed model config.

The merged file keeps the reference's framing (size-prefixed config, then
``Parameter::save`` streams in config order) with the config serialized as
JSON — see README "wire compatibility" for why protobuf wire format is
not reproduced.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..config.model_config import ModelConfig, ParameterConfig
from ..utils import PaddleTpuError, enforce, get_logger

log = get_logger("interop")

# Parameter.h:263-267 — int32 format, uint32 valueSize, uint64 size
_PARAM_HEADER = struct.Struct("<iIQ")
PARAM_FORMAT_ORIGINAL = 0          # PARAM_FORMAT_ORIGINAL in Parameter.h
MERGED_MAGIC = b"PTPU"


def write_parameter(f, value: np.ndarray,
                    fmt: int = PARAM_FORMAT_ORIGINAL) -> None:
    """``Parameter::save(ostream&)``: header + row-major fp32 buffer."""
    arr = np.ascontiguousarray(np.asarray(value), dtype=np.float32)
    f.write(_PARAM_HEADER.pack(fmt, 4, arr.size))
    f.write(arr.tobytes())


def read_parameter(f, expect_size: Optional[int] = None) -> np.ndarray:
    """``Parameter::load(istream&)`` counterpart (flat fp32 vector)."""
    raw = f.read(_PARAM_HEADER.size)
    enforce(len(raw) == _PARAM_HEADER.size,
            "truncated parameter stream (short header)")
    fmt, value_size, size = _PARAM_HEADER.unpack(raw)
    enforce(fmt == PARAM_FORMAT_ORIGINAL,
            f"unsupported parameter format {fmt} (only "
            f"PARAM_FORMAT_ORIGINAL={PARAM_FORMAT_ORIGINAL}; MKLDNN "
            "packed formats are GPU/CPU-layout specific)")
    enforce(value_size == 4,
            f"parameter valueSize {value_size} != 4 (fp32); double builds "
            "(WITH_DOUBLE) are out of scope")
    if expect_size is not None:
        enforce(size == expect_size,
                f"parameter size {size} != expected {expect_size}")
    data = f.read(size * 4)
    enforce(len(data) == size * 4, "truncated parameter stream (short body)")
    return np.frombuffer(data, dtype=np.float32).copy()


def save_parameter_file(path: str, value: np.ndarray) -> None:
    with open(path, "wb") as f:
        write_parameter(f, value)


def load_parameter_file(path: str,
                        dims: Optional[List[int]] = None) -> np.ndarray:
    with open(path, "rb") as f:
        flat = read_parameter(f)
    return flat.reshape(dims) if dims else flat


def load_reference_model_dir(model_dir: str, model: ModelConfig,
                             strict: bool = False
                             ) -> Dict[str, np.ndarray]:
    """Load a reference ``pass-%05d`` directory (one ``Parameter::save``
    file per parameter, named by parameter name) against our parsed
    config — the reference-trained-model import path."""
    params: Dict[str, np.ndarray] = {}
    for spec in model.parameters:
        path = os.path.join(model_dir, spec.name)
        if not os.path.exists(path):
            if strict:
                raise PaddleTpuError(
                    f"{model_dir}: missing parameter file {spec.name!r}")
            log.warning("missing parameter file %s", spec.name)
            continue
        flat = load_parameter_file(path)
        if spec.dims and int(np.prod(spec.dims)) == flat.size:
            flat = flat.reshape(spec.dims)
        params[spec.name] = flat
    return params


def save_reference_model_dir(model_dir: str,
                             params: Dict[str, np.ndarray]) -> None:
    """Write params as a reference-layout model dir (round-trip tool)."""
    os.makedirs(model_dir, exist_ok=True)
    for name, value in params.items():
        save_parameter_file(os.path.join(model_dir, name), value)


def with_full_param_specs(model: ModelConfig) -> ModelConfig:
    """Return the config with ``parameters`` completed to the FULL
    layer-derived spec list (name-sorted, like ``init_params``) — config
    files usually declare only overrides, but the merged-file/model-dir
    formats need every parameter's name + dims."""
    from ..layers.network import NeuralNetwork

    net = NeuralNetwork(model)
    model.parameters = [net.param_specs[n]
                        for n in sorted(net.param_specs)]
    return model


# ------------------------------------------------------------ merge_model

def merge_model(model: ModelConfig, params: Dict[str, np.ndarray],
                out_path: str) -> None:
    """``paddle_merge_model``: one self-contained file = size-prefixed
    config + ``Parameter::save`` streams in config parameter order
    (``MergeModel.cpp:50-60`` framing, JSON config payload)."""
    blob = MERGED_MAGIC + model.to_json().encode("utf-8")
    with open(out_path, "wb") as f:
        f.write(struct.pack("<q", len(blob)))
        f.write(blob)
        for spec in model.parameters:
            enforce(spec.name in params,
                    f"merge_model: parameter {spec.name!r} not loaded")
            write_parameter(f, params[spec.name])


def load_merged_model(path: str) -> Tuple[ModelConfig,
                                          Dict[str, np.ndarray]]:
    with open(path, "rb") as f:
        (size,) = struct.unpack("<q", f.read(8))
        blob = f.read(size)
        enforce(blob[:4] == MERGED_MAGIC,
                f"{path}: not a paddle-tpu merged model (reference "
                "protobuf-config merged models need their original "
                "config .py; see README wire-compatibility note)")
        model = ModelConfig.from_json(blob[4:].decode("utf-8"))
        params: Dict[str, np.ndarray] = {}
        for spec in model.parameters:
            flat = read_parameter(f, expect_size=spec.size or None)
            if spec.dims and int(np.prod(spec.dims)) == flat.size:
                flat = flat.reshape(spec.dims)
            params[spec.name] = flat
    return model, params


def checkpoint_to_params(path: str) -> Dict[str, np.ndarray]:
    """Accept either our ``pass-%05d`` npz checkpoint or a reference
    raw-buffer model dir."""
    npz = os.path.join(path, "params.npz")
    if os.path.exists(npz):
        with np.load(npz) as data:
            return {k: data[k] for k in data.files}
    return {}

"""Checkpoint save/load.

Reference surfaces covered: per-pass parameter dirs ``pass-%05d``
(``paddle/trainer/ParamUtil.cpp:71-92``), v2 ``parameters.to_tar/from_tar``,
and — unlike the legacy C++ path — **optimizer state and batch-norm buffers
are checkpointed too** (the reference only does this in the Go pserver,
``go/pserver/service.go:146``).  Format: one ``.npz`` per state collection +
a JSON manifest with step counters and config digest, written atomically so
a preempted TPU job never sees a torn checkpoint.

Integrity + retention (robustness pass): the manifest records a SHA-256
digest and byte size per file; :func:`verify_checkpoint` re-checks them,
:func:`latest_valid_checkpoint` scans backward past corrupt/torn dirs
(quarantining them as ``.corrupt-*`` so the scan never re-reads them),
and :func:`sweep_retention` keeps the newest ``--ckpt_keep`` dirs after
each save.  ``--ckpt_verify=false`` restores the legacy blind load.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, Iterator, List, Optional

import jax
import numpy as np

from ..observe import counter, histogram, trace
from ..utils import FLAGS, PaddleTpuError, get_logger

log = get_logger("checkpoint")


def _flatten_state(tree) -> Dict[str, np.ndarray]:
    flat = {}
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for i, leaf in enumerate(leaves):
        flat[f"leaf_{i}"] = np.asarray(leaf)
    return flat, treedef


# ----------------------------------------------------- sharded layout
def _shard_layout(x) -> Optional[Dict[str, int]]:
    """``{"dim": d, "shards": n}`` when ``x`` is a committed jax array
    sharded over some mesh axis (the FSDP placement), else None."""
    sh = getattr(x, "sharding", None)
    spec = getattr(sh, "spec", None)
    mesh = getattr(sh, "mesh", None)
    if spec is None or mesh is None:
        return None
    from ..parallel.sharding import spec_shard_info
    info = spec_shard_info(spec, mesh)
    if info is None:
        return None
    return {"dim": int(info[0]), "shards": int(info[1])}


def _owned_shard_indices(x, dim: int, n: int) -> List[int]:
    """Shard indices along ``dim`` this PROCESS holds locally — on a
    multi-host mesh each host writes only its own shard files (the
    per-host half of the sharded-checkpoint format); single-host
    meshes own everything."""
    try:
        size = x.shape[dim] // n
        idxs = set()
        for s in x.addressable_shards:
            sl = s.index[dim]
            idxs.add(int((sl.start or 0) // max(size, 1)))
        if idxs:
            return sorted(idxs)
    except (AttributeError, IndexError, TypeError):
        pass        # numpy leaf / backend without addressable_shards:
        # fall through to owning every shard (single-host behaviour)
    return list(range(n))


def _shard_file(kind: str, i: int, n: int) -> str:
    return f"{kind}.shard-{i:05d}-of-{n:05d}.npz"


def _write_sharded(tmp: str, kind: str,
                   arrays: Dict[str, Any]) -> Dict[str, Dict[str, int]]:
    """Split the sharded leaves of ``arrays`` into per-shard-index
    ``<kind>.shard-i-of-n.npz`` files (each holds that index's slice
    of every leaf sharded n ways); returns the layout dict the
    manifest records, and REMOVES the sharded keys from ``arrays`` so
    the caller's base ``.npz`` keeps only replicated leaves."""
    layout: Dict[str, Dict[str, int]] = {}
    owned: Dict[int, Dict[int, Dict[str, np.ndarray]]] = {}
    for key in list(arrays):
        info = _shard_layout(arrays[key])
        if info is None:
            continue
        d, n = info["dim"], info["shards"]
        x = arrays.pop(key)
        layout[key] = info
        arr = np.asarray(x)
        size = arr.shape[d] // n
        for i in _owned_shard_indices(x, d, n):
            sl = [slice(None)] * arr.ndim
            sl[d] = slice(i * size, (i + 1) * size)
            owned.setdefault(n, {}).setdefault(i, {})[key] = \
                arr[tuple(sl)]
    for n, by_index in owned.items():
        for i, chunk in by_index.items():
            np.savez(os.path.join(tmp, _shard_file(kind, i, n)), **chunk)
    return layout


def _read_sharded(ckpt_dir: str, kind: str,
                  layout: Dict[str, Dict[str, int]]) -> Dict[str, np.ndarray]:
    """Reassemble the global arrays of one sharded collection by
    concatenating its shard files along each leaf's recorded dim —
    mesh-free, so a load onto ANY mesh shape (1→8, 8→1, 4×2→8) just
    re-places the full arrays (resharding on load)."""
    files: Dict[str, Any] = {}
    out: Dict[str, np.ndarray] = {}
    try:
        for key, info in layout.items():
            d, n = int(info["dim"]), int(info["shards"])
            parts = []
            for i in range(n):
                fname = _shard_file(kind, i, n)
                if fname not in files:
                    path = os.path.join(ckpt_dir, fname)
                    if not os.path.exists(path):
                        raise PaddleTpuError(
                            f"sharded checkpoint {ckpt_dir!r} is "
                            f"missing {fname} (manifest lists "
                            f"{key!r} as {n}-way sharded)")
                    files[fname] = np.load(path)
                parts.append(files[fname][key])
            out[key] = np.concatenate(parts, axis=d) if n > 1 \
                else parts[0]
    finally:
        for z in files.values():
            z.close()
    return out


def _manifest_shards(ckpt_dir: str, kind: str) -> Dict[str, Dict[str, int]]:
    """The manifest's recorded shard layout for one collection
    (``{}`` for legacy/unsharded checkpoints)."""
    try:
        man = load_manifest(ckpt_dir)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}
    return (man.get("shards") or {}).get(kind, {})


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_checkpoint(save_dir: str, pass_id: int, params: Dict[str, Any],
                    opt_state: Any = None, buffers: Optional[Dict] = None,
                    meta: Optional[Dict] = None,
                    keep: Optional[int] = None,
                    shard: bool = False) -> str:
    """Write ``<save_dir>/pass-%05d`` atomically; returns the dir path.

    The manifest carries per-file SHA-256 digests (``files``) so loaders
    can detect bit-flips/truncation, and a successful save sweeps
    retention (keep the newest ``keep`` dirs, default ``--ckpt_keep``).

    ``shard=True`` (the trainer passes it under ``--fsdp``) writes a
    **sharded checkpoint**: every leaf committed with a sharded
    NamedSharding lands as per-shard-index files
    (``params.shard-i-of-n.npz`` / ``opt_state.shard-i-of-n.npz`` —
    on a multi-host mesh each host writes only the indices it owns)
    while replicated leaves stay in the base archives; the manifest
    records the layout under ``"shards"`` and the per-file digests
    cover shard files exactly like base files, so verify/quarantine/
    retention and the chaos gauntlet carry over unchanged.  Loaders
    reassemble global arrays from the recorded layout, so a load onto
    a DIFFERENT mesh shape re-places cleanly (resharding on load)."""
    final = os.path.join(save_dir, f"pass-{pass_id:05d}")
    os.makedirs(save_dir, exist_ok=True)
    t0 = time.perf_counter()
    with trace.span("ckpt_save", pass_id=pass_id):
        tmp = tempfile.mkdtemp(dir=save_dir, prefix=".tmp-ckpt-")
        try:
            manifest = {"pass_id": pass_id, "format": 2, **(meta or {})}
            shards: Dict[str, Dict] = {}
            p_arrays: Dict[str, Any] = dict(params)
            if shard:
                layout = _write_sharded(tmp, "params", p_arrays)
                if layout:
                    shards["params"] = layout
            np.savez(os.path.join(tmp, "params.npz"),
                     **{k: np.asarray(v) for k, v in p_arrays.items()})
            if buffers:
                np.savez(os.path.join(tmp, "buffers.npz"),
                         **{k: np.asarray(v) for k, v in buffers.items()})
            if opt_state is not None:
                leaves, treedef = jax.tree_util.tree_flatten(opt_state)
                o_arrays = {f"leaf_{i}": leaf
                            for i, leaf in enumerate(leaves)}
                if shard:
                    layout = _write_sharded(tmp, "opt_state", o_arrays)
                    if layout:
                        shards["opt_state"] = layout
                np.savez(os.path.join(tmp, "opt_state.npz"),
                         **{k: np.asarray(v)
                            for k, v in o_arrays.items()})
                manifest["opt_treedef"] = str(treedef)
            if shards:
                manifest["shards"] = shards
            # digest every data file; the manifest is written LAST so
            # its presence certifies the .npz files were fully flushed.
            # The --ckpt_verify kill switch disables the save-side
            # hashing cost too (the dir then loads via the legacy
            # structural check).
            if FLAGS.ckpt_verify:
                manifest["files"] = {
                    fname: {"sha256": _sha256_file(
                                os.path.join(tmp, fname)),
                            "bytes": os.path.getsize(
                                os.path.join(tmp, fname))}
                    for fname in sorted(os.listdir(tmp))}
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=1)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    histogram("ckpt_save_seconds",
              "wall time of one atomic checkpoint save (serialize + "
              "digest + rename)").observe(time.perf_counter() - t0)
    counter("ckpt_saves", "checkpoints saved").inc()
    log.info("saved checkpoint %s", final)
    sweep_retention(save_dir, keep)
    return final


def load_params(ckpt_dir: str) -> Dict[str, np.ndarray]:
    path = os.path.join(ckpt_dir, "params.npz")
    if not os.path.exists(path):
        raise PaddleTpuError(f"no params.npz under {ckpt_dir!r}")
    with np.load(path) as z:
        out = {k: z[k] for k in z.files}
    layout = _manifest_shards(ckpt_dir, "params")
    if layout:
        out.update(_read_sharded(ckpt_dir, "params", layout))
    return out


def load_buffers(ckpt_dir: str) -> Dict[str, np.ndarray]:
    path = os.path.join(ckpt_dir, "buffers.npz")
    if not os.path.exists(path):
        return {}
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def load_opt_state(ckpt_dir: str, template: Any) -> Any:
    """Restore optimizer state into the treedef of ``template``
    (reassembling any leaves a sharded save split into shard files)."""
    path = os.path.join(ckpt_dir, "opt_state.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        by_key = {k: z[k] for k in z.files}
    layout = _manifest_shards(ckpt_dir, "opt_state")
    if layout:
        by_key.update(_read_sharded(ckpt_dir, "opt_state", layout))
    leaves = [by_key[f"leaf_{i}"] for i in range(len(by_key))]
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def load_manifest(ckpt_dir: str) -> Dict:
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        return json.load(f)


def _verify_result(ckpt_dir: str) -> str:
    """``"ok"`` | ``"corrupt"`` (definitive mismatch/torn state) |
    ``"unreadable"`` (a transient read fault — EIO/ESTALE on a shared
    filesystem — proved nothing about the data)."""
    if not os.path.isdir(ckpt_dir):
        return "corrupt"
    try:
        manifest = load_manifest(ckpt_dir)
    except (FileNotFoundError, json.JSONDecodeError):
        manifest = None
    except OSError:
        return "unreadable"
    files = (manifest or {}).get("files")
    if files:
        for fname, info in files.items():
            path = os.path.join(ckpt_dir, fname)
            if not os.path.exists(path):
                log.warning("checkpoint %s: %s missing", ckpt_dir, fname)
                return "corrupt"
            try:
                if os.path.getsize(path) != info.get("bytes"):
                    log.warning("checkpoint %s: %s size mismatch",
                                ckpt_dir, fname)
                    return "corrupt"
                if _sha256_file(path) != info.get("sha256"):
                    log.warning("checkpoint %s: %s digest mismatch",
                                ckpt_dir, fname)
                    return "corrupt"
            except OSError as e:
                log.warning("checkpoint %s: %s unreadable (%s)",
                            ckpt_dir, fname, e)
                return "unreadable"
        return "ok"
    # legacy / foreign dir: no digests recorded — check the archives open
    if not os.path.exists(os.path.join(ckpt_dir, "params.npz")):
        return "corrupt"
    for fname in ("params.npz", "buffers.npz", "opt_state.npz"):
        p = os.path.join(ckpt_dir, fname)
        if not os.path.exists(p):
            continue
        try:
            with np.load(p):
                pass
        except OSError:
            return "unreadable"
        except Exception:
            log.warning("checkpoint %s: %s does not open", ckpt_dir, fname)
            return "corrupt"
    return "ok"


def verify_checkpoint(ckpt_dir: str) -> bool:
    """True iff ``ckpt_dir`` passes integrity checks.

    Format-2 checkpoints (manifest with ``files``) re-hash every listed
    file against its recorded SHA-256 + size.  Older dirs (legacy
    manifest, or a bare params.npz from an external tool) degrade to a
    structural check: the archives must exist and open as valid zips.
    """
    with trace.span("ckpt_verify", dir=ckpt_dir), \
            histogram("ckpt_verify_seconds",
                      "wall time of one checkpoint integrity "
                      "verification (digest re-hash or structural "
                      "check)").time():
        return _verify_result(ckpt_dir) == "ok"


def _pass_dirs(save_dir: str) -> List[str]:
    return sorted(d for d in os.listdir(save_dir) if d.startswith("pass-"))


def latest_checkpoint(save_dir: str) -> Optional[str]:
    if not os.path.isdir(save_dir):
        return None
    passes = _pass_dirs(save_dir)
    return os.path.join(save_dir, passes[-1]) if passes else None


def quarantine_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Rename a corrupt checkpoint dir to ``.corrupt-<name>[-N]`` so
    backward scans never re-validate it; returns the new path."""
    parent, name = os.path.split(os.path.normpath(ckpt_dir))
    target = os.path.join(parent, f".corrupt-{name}")
    n = 0
    while os.path.exists(target):
        n += 1
        target = os.path.join(parent, f".corrupt-{name}-{n}")
    try:
        with trace.span("ckpt_quarantine", dir=ckpt_dir):
            os.rename(ckpt_dir, target)
    except OSError as e:
        log.warning("could not quarantine %s (%s)", ckpt_dir, e)
        return None
    counter("ckpt_quarantined",
            "corrupt checkpoint dirs renamed to .corrupt-*").inc()
    log.warning("quarantined corrupt checkpoint %s -> %s", ckpt_dir, target)
    return target


def latest_valid_checkpoint(save_dir: str,
                            quarantine: bool = True) -> Optional[str]:
    """Newest ``pass-*`` dir that passes :func:`verify_checkpoint`,
    scanning backward past corrupt/torn dirs (renamed ``.corrupt-*``
    when ``quarantine``)."""
    if not os.path.isdir(save_dir):
        return None
    for name in reversed(_pass_dirs(save_dir)):
        path = os.path.join(save_dir, name)
        verdict = _verify_result(path)
        if verdict == "ok":
            return path
        log.warning("checkpoint %s failed verification (%s); falling "
                    "back", path, verdict)
        # only DEFINITIVE corruption is quarantined — a transient read
        # fault must not get a valid checkpoint renamed away (and later
        # reaped by the retention sweep)
        if quarantine and verdict == "corrupt":
            quarantine_checkpoint(path)
    return None


def checkpoint_digest(ckpt_dir: str) -> Optional[str]:
    """Content-stable identity of a checkpoint: sha256 over the sorted
    per-file digests in its manifest.  This is the exactly-once key the
    export watcher uses (``serving/rollout.py``) — re-saving identical
    bytes under a new pass id gets the same digest; any data change
    changes it.  None when the manifest is unreadable or predates
    digest recording (``--ckpt_verify=false`` saves)."""
    try:
        files = load_manifest(ckpt_dir).get("files")
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None
    if not files:
        return None
    h = hashlib.sha256()
    for fname in sorted(files):
        h.update(fname.encode())
        h.update(str(files[fname].get("sha256")).encode())
    return h.hexdigest()


# ------------------------------------------------- export pin / lease
def _export_markers(ckpt_dir: str) -> List[str]:
    try:
        return [os.path.join(ckpt_dir, n) for n in os.listdir(ckpt_dir)
                if n.startswith(".exporting-")]
    except OSError:
        return []


def export_pinned(ckpt_dir: str) -> bool:
    """True when a live export lease pins ``ckpt_dir`` against the
    retention sweep: some ``.exporting-<pid>`` marker inside it has an
    mtime fresher than ``--ckpt_export_lease_s``.  Stale markers (a
    SIGKILLed exporter never removes its marker) expire by mtime, so a
    dead exporter cannot pin a checkpoint forever."""
    lease_s = FLAGS.ckpt_export_lease_s
    now = time.time()
    for path in _export_markers(ckpt_dir):
        try:
            if now - os.path.getmtime(path) < lease_s:
                return True
        except OSError:
            continue        # marker vanished between listdir and stat
    return False


@contextlib.contextmanager
def export_lease(ckpt_dir: str) -> Iterator[str]:
    """Pin ``ckpt_dir`` for the duration of an export.

    Writes a ``.exporting-<pid>`` marker INSIDE the checkpoint dir
    (same-directory so the pin travels with the dir and needs no
    side-channel registry); :func:`sweep_retention` skips pinned pass
    dirs, closing the race where a slow export loses its source mid-
    read.  The marker is removed on exit; if the exporter is SIGKILLed
    the marker goes stale and expires via ``--ckpt_export_lease_s``.
    """
    marker = os.path.join(ckpt_dir, f".exporting-{os.getpid()}")
    with open(marker, "w") as f:
        f.write(str(time.time()))
    try:
        yield marker
    finally:
        try:
            os.remove(marker)
        except OSError:
            pass        # dir already reaped (lease expired) or marker
            # removed by hand — nothing left to unpin


# a .tmp-ckpt-* dir older than this is an orphan from a save that was
# SIGKILLed mid-write (no in-process cleanup ran); no live save under
# the election window ever takes this long
_TMP_STALE_S = 3600.0


def _stale_tmp_dirs(save_dir: str) -> List[str]:
    out = []
    now = time.time()
    for name in os.listdir(save_dir):
        if not name.startswith(".tmp-ckpt-"):
            continue
        try:
            if now - os.path.getmtime(os.path.join(save_dir, name)) \
                    > _TMP_STALE_S:
                out.append(name)
        except OSError:
            pass
    return out


def sweep_retention(save_dir: str, keep: Optional[int] = None) -> List[str]:
    """Delete the oldest ``pass-*`` dirs beyond the newest ``keep``
    (default ``--ckpt_keep``; 0 or negative disables).  Returns the
    removed paths."""
    keep = FLAGS.ckpt_keep if keep is None else keep
    if keep is None or keep <= 0 or not os.path.isdir(save_dir):
        return []
    removed = []
    # ckpt_retention: the one checkpoint phase PR 8 left unspanned — a
    # retention stall (slow rmtree on a network filesystem) was
    # invisible in Perfetto between the ckpt_save span and the next step
    with trace.span("ckpt_retention", keep=keep):
        # quarantined dirs are capped by the same keep count — recurring
        # corruption (a bad disk region) must not grow storage
        # unboundedly — and orphaned temp dirs from preemption-killed
        # saves are reaped
        corrupt = sorted(d for d in os.listdir(save_dir)
                         if d.startswith(".corrupt-"))
        for name in _pass_dirs(save_dir)[:-keep] + corrupt[:-keep] \
                + _stale_tmp_dirs(save_dir):
            path = os.path.join(save_dir, name)
            if export_pinned(path):
                # an exporter holds a live lease on this dir — reaping
                # it now would tear the artifact mid-read.  The NEXT
                # sweep gets it once the lease is released or expires.
                counter("ckpt_retention_pinned",
                        "retention-eligible checkpoint dirs skipped "
                        "because a live export lease pins them").inc()
                log.info("retention sweep: %s pinned by export lease, "
                         "skipping", name)
                continue
            try:
                shutil.rmtree(path)
            except OSError as e:
                log.warning("retention sweep could not remove %s (%s)",
                            path, e)
                continue
            removed.append(path)
    if removed:
        counter("ckpt_retention_removed",
                "checkpoint/quarantine/orphan dirs reaped by the "
                "retention sweep").inc(len(removed))
        log.info("retention sweep (keep=%d): removed %s", keep,
                 [os.path.basename(p) for p in removed])
    return removed

"""Checkpoint save/load.

Reference surfaces covered: per-pass parameter dirs ``pass-%05d``
(``paddle/trainer/ParamUtil.cpp:71-92``), v2 ``parameters.to_tar/from_tar``,
and — unlike the legacy C++ path — **optimizer state and batch-norm buffers
are checkpointed too** (the reference only does this in the Go pserver,
``go/pserver/service.go:146``).  Format: one ``.npz`` per state collection +
a JSON manifest with step counters and config digest, written atomically so
a preempted TPU job never sees a torn checkpoint.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils import PaddleTpuError, get_logger

log = get_logger("checkpoint")


def _flatten_state(tree) -> Dict[str, np.ndarray]:
    flat = {}
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for i, leaf in enumerate(leaves):
        flat[f"leaf_{i}"] = np.asarray(leaf)
    return flat, treedef


def save_checkpoint(save_dir: str, pass_id: int, params: Dict[str, Any],
                    opt_state: Any = None, buffers: Optional[Dict] = None,
                    meta: Optional[Dict] = None) -> str:
    """Write ``<save_dir>/pass-%05d`` atomically; returns the dir path."""
    final = os.path.join(save_dir, f"pass-{pass_id:05d}")
    os.makedirs(save_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=save_dir, prefix=".tmp-ckpt-")
    try:
        np.savez(os.path.join(tmp, "params.npz"),
                 **{k: np.asarray(v) for k, v in params.items()})
        if buffers:
            np.savez(os.path.join(tmp, "buffers.npz"),
                     **{k: np.asarray(v) for k, v in buffers.items()})
        manifest = {"pass_id": pass_id, "format": 1, **(meta or {})}
        if opt_state is not None:
            flat, treedef = _flatten_state(opt_state)
            np.savez(os.path.join(tmp, "opt_state.npz"), **flat)
            manifest["opt_treedef"] = str(treedef)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    log.info("saved checkpoint %s", final)
    return final


def load_params(ckpt_dir: str) -> Dict[str, np.ndarray]:
    path = os.path.join(ckpt_dir, "params.npz")
    if not os.path.exists(path):
        raise PaddleTpuError(f"no params.npz under {ckpt_dir!r}")
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def load_buffers(ckpt_dir: str) -> Dict[str, np.ndarray]:
    path = os.path.join(ckpt_dir, "buffers.npz")
    if not os.path.exists(path):
        return {}
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def load_opt_state(ckpt_dir: str, template: Any) -> Any:
    """Restore optimizer state into the treedef of ``template``."""
    path = os.path.join(ckpt_dir, "opt_state.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def load_manifest(ckpt_dir: str) -> Dict:
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        return json.load(f)


def latest_checkpoint(save_dir: str) -> Optional[str]:
    if not os.path.isdir(save_dir):
        return None
    passes = sorted(d for d in os.listdir(save_dir) if d.startswith("pass-"))
    return os.path.join(save_dir, passes[-1]) if passes else None

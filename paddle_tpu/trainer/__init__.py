from . import events
from .checkpoint import (
    latest_checkpoint,
    latest_valid_checkpoint,
    load_buffers,
    load_opt_state,
    load_params,
    save_checkpoint,
    sweep_retention,
    verify_checkpoint,
)
from .trainer import Trainer, optimizer_from_config

__all__ = [
    "Trainer",
    "events",
    "latest_checkpoint",
    "latest_valid_checkpoint",
    "load_buffers",
    "load_opt_state",
    "load_params",
    "optimizer_from_config",
    "save_checkpoint",
    "sweep_retention",
    "verify_checkpoint",
]

"""Trainer events (port of ``python/paddle/v2/event.py``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class WithMetric:
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass(WithMetric):
    pass_id: int = 0
    evaluator: Any = None


@dataclasses.dataclass
class BeginIteration:
    pass_id: int = 0
    batch_id: int = 0


@dataclasses.dataclass
class EndIteration(WithMetric):
    pass_id: int = 0
    batch_id: int = 0
    cost: float = 0.0


@dataclasses.dataclass
class TestResult(WithMetric):
    pass_id: int = 0
    cost: float = 0.0

"""Training driver.

Equivalent of ``paddle/trainer/Trainer.{h,cpp}`` + ``TrainerInternal`` +
the v2 ``SGD`` event loop (``python/paddle/v2/trainer.py:124-202``), unified:
``Trainer.train`` is the pass/batch loop with events; jobs ``test``, ``time``
and ``checkgrad`` mirror the reference CLI jobs (``--job=...``,
``TrainerBenchmark.cpp``, ``Trainer.cpp:299``).

The hot loop is ONE jit-compiled XLA computation per batch shape:
fwd + autodiff bwd + optimizer update + (when a mesh axis ``data`` > 1)
gradient all-reduce inserted by the SPMD partitioner — this replaces the
reference's ``TrainerInternal::trainOneBatch`` hot loop, the
``MultiGradientMachine`` thread fleet, and the sync parameter-server
exchange with a single compiled program (SURVEY §2.5 → TPU mapping).
"""

from __future__ import annotations

import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import OptimizationConfig
from ..core.device import DATA_AXIS, data_sharding, get_mesh, replicated
from ..core.dtypes import policy_for, policy_scope, resolve_precision
from ..core.sequence import SequenceBatch, value_of
from ..layers.network import NeuralNetwork
from ..optimizer import Optimizer, create_optimizer, make_schedule
from ..optimizer import loss_scale as ls
from .. import observe
from ..observe import trace
from ..utils import FLAGS, PaddleTpuError, enforce, get_logger, global_stat
from . import events as ev
from .checkpoint import (
    latest_checkpoint,
    latest_valid_checkpoint,
    load_buffers,
    load_manifest,
    load_opt_state,
    load_params,
    save_checkpoint,
    verify_checkpoint,
)

log = get_logger("trainer")

# end-of-pass sentinel for the traced input wait (a StopIteration
# escaping the span would stamp a false error on every pass's trace)
_PASS_END = object()

# Live trainers, for the conftest dtype-drift guard: after each precision
# test it asserts no master parameter or optimizer-state leaf silently
# became bf16 (the in-place-downcast bug class mixed precision invites).
_LIVE_TRAINERS: "weakref.WeakSet[Trainer]" = weakref.WeakSet()


def optimizer_from_config(oc: OptimizationConfig) -> Tuple[Optimizer, Callable]:
    """OptimizationConfig → (optimizer, lr schedule) — the
    ``TrainerConfigHelper`` flag/proto merge equivalent."""
    kw: Dict[str, Any] = dict(
        learning_rate=oc.learning_rate,
        weight_decay=oc.l2_weight_decay,
        l1_decay=oc.l1_weight_decay,
        gradient_clipping_threshold=oc.gradient_clipping_threshold,
    )
    name = oc.learning_method or "sgd"
    if name in ("momentum", "sgd") and oc.momentum:
        name = "momentum"
        kw["momentum"] = oc.momentum
    if name in ("adam", "adamax"):
        kw.update(beta1=oc.adam_beta1, beta2=oc.adam_beta2,
                  epsilon=oc.adam_epsilon)
    if name in ("adadelta", "rmsprop", "decayed_adagrad"):
        kw.update(rho=oc.ada_rou, epsilon=oc.ada_epsilon)
    if name == "adagrad":
        kw.update(epsilon=oc.ada_epsilon)
    sched = make_schedule(oc.learning_rate_schedule, oc.learning_rate,
                          oc.learning_rate_decay_a, oc.learning_rate_decay_b,
                          oc.learning_rate_args)
    return create_optimizer(name, **kw), sched


class Trainer:
    def __init__(self, network: NeuralNetwork,
                 optimizer: Optional[Optimizer] = None,
                 opt_config: Optional[OptimizationConfig] = None,
                 mesh=None, seed: Optional[int] = None,
                 sharding_rules=None, fsdp: Optional[bool] = None,
                 fsdp_rules=None):
        self.network = network
        self.sharding_rules = sharding_rules
        # FSDP over the data axis (--fsdp): parameters AND optimizer
        # slots sharded per _resolve_fsdp(); fsdp_rules is a committed
        # per-zoo ShardingRules table (parallel/rule_tables.py), else
        # the largest-divisible-dim heuristic places each param.  On a
        # 1-chip data axis the mode is inert — the replicated path,
        # byte-for-byte (the kill-switch contract bench_multichip pins).
        self.fsdp = bool(FLAGS.fsdp) if fsdp is None else bool(fsdp)
        self.fsdp_rules = fsdp_rules
        self._fsdp_shardings = None
        if optimizer is None:
            optimizer, self.schedule = optimizer_from_config(
                opt_config or OptimizationConfig())
        else:
            self.schedule = make_schedule("constant", optimizer.learning_rate)
        self.optimizer = optimizer
        self.mesh = mesh or get_mesh()
        self.seed = FLAGS.seed if seed is None else seed
        # end-to-end precision policy: "fp32" (default — the legacy
        # code path, byte-for-byte) or "bf16" (fp32 master weights,
        # bf16 compute casts at the step boundary, dynamic loss
        # scaling).  OptimizationConfig.precision wins over --precision.
        self.precision = resolve_precision(opt_config)
        self._ls_state = ls.init_state() \
            if self.precision == "bf16" else None
        self._skipped_reported = 0
        # --health_interval N > 0: fuse the per-layer grad/param/update
        # telemetry aux into the train step (observe/health.py) and
        # drain every N steps.  At the default 0 the session is None
        # and every step builder/dispatch below takes its legacy
        # branch byte-for-byte.
        self._health = None
        if int(FLAGS.health_interval) > 0:
            from ..observe.health import HealthSession
            self._health = HealthSession(network,
                                         int(FLAGS.health_interval))
        _LIVE_TRAINERS.add(self)
        self.params = network.init_params(self.seed)
        self.buffers = network.init_buffers()
        self.opt_state = self.optimizer.init_state(self.params)
        self._lr_scales = network.lr_scales(self.params)
        self._train_step = None
        self._eval_step = None
        self._sparse_plan = None
        self.samples_seen = 0
        # --roofline_dump: first-batch feed retained for the one-shot
        # compiled-step cost attribution at the end of pass 0
        self._roofline_feed = None
        self._roofline_dumped = False
        if FLAGS.init_model_path:
            self.load(FLAGS.init_model_path)
        # static pruning hooks (ParameterUpdaterHook.cpp:39): masks are
        # generated from the initial/loaded values, applied to the value
        # now and to every gradient inside the train step
        from ..optimizer.hooks import apply_prune_init, build_prune_masks
        self._prune_masks = build_prune_masks(network.param_specs,
                                              self.params)
        self.params = apply_prune_init(self.params, self._prune_masks)

    # ----------------------------------------------------------- sharding
    def _shard_feed(self, feed: Dict[str, Any]) -> Dict[str, Any]:
        n = self.mesh.shape.get(DATA_AXIS, 1)
        if n <= 1:
            return feed
        multihost = jax.process_count() > 1

        def place(x):
            if np.ndim(x) >= 1 and np.shape(x)[0] % max(
                    n // jax.process_count(), 1) == 0:
                if multihost:
                    # each process feeds its LOCAL rows; the global batch
                    # is their concatenation over the data axis
                    # (cluster_train: every trainer reads its own shard)
                    gshape = ((np.shape(x)[0] * jax.process_count(),)
                              + np.shape(x)[1:])
                    return jax.make_array_from_process_local_data(
                        data_sharding(self.mesh, np.ndim(x)),
                        np.asarray(x), gshape)
                if np.shape(x)[0] % n == 0:
                    return jax.device_put(
                        x, data_sharding(self.mesh, np.ndim(x)))
            return jax.device_put(x, replicated(self.mesh))

        return {k: jax.tree_util.tree_map(place, v)
                for k, v in feed.items()}

    def _place_feed(self, feed: Dict[str, Any]) -> Dict[str, Any]:
        """Shard/place a converted feed on the CALLING thread.

        The async input pipeline runs this on its worker threads so the
        host→device copy overlaps the running step; the result is
        handed to ``train_one_batch(..., placed=True)`` which then
        skips its own ``_shard_feed``.  On a single-device mesh
        ``_shard_feed`` is the identity, so leaves are committed with
        ``jnp.asarray`` here — otherwise a numpy feed would pay its
        H2D transfer inside the jit dispatch, on the critical path."""
        feed = self._shard_feed(feed)
        if self.mesh.shape.get(DATA_AXIS, 1) <= 1:
            feed = {k: jax.tree_util.tree_map(jnp.asarray, v)
                    for k, v in feed.items()}
        return feed

    def _pipeline_or_sync(self, reader, feeder):
        """Build this pass's batch source: an :class:`AsyncPipeline`
        (convert + device placement on worker threads) when
        ``--prefetch_depth`` > 0, else the raw reader iterator.
        Returns ``(iterable, pipe)`` — ``pipe`` is None on the
        synchronous path and must be ``close()``d otherwise."""
        depth = max(0, int(FLAGS.prefetch_depth))
        if depth == 0:
            return iter(reader()), None
        from ..data.pipeline import AsyncPipeline
        pipe = AsyncPipeline(
            reader(),
            convert_fn=feeder.convert if feeder else None,
            place_fn=self._place_feed,
            depth=depth, workers=FLAGS.reader_workers)
        return pipe, pipe

    def _replicate(self, tree):
        if self.mesh.devices.size <= 1:
            return tree
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, replicated(self.mesh)), tree)

    def _resolve_fsdp(self):
        """Resolve the FSDP placement once: param name → ``(shape,
        NamedSharding)`` over the ``data`` axis, from ``fsdp_rules``
        (the committed per-zoo table) else the largest-divisible-dim
        heuristic (:func:`paddle_tpu.parallel.sharding.fsdp_spec`).
        None when FSDP is off or the data axis has a single shard —
        every placement/step call site then takes its legacy branch
        byte-for-byte (the ``--fsdp=false`` kill-switch contract)."""
        n = self.mesh.shape.get(DATA_AXIS, 1)
        if not self.fsdp or n <= 1:
            return None
        if self._fsdp_shardings is None:
            from jax.sharding import NamedSharding
            from ..parallel.sharding import fsdp_spec, spec_shard_info
            from ..utils import warn_once
            min_size = int(FLAGS.fsdp_min_size)
            specs = {}
            for name, value in self.params.items():
                leaves = jax.tree_util.tree_leaves(value)
                shape = tuple(np.shape(leaves[0])) if leaves else ()
                if self.fsdp_rules is not None:
                    spec = self.fsdp_rules.spec_for(name, len(shape))
                    info = spec_shard_info(spec, self.mesh)
                    if info is not None and shape[info[0]] % info[1]:
                        # an indivisible table entry would be a
                        # pod-compile failure — degrade to replicated
                        # and say so (the preflight/tests catch this
                        # for committed tables; user tables may meet
                        # sizes the author never saw)
                        warn_once(
                            f"trainer.fsdp_indivisible:{name}",
                            "FSDP rule spec %s for %r does not divide "
                            "shape %s on a %d-way data axis — "
                            "replicating this parameter",
                            tuple(spec), name, shape, n, logger=log)
                        spec = jax.sharding.PartitionSpec()
                else:
                    spec = fsdp_spec(shape, n, min_size=min_size)
                specs[name] = (shape, NamedSharding(self.mesh, spec))
            self._fsdp_shardings = specs
        return self._fsdp_shardings

    def _place_params(self, params):
        """FSDP placement (``--fsdp``: every parameter sharded over
        ``data``), else tensor-parallel placement honoring
        sharding_rules (per-parameter PartitionSpec, ``parallel_nn``
        equivalent), else replicate."""
        fs = self._resolve_fsdp()
        if fs is not None:
            rep = replicated(self.mesh)
            return {
                name: jax.tree_util.tree_map(
                    lambda x, e=fs[name]: jax.device_put(
                        x, e[1] if tuple(np.shape(x)) == e[0] else rep),
                    value)
                for name, value in params.items()}
        if self.sharding_rules is None or self.mesh.devices.size <= 1:
            return self._replicate(params)
        from ..parallel.sharding import shard_params
        return shard_params(params, self.sharding_rules, self.mesh)

    def _place_opt_state(self, opt_state, params):
        """Optimizer slots (Adam moments etc.) shard like their parameter —
        otherwise the sharding's memory win is lost and XLA reshards
        every step.  Covers both modes: FSDP (``data``-axis specs from
        ``_resolve_fsdp``) and TP (``sharding_rules``)."""
        fs = self._resolve_fsdp()
        if fs is None and (self.sharding_rules is None
                           or self.mesh.devices.size <= 1):
            return self._replicate(opt_state)
        count, slots = opt_state
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        names = [".".join(str(k.key) if hasattr(k, "key") else str(k)
                          for k in path)
                 for path, _ in jax.tree_util.tree_flatten_with_path(
                     params)[0]]
        placed_slots = []
        for name, p, slot in zip(names, p_leaves, slots):
            if fs is not None:
                ent = fs.get(name)
                sh = ent[1] if ent is not None \
                    and tuple(np.shape(p)) == ent[0] \
                    else replicated(self.mesh)
            else:
                sh = self.sharding_rules.sharding_for(
                    name, getattr(p, "ndim", 0), self.mesh)

            def place(x, sh=sh, pshape=np.shape(p)):
                if np.shape(x) == pshape:
                    return jax.device_put(x, sh)
                return jax.device_put(x, replicated(self.mesh))
            placed_slots.append(jax.tree_util.tree_map(place, slot))
        return (jax.device_put(count, replicated(self.mesh)), placed_slots)

    def _fsdp_constrainers(self):
        """``(constrain_params, constrain_opt)`` for the train-step
        builders: identity pass-throughs when FSDP is inactive (the
        legacy jaxpr, byte-for-byte), else
        ``jax.lax.with_sharding_constraint`` appliers that pin
        gradients, updated parameters, and param-shaped optimizer
        slots to their ``data``-axis sharding — the annotations that
        make XLA's partitioner emit the all-gather/reduce-scatter pair
        instead of a dense all-reduce plus per-step reshards."""
        fs = self._resolve_fsdp()
        if fs is None:
            return (lambda tree: tree), (lambda opt: opt)

        def constrain_leaf(x, ent):
            if ent is not None and tuple(np.shape(x)) == ent[0]:
                return jax.lax.with_sharding_constraint(x, ent[1])
            return x

        def constrain_params(tree):
            return {
                name: jax.tree_util.tree_map(
                    lambda x, e=fs.get(name): constrain_leaf(x, e),
                    value)
                for name, value in tree.items()}

        # opt slots align with the flattened param leaves — the same
        # order _place_opt_state places them in
        names = [".".join(str(k.key) if hasattr(k, "key") else str(k)
                          for k in path)
                 for path, _ in jax.tree_util.tree_flatten_with_path(
                     self.params)[0]]

        def constrain_opt(opt):
            count, slots = opt
            out = []
            for name, slot in zip(names, slots):
                ent = fs.get(name)
                out.append(jax.tree_util.tree_map(
                    lambda x, e=ent: constrain_leaf(x, e), slot))
            return (count, out)

        return constrain_params, constrain_opt

    def _step_extras(self) -> Tuple:
        """Trailing jitted-step inputs beyond ``(params, opt_state,
        buffers, feed, rng, progress)``: the loss-scale state
        (``--precision=bf16``) then the health accumulator
        (``--health_interval``).  THE one definition of the extra-state
        order — every step variant mirrors it in its trailing outputs,
        and ``bench._scan_time_ms`` / ``costmodel._step_args`` reuse it
        instead of re-deriving the tuple."""
        extras: Tuple = ()
        if self._ls_state is not None:
            extras += (self._ls_state,)
        if self._health is not None:
            extras += (self._health.ensure_state(),)
        return extras

    def _param_leaf_names(self):
        """Flattened parameter leaf names in tree order — the alignment
        contract of the optimizer slot list (``Optimizer.init``) that
        ``_place_opt_state`` / ``_fsdp_constrainers`` also rely on."""
        return [".".join(str(k.key) if hasattr(k, "key") else str(k)
                         for k in path)
                for path, _ in jax.tree_util.tree_flatten_with_path(
                    self.params)[0]]

    def _sparse_exchange_plan(self):
        """Sparse gradient exchange plan (``--sparse_grads``): param
        name → list of feed keys (data-layer names) whose ids touch it.

        A ``ParameterConfig(sparse_update=True)`` table is ELIGIBLE when
        every use is a top-level embedding layer fed directly by a data
        layer — then the step can dedupe the batch's ids up front,
        gather the touched rows once (ops/pallas_embedding.py), route
        every lookup through the block (``parallel.sparse
        .exchange_scope``), and autodiff hands back a fixed-capacity
        ``(rows, values)`` gradient instead of the dense ``[V, D]`` one.
        Ineligible tables (shared into non-embedding layers, inside
        recurrent groups, pruned, health telemetry active) keep the
        legacy in-graph lazy masking, with a one-time notice."""
        if self._sparse_plan is None:
            self._sparse_plan = self._build_sparse_exchange_plan()
        return self._sparse_plan

    def _build_sparse_exchange_plan(self):
        from ..utils import warn_once
        net = self.network
        sparse_names = {n for n, s in net.param_specs.items()
                        if s.sparse_update and n not in net.static_params}
        if not FLAGS.sparse_grads or not sparse_names:
            return {}
        if self._health is not None:
            # the health aux consumes the dense per-param grads dict;
            # a missing-table grads tree would hole its telemetry
            warn_once(
                "trainer.sparse_exchange:health",
                "sparse gradient exchange disabled while "
                "--health_interval is active (health telemetry reads "
                "dense per-parameter gradients) — sparse tables take "
                "the lazy dense-masked update", logger=log)
            return {}
        leaf_names = self._param_leaf_names()
        group_specs = {
            spec.name
            for g in net.groups.values()
            for lyr in g.layers.values()
            for spec in lyr.param_specs()}
        plan = {}
        for name in sorted(sparse_names):
            uses = [lyr for lyr in net.layers.values()
                    if any(spec.name == name
                           for spec in lyr.param_specs())]
            eligible = (
                name not in (self._prune_masks or {})
                and name not in group_specs
                and leaf_names.count(name) == 1
                and np.ndim(self.params.get(name)) == 2
                and bool(uses)
                and all(lyr.conf.type == "embedding"
                        and lyr.conf.inputs
                        and lyr.conf.inputs[0].input_layer_name
                        in net.data_layers
                        for lyr in uses))
            if not eligible:
                warn_once(
                    f"trainer.sparse_exchange:ineligible:{name}",
                    "sparse_update parameter %r is not exchange-"
                    "eligible (used outside a directly-fed embedding "
                    "layer, pruned, or not a plain [V, D] leaf) — "
                    "taking the lazy dense-masked update", name,
                    logger=log)
                continue
            plan[name] = sorted({lyr.conf.inputs[0].input_layer_name
                                 for lyr in uses})
        return plan

    def _exchange_prefetch(self, ex_plan, params, feed):
        """Per-table batch prefetch inside the jitted step: dedupe this
        batch's ids into a sorted fixed-capacity row set and gather the
        touched rows (Pallas scalar-prefetch kernel on capable
        single-device shapes).  Capacity is ``--sparse_grad_rows`` or
        the batch's total id count — which can never overflow."""
        from ..core.sequence import value_of
        from ..ops import pallas_embedding
        from ..parallel import sparse as psparse
        # host flag, read at trace time by design (capacity is static)
        cap_flag = int(FLAGS.sparse_grad_rows)  # ptpu: lint-ok[PT-TRACE]
        # the kernel is a single-device program; on a real mesh the
        # (possibly row-sharded) gather stays with the SPMD partitioner
        allow_kernel = self.mesh.devices.size <= 1
        ex_rows, ex_blocks = {}, {}
        with jax.named_scope("sparse_prefetch"):
            for name, keys in ex_plan.items():
                table = params[name]
                ids = jnp.concatenate(
                    [value_of(feed[k]).astype(jnp.int32).ravel()
                     for k in keys])
                # .size is the static shape product, not a traced value
                cap = cap_flag if cap_flag > 0 \
                    else int(ids.size)  # ptpu: lint-ok[PT-TRACE]
                rows = psparse.unique_rows_sorted(
                    ids, cap, table.shape[0])
                ex_rows[name] = rows
                ex_blocks[name] = pallas_embedding.gather_rows(
                    table, rows, allow_kernel=allow_kernel)
        return ex_rows, ex_blocks

    def _exchange_apply(self, ex_plan, params, opt_state, ex_rows,
                        block_grads, dense_new, dense_opt_new, lr):
        """Apply the exchanged ``(rows, values)`` gradients as per-table
        O(K) row updates (``Optimizer.apply_rows`` — touched rows' value
        and moments only, the SelectedRows optimizer-kernel contract)
        and splice the results back into the full param dict / slot
        list.  Rows whose exchanged gradient is exactly zero are routed
        out of bounds first, mirroring the dense path's inferred
        ``touched_row_mask`` — so ``--sparse_grads`` on/off agree on
        which rows a batch may move (weight decay included)."""
        count, slots = opt_state
        new_count, dense_slots_new = dense_opt_new
        leaf_names = self._param_leaf_names()
        new_params = dict(dense_new)
        slot_new_by_name = {}
        for name in ex_plan:
            table = params[name]
            rows = ex_rows[name]
            row_g = block_grads[name].astype(table.dtype)
            touched = jnp.any(row_g != 0,
                              axis=tuple(range(1, row_g.ndim)))
            rows_eff = jnp.where(touched, rows, table.shape[0])
            sc = self._lr_scales.get(name) if self._lr_scales else None
            eff_lr = lr if sc is None else lr * sc
            slot = slots[leaf_names.index(name)]
            new_table, (_, new_slot) = self.optimizer.apply_rows(
                table, rows_eff, row_g, (count, slot), eff_lr)
            new_params[name] = new_table
            slot_new_by_name[name] = new_slot
        dense_iter = iter(dense_slots_new)
        slots_out = [slot_new_by_name[n] if n in slot_new_by_name
                     else next(dense_iter) for n in leaf_names]
        return new_params, (new_count, slots_out)

    @staticmethod
    def _dealias(tree):
        """Copy every leaf so no two donated leaves share a buffer (JAX
        dedupes identical constants like the zero-init Adam m/v slots;
        donating an aliased buffer twice is an error)."""
        return jax.tree_util.tree_map(
            lambda x: x.copy() if hasattr(x, "copy") else x, tree)

    # --------------------------------------------------------- train step
    def _build_train_step(self):
        if self.precision == "bf16":
            return self._build_mixed_train_step()
        net = self.network
        opt = self.optimizer
        lr_scales = self._lr_scales
        # ParamAttr(sparse_update=True) → lazy row-sparse updates: only
        # rows touched by the batch get value/moment updates (the
        # SparseRowMatrix/SelectedRows contract, paddle/math/
        # SparseRowMatrix.h:29; see paddle_tpu/parallel/sparse.py)
        sparse_names = {n for n, s in net.param_specs.items()
                        if s.sparse_update}
        # --sparse_grads: exchange-eligible tables leave the dense
        # gradient entirely — their grads travel as fixed-capacity
        # (rows, values) pairs and apply as O(K) row updates; the rest
        # of sparse_names keeps the lazy masked path
        ex_plan = self._sparse_exchange_plan()
        sparse_names -= set(ex_plan)
        leaf_names = self._param_leaf_names() if ex_plan else []

        hs = self._health
        hs_stats = hs.stats_fn() if hs is not None else None
        from ..observe import health as _health
        from ..parallel import sparse as psparse
        # FSDP (--fsdp): sharding constraints threaded through the step
        # (identity closures when inactive — the legacy jaxpr)
        c_params, c_opt = self._fsdp_constrainers()

        def step(params, opt_state, buffers, feed, rng, progress,
                 *health_state):
            def loss_fn(p):
                loss, (values, new_buffers) = net.loss(
                    p, feed, buffers, is_training=True, rng=rng)
                return loss, new_buffers

            if ex_plan:
                ex_rows, ex_blocks = self._exchange_prefetch(
                    ex_plan, params, feed)

                def loss_fn_ex(p, blocks):
                    full = dict(p)
                    for n in ex_plan:
                        full[n] = jax.lax.stop_gradient(params[n])
                    with psparse.exchange_scope(
                            {n: (ex_rows[n], blocks[n])
                             for n in ex_plan}):
                        return loss_fn(full)

                dense_p = {n: v for n, v in params.items()
                           if n not in ex_plan}
                (loss, new_buffers), (grads, block_grads) = \
                    jax.value_and_grad(loss_fn_ex, (0, 1),
                                       has_aux=True)(dense_p, ex_blocks)
            else:
                (loss, new_buffers), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                block_grads = {}
            grads = c_params(grads)
            if self._prune_masks:
                from ..optimizer.hooks import apply_prune_grads
                grads = apply_prune_grads(grads, self._prune_masks)
            lr = self.schedule(progress)
            masks = None
            if sparse_names:
                from ..parallel.sparse import touched_row_mask
                masks = {n: (touched_row_mask(g) if n in sparse_names
                             else None)
                         for n, g in grads.items()}
            # named_scope: the update lands in its own "optimizer"
            # region in the compiled-step cost attribution
            # (observe/costmodel.py) instead of polluting layer regions
            with jax.named_scope("optimizer"):
                if ex_plan:
                    count, slots = opt_state
                    dense_slots = [s for n, s in zip(leaf_names, slots)
                                   if n not in ex_plan]
                    dense_scales = {n: lr_scales[n] for n in grads} \
                        if lr_scales is not None else None
                    new_dense, dense_opt_new = opt.apply(
                        {n: params[n] for n in grads}, grads,
                        (count, dense_slots), lr, dense_scales,
                        sparse_masks=masks)
                    new_params, new_opt = self._exchange_apply(
                        ex_plan, params, opt_state, ex_rows,
                        block_grads, new_dense, dense_opt_new, lr)
                else:
                    new_params, new_opt = opt.apply(
                        params, grads, opt_state, lr, lr_scales,
                        sparse_masks=masks)
                new_params = c_params(new_params)
                new_opt = c_opt(new_opt)
            if hs_stats is not None:
                # the health aux scopes as its own attribution region,
                # like the optimizer — it must not pollute layer costs
                with jax.named_scope("health"):
                    new_health = _health.accumulate(
                        health_state[0],
                        hs_stats(grads, params, new_params),
                        applied=True)
                return (new_params, new_opt, new_buffers, loss,
                        new_health)
            return new_params, new_opt, new_buffers, loss

        self._raw_step = step   # unjitted; benchmarks scan over it
        donate = (0, 1, 2, 6) if hs is not None else (0, 1, 2)
        return jax.jit(step, donate_argnums=donate)

    def _build_mixed_train_step(self):
        """The ``--precision=bf16`` train step: fp32 master weights are
        cast to the policy compute dtype ONCE at the step boundary (the
        backward through the cast yields fp32 gradients, so gradient
        accumulation across shared-parameter uses happens in fp32), the
        loss is multiplied by the dynamic scale before the backward and
        the gradients divided by it in fp32 after, the optimizer applies
        to the fp32 masters with fp32 slots, and a non-finite gradient
        skips the whole update — parameters, optimizer state, and
        buffers stay bit-identical while the scale halves.  The op-level
        bf16 policy is entered INSIDE the traced function so every
        retrace (new feed shape) sees it regardless of which flag or
        config carried the policy.
        """
        net = self.network
        opt = self.optimizer
        lr_scales = self._lr_scales
        sparse_names = {n for n, s in net.param_specs.items()
                        if s.sparse_update}
        # --sparse_grads: exchange-eligible tables leave the dense
        # gradient — see _build_train_step; the bf16 wrinkles are that
        # the [K, D] block grads unscale in fp32 with the dense grads
        # and join the finite sweep, and the fp32 master table updates
        # through apply_rows behind the same skipped-step select
        ex_plan = self._sparse_exchange_plan()
        sparse_names -= set(ex_plan)
        leaf_names = self._param_leaf_names() if ex_plan else []
        pol = policy_for("bf16")
        cd = pol.compute_dtype
        growth_interval = FLAGS.loss_scale_growth_interval

        def cast_compute(tree):
            return jax.tree_util.tree_map(
                lambda x: x.astype(cd)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

        hs = self._health
        hs_stats = hs.stats_fn() if hs is not None else None
        from ..observe import health as _health
        from ..parallel import sparse as psparse
        # FSDP (--fsdp): sharding constraints threaded through the step
        # (identity closures when inactive — the legacy jaxpr)
        c_params, c_opt = self._fsdp_constrainers()

        def step(params, opt_state, buffers, feed, rng, progress,
                 ls_state, *health_state):
            with policy_scope(pol):
                def loss_fn(p):
                    # net.forward updates its ctx.buffers dict IN PLACE
                    # — hand it a copy so the step's own `buffers` arg
                    # stays pristine for the skipped-step select below
                    # (otherwise it reads back this trace's JVP tracers)
                    loss, (values, new_buffers) = net.loss(
                        cast_compute(p), feed, dict(buffers),
                        is_training=True, rng=rng)
                    return (loss * ls_state.scale.astype(loss.dtype),
                            (loss, new_buffers))

                if ex_plan:
                    # prefetch gathers from the fp32 master table; the
                    # blocks cast to compute dtype inside loss_fn so
                    # their cotangents come back fp32, like the masters'
                    ex_rows, ex_blocks = self._exchange_prefetch(
                        ex_plan, params, feed)

                    def loss_fn_ex(p, blocks):
                        full = dict(p)
                        for n in ex_plan:
                            full[n] = jax.lax.stop_gradient(params[n])
                        cb = cast_compute(blocks)
                        with psparse.exchange_scope(
                                {n: (ex_rows[n], cb[n])
                                 for n in ex_plan}):
                            return loss_fn(full)

                    dense_p = {n: v for n, v in params.items()
                               if n not in ex_plan}
                    (_, (loss, new_buffers)), (grads, block_grads) = \
                        jax.value_and_grad(loss_fn_ex, (0, 1),
                                           has_aux=True)(dense_p,
                                                         ex_blocks)
                    block_grads = ls.unscale(block_grads,
                                             ls_state.scale)
                else:
                    (_, (loss, new_buffers)), grads = \
                        jax.value_and_grad(loss_fn,
                                           has_aux=True)(params)
                    block_grads = {}
            grads = ls.unscale(grads, ls_state.scale)
            grads = c_params(grads)
            if hs_stats is not None:
                # skip-step disambiguation: ONE isfinite sweep yields
                # both the loss-scale skip decision and the per-layer
                # non-finite localization counts
                nf_counts = ls.leaf_nonfinite_counts(grads)
                finite = ls.all_finite_from_counts(nf_counts)
            else:
                nf_counts = None
                finite = ls.all_finite((grads, block_grads))
            if self._prune_masks:
                from ..optimizer.hooks import apply_prune_grads
                grads = apply_prune_grads(grads, self._prune_masks)
            lr = self.schedule(progress)
            masks = None
            if sparse_names:
                from ..parallel.sparse import touched_row_mask
                masks = {n: (touched_row_mask(g) if n in sparse_names
                             else None)
                         for n, g in grads.items()}
            with jax.named_scope("optimizer"):
                if ex_plan:
                    count, slots = opt_state
                    dense_slots = [s for n, s in zip(leaf_names, slots)
                                   if n not in ex_plan]
                    dense_scales = {n: lr_scales[n] for n in grads} \
                        if lr_scales is not None else None
                    new_dense, dense_opt_new = opt.apply(
                        {n: params[n] for n in grads}, grads,
                        (count, dense_slots), lr, dense_scales,
                        sparse_masks=masks)
                    new_params, new_opt = self._exchange_apply(
                        ex_plan, params, opt_state, ex_rows,
                        block_grads, new_dense, dense_opt_new, lr)
                else:
                    new_params, new_opt = opt.apply(
                        params, grads, opt_state, lr, lr_scales,
                        sparse_masks=masks)
                new_params = ls.select(finite, new_params, params)
                new_opt = ls.select(finite, new_opt, opt_state)
                new_buffers = ls.select(finite, new_buffers, buffers)
                new_ls = ls.update(ls_state, finite, growth_interval)
                new_params = c_params(new_params)
                new_opt = c_opt(new_opt)
            if hs_stats is not None:
                # post-select new_params: a skipped step reports a zero
                # update norm (nothing was applied), and its non-finite
                # counts land in the benign bucket (applied=finite)
                with jax.named_scope("health"):
                    new_health = _health.accumulate(
                        health_state[0],
                        hs_stats(grads, params, new_params, nf_counts),
                        applied=finite)
                return (new_params, new_opt, new_buffers, loss, new_ls,
                        new_health)
            return new_params, new_opt, new_buffers, loss, new_ls

        self._raw_step = step   # unjitted; benchmarks scan over it
        donate = (0, 1, 2, 6, 7) if hs is not None else (0, 1, 2, 6)
        return jax.jit(step, donate_argnums=donate)

    def _eval_output_names(self) -> List[str]:
        """Layers whose values evaluators should see: a declared output that
        is a cost layer stands in for its first input (the prediction) —
        the reference wires evaluators to the prediction layer the same way
        (``Evaluator::eval(nn)`` reads the layer named in its config)."""
        names: List[str] = []
        for n in self.network.output_names:
            lyr = self.network.layers.get(n)
            if lyr is not None and getattr(lyr, "is_cost", False) \
                    and lyr.conf.inputs:
                names.append(lyr.conf.inputs[0].input_layer_name)
            else:
                names.append(n)
        return names

    def _build_eval_step(self):
        net = self.network
        eval_names = list(self._eval_output_names())
        # config-declared evaluators read their own input layers
        eval_names += [e["input_layer_name"]
                       for e in net.config.evaluators
                       if e.get("input_layer_name")]

        # the bf16 policy also governs evaluation compute (the config-
        # carried case: FLAGS may still say fp32, so the scope must be
        # entered inside the traced function like the train step)
        import contextlib
        pol = policy_for("bf16") if self.precision == "bf16" else None

        def step(params, buffers, feed):
            scope = policy_scope(pol) if pol is not None \
                else contextlib.nullcontext()
            with scope:
                loss, (values, _) = net.loss(params, feed, buffers,
                                             is_training=False)
                outs = dict(net.outputs(values))
                for n in eval_names:
                    if n in values:
                        outs[n] = values[n]
            return loss, outs

        return jax.jit(step)

    def _config_evaluators(self):
        """Instantiate the model config's EvaluatorConfig entries
        (reference: ``Evaluator::create`` from ``ModelConfig``)."""
        from ..evaluators import create_evaluator

        out = []
        for e in self.network.config.evaluators:
            extra = {k: v for k, v in e.items()
                     if k not in ("type", "name", "input_layer_name",
                                  "label_layer_name",
                                  "weight_layer_name")}
            ev = create_evaluator(e["type"], **extra)
            ev._config_entry = e
            out.append(ev)
        return out

    def _count_recompiles(self) -> None:
        """Tick ``jit_recompiles`` when the train step's jit cache grew.
        The first entry is the initial compile; anything beyond one per
        intended feed shape means shape churn is recompiling the hot
        loop — the counter makes that visible without -jax_log_compiles
        spelunking."""
        try:
            n = self._train_step._cache_size()
        except (AttributeError, TypeError):
            return
        prev = getattr(self, "_jit_cache_size", 0)
        if n > prev:
            observe.counter(
                "jit_recompiles",
                "train-step XLA compiles (first compile included; >1 "
                "per feed shape = recompile churn)").inc(n - prev)
            self._jit_cache_size = n

    def train_one_batch(self, feed: Dict[str, Any],
                        placed: bool = False) -> float:
        """``TrainerInternal::trainOneBatch`` equivalent (one jit call).

        ``placed=True`` marks a feed the async input pipeline already
        sharded/placed on a worker thread (``_place_feed``) — the
        step skips its own ``_shard_feed`` so no placement work is
        repeated (and multihost feeds aren't re-globalized).

        Telemetry: step latency lands in ``train_step_seconds`` split as
        ``train_host_feed_seconds`` (shard/place the feed) + dispatch;
        when a metrics sink is attached (``--metrics_jsonl``) the step
        is additionally fenced with ``block_until_ready`` so
        ``train_device_blocked_seconds`` captures true device time and
        ``train_samples_per_sec`` is honest throughput — the Wang et
        al. host-vs-device split.  With no sink the fence is skipped:
        dispatch stays async and instrumentation is a few counter
        increments.

        Tracing (``--trace_jsonl`` / ``--metrics_port``): the step runs
        under a ``train_step`` span with ``feed`` / ``step_dispatch`` /
        ``fence`` child phases; an explicitly opened trace
        (``--trace_jsonl`` / ``trace.enable()``, NOT a lazy ``/trace``
        scrape — see ``trace.fences_steps``) also fences the step so
        the timeline shows true device time.  With tracing off every
        span call is a shared no-op (<50 µs/step contract).
        """
        if self._train_step is None:
            self._train_step = self._build_train_step()
            self.params = self._place_params(self._dealias(self.params))
            self.opt_state = self._place_opt_state(
                self._dealias(self.opt_state), self.params)
            self.buffers = self._replicate(self._dealias(self.buffers))
            if self._ls_state is not None:
                self._ls_state = self._replicate(
                    self._dealias(self._ls_state))
            if self._health is not None:
                self._health.ensure_state(place=self._replicate)
        with trace.span("train_step",
                        samples_seen=self.samples_seen) as sp:
            t0, t_feed, t_done, batch, loss = \
                self._traced_step_body(feed, placed)
            if self._health is not None and self._health.step_done():
                # drain due: the small D2H fetch below is the health
                # path's only fence, amortized over --health_interval
                # steps; its summary lands on this step's span
                report = self._health.drain(loss=float(loss),
                                            place=self._replicate)
                if report is not None \
                        and isinstance(getattr(sp, "attrs", None),
                                       dict):
                    sp.attrs.update(self._health.span_summary(report))
        observe.histogram(
            "train_host_feed_seconds",
            "host time sharding/placing the feed per step"
        ).observe(t_feed - t0)
        observe.histogram(
            "train_step_seconds",
            "end-to-end train_one_batch latency (unfenced = dispatch "
            "time unless a sink is attached)").observe(t_done - t0)
        observe.counter("train_steps", "train steps executed").inc()
        observe.counter("train_samples", "samples trained").inc(batch)
        self.samples_seen += batch
        return loss  # device scalar: don't block — caller decides when

    def _traced_step_body(self, feed: Dict[str, Any], placed: bool):
        """The span-covered phases of one step: feed -> dispatch ->
        fence.  Split out so the ``train_step`` span brackets exactly
        this work (and restores its context even when a phase raises)."""
        t0 = time.perf_counter()
        with trace.span("feed", placed=placed):
            if not placed:
                feed = self._shard_feed(feed)
            batch = _batch_size(feed)
            rng = jax.random.PRNGKey(
                (self.seed * 1000003 + self.samples_seen) % (2 ** 31))
        t_feed = time.perf_counter()
        with trace.span("step_dispatch"), global_stat.timer("train_batch"):
            progress = jnp.asarray(self.samples_seen, jnp.float32)
            # every step variant returns (params, opt, buffers, loss,
            # *extras) with the extras mirroring the trailing inputs
            # (_step_extras order), so dispatch/unpack is uniform
            out = self._train_step(self.params, self.opt_state,
                                   self.buffers, feed, rng, progress,
                                   *self._step_extras())
            self.params, self.opt_state, self.buffers, loss = out[:4]
            tail = out[4:]
            if self._ls_state is not None:
                self._ls_state, tail = tail[0], tail[1:]
            if self._health is not None:
                self._health.state = tail[0]
        self._count_recompiles()
        t_dispatch = time.perf_counter()
        # fence when anyone is LISTENING: a metrics sink (the
        # host/device split) or an explicitly-opened trace (a timeline
        # whose step spans end at dispatch time would lie about where
        # time went) — but NOT ring-only recording lazily enabled by a
        # /trace scrape (trace.fences_steps): an endpoint probe must
        # never convert async dispatch into a per-step device sync
        if observe.active() or trace.fences_steps():
            with trace.span("fence"):
                jax.block_until_ready(loss)
            t_done = time.perf_counter()
            self._sync_precision_metrics()   # fenced anyway: keep fresh
            observe.histogram(
                "train_device_blocked_seconds",
                "time blocked on the device per step (fenced; only "
                "recorded while a metrics sink or trace is attached)"
            ).observe(t_done - t_dispatch)
            if t_done > t0:
                observe.gauge(
                    "train_samples_per_sec",
                    "fenced per-step training throughput"
                ).set(batch / (t_done - t0))
        else:
            t_done = t_dispatch
        return t0, t_feed, t_done, batch, loss

    def _sync_precision_metrics(self) -> None:
        """Drain the device-side loss-scale state into observe: the
        ``loss_scale`` gauge and the ``loss_scale_skipped_steps_total``
        counter delta.  Costs a D2H sync, so the hot loop calls it only
        at pass boundaries (and per-step when a metrics sink already
        fences the step); no-op under ``--precision=fp32``."""
        if self._ls_state is None:
            return
        observe.gauge(
            "loss_scale",
            "current dynamic loss scale (--precision=bf16; grows 2x "
            "per overflow-free growth interval, halves on inf/nan "
            "gradients)").set(float(self._ls_state.scale))
        skipped = int(self._ls_state.skipped_total)
        delta = skipped - self._skipped_reported
        if delta > 0:
            observe.counter(
                "loss_scale_skipped_steps_total",
                "train steps skipped on non-finite gradients "
                "(parameters and optimizer state left untouched)"
            ).inc(delta)
            self._skipped_reported = skipped

    def _pass_boundary_observability(self) -> None:
        """Once-per-pass observability work that must stay OFF the step
        hot path: HBM gauges (``hbm_in_use_bytes`` / ``hbm_peak_bytes``
        / category attribution — sampled only when a metrics sink or
        the ``/metrics`` endpoint is live, so the no-sink path pays one
        boolean test per pass), and the one-shot ``--roofline_dump``
        cost-attribution report of the compiled train step."""
        from ..observe import http as ohttp
        from ..observe import memory as omem

        if self._health is not None and self._health.pending():
            # end-of-pass drain: whatever accumulated since the last
            # interval boundary is published before the pass closes
            self._health.drain(place=self._replicate)
        if observe.active() or ohttp.serving():
            omem.sample(self, feed=self._roofline_feed)
        path = FLAGS.roofline_dump
        if path and not self._roofline_dumped \
                and self._roofline_feed is not None:
            from ..observe import costmodel

            report = costmodel.analyze_trainer_step(
                self, self._roofline_feed)
            if report is not None:
                # stamp MFU when a fenced step time exists (a metrics
                # sink fenced the steps) — makes two dumps diffable on
                # MFU by --attribution_diff without an extra bench run
                fenced = observe.histogram(
                    "train_device_blocked_seconds",
                    "time blocked on the device per step (fenced; only "
                    "recorded while a metrics sink or trace is "
                    "attached)")
                # reservoir first: exact order statistic, where the
                # fixed latency buckets only interpolate (a step time
                # mid-bucket can read up to ~40% off)
                p50 = fenced.sample_quantile(0.5) or fenced.quantile(0.5)
                if p50 and report.get("flops_per_step"):
                    report["mfu_est"] = round(costmodel.mfu(
                        report["flops_per_step"], p50,
                        devices=max(self.mesh.devices.size, 1)), 4)
                costmodel.dump_report(report, path)
                log.info("roofline/cost attribution written to %s "
                         "(%d regions)", path, len(report["regions"]))
            self._roofline_dumped = True
            if not (observe.active() or ohttp.serving()):
                self._roofline_feed = None   # keep nothing alive

    # --------------------------------------------------------- main loops
    def train(self, reader, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              feeder=None, test_reader=None,
              evaluators: Sequence = ()) -> None:
        event_handler = event_handler or _default_event_handler
        observe.start_from_flags()   # --metrics_jsonl sink, if configured
        wait_hist = observe.histogram(
            "data_reader_wait_seconds",
            "host time blocked on input per batch: the raw reader on "
            "the synchronous path, the prefetch queue when the async "
            "pipeline is on (--prefetch_depth > 0) — an input-pipeline "
            "stall either way")
        for pass_id in range(FLAGS.start_pass, FLAGS.start_pass + num_passes):
            event_handler(ev.BeginPass(pass_id))
            last_loss = None
            batch_id = 0
            # input-wait vs train-time split per pass: the input-bound
            # ratio is THE TPU-utilization diagnostic (Wang et al.,
            # arXiv:1907.10701) — ~0 means compute-bound, → 1 means the
            # chips starve on the input pipeline.  With the async
            # pipeline on, reader IO + convert + H2D run on worker
            # threads and `wait` is the queue-get stall, so the ratio
            # keeps meaning "host input work the step had to wait for".
            wait_s = 0.0
            busy_s = 0.0
            # the pass span is the trace root of everything this pass
            # does: step spans nest under it directly, and the async
            # pipeline's worker threads (created inside it) adopt its
            # context, so reader/convert/place and master-RPC spans all
            # land in the same trace as the steps that consumed them
            with trace.span("train_pass", pass_id=pass_id):
                src, pipe = self._pipeline_or_sync(reader, feeder)
                batches = iter(src)
                try:
                    while True:
                        t0 = time.perf_counter()
                        # sentinel instead of StopIteration so the last
                        # (end-of-pass) wait isn't a false error span
                        with trace.span("input_wait"):
                            batch = next(batches, _PASS_END)
                        if batch is _PASS_END:
                            break
                        dt = time.perf_counter() - t0
                        wait_s += dt
                        wait_hist.observe(dt)
                        event_handler(ev.BeginIteration(pass_id, batch_id))
                        t1 = time.perf_counter()
                        if pipe is not None:  # converted+placed upstream
                            feed = batch
                        else:
                            feed = feeder.convert(batch) if feeder \
                                else batch
                        if FLAGS.roofline_dump and \
                                self._roofline_feed is None:
                            self._roofline_feed = feed
                        loss = self.train_one_batch(
                            feed, placed=pipe is not None)
                        busy_s += time.perf_counter() - t1
                        last_loss = loss
                        if FLAGS.log_period and \
                                (batch_id + 1) % FLAGS.log_period == 0:
                            event_handler(ev.EndIteration(
                                pass_id=pass_id, batch_id=batch_id,
                                cost=float(loss)))
                        if FLAGS.show_parameter_stats_period and \
                                (batch_id + 1) % \
                                FLAGS.show_parameter_stats_period == 0:
                            from ..utils.profiler import parameter_stats
                            log.info("parameter stats:\n%s",
                                     parameter_stats(self.params))
                        batch_id += 1
                finally:
                    if pipe is not None:
                        pipe.close()
            self._sync_precision_metrics()   # pass boundary: one sync
            self._pass_boundary_observability()
            if wait_s + busy_s > 0:
                observe.gauge(
                    "input_bound_ratio",
                    "input wait / (input wait + train time) of the "
                    "last completed pass — reader wait on the sync "
                    "path, prefetch-queue wait with the async "
                    "pipeline; ~0 compute-bound, →1 input-bound"
                ).set(wait_s / (wait_s + busy_s))
            metrics = {}
            if test_reader is not None:
                res = self.test(test_reader, feeder, evaluators)
                metrics.update(res)
            if FLAGS.save_dir and FLAGS.saving_period and \
                    (pass_id + 1) % FLAGS.saving_period == 0:
                self.save(FLAGS.save_dir, pass_id)
            event_handler(ev.EndPass(
                pass_id=pass_id,
                metrics={"cost": float(last_loss) if last_loss is not None
                         else float("nan"), **metrics}))

    def test(self, reader, feeder=None, evaluators: Sequence = (),
             label_name: str = "label") -> Dict[str, float]:
        """``Tester::test`` equivalent.  With no explicit ``evaluators``,
        the model config's declared evaluators run (the v1
        ``*_evaluator(...)`` config calls).  Shares the async input
        pipeline with ``train`` (``--prefetch_depth``): convert +
        device placement overlap the eval steps."""
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        if not evaluators:
            evaluators = self._config_evaluators()
        total, n = 0.0, 0
        eval_names = self._eval_output_names() if evaluators else []
        for e in evaluators:
            e.start()
        with trace.span("test_pass"):
            src, pipe = self._pipeline_or_sync(reader, feeder)
            try:
                for batch in src:
                    if pipe is not None:    # converted+placed upstream
                        feed = batch
                    else:
                        feed = feeder.convert(batch) if feeder else batch
                        feed = self._shard_feed(feed)
                    loss, outputs = self._eval_step(self.params,
                                                    self.buffers, feed)
                    b = _batch_size(feed)
                    total += float(loss) * b
                    n += b
                    if evaluators:
                        # prefer the prediction layer over the cost output
                        out0 = outputs.get(eval_names[0]) if eval_names \
                            else None
                        if out0 is None:
                            out0 = next(iter(outputs.values()))
                        for e in evaluators:
                            entry = getattr(e, "_config_entry", None)
                            if entry:
                                ein = outputs.get(entry["input_layer_name"])
                                if ein is None:
                                    log.warning(
                                        "evaluator %s: input layer %r not "
                                        "in eval outputs; skipping",
                                        entry.get("name"),
                                        entry["input_layer_name"])
                                    continue
                                elab = feed.get(entry.get("label_layer_name",
                                                          label_name))
                                w = feed.get(entry["weight_layer_name"]) \
                                    if entry.get("weight_layer_name") \
                                    else None
                                if w is not None and "weight" in \
                                        e.eval_batch.__code__.co_varnames:
                                    e.eval_batch(ein, elab, weight=w)
                                else:
                                    e.eval_batch(ein, elab)
                            else:
                                e.eval_batch(out0, feed.get(label_name))
            finally:
                if pipe is not None:
                    pipe.close()
        metrics = {"test_cost": total / max(n, 1)}
        for e in evaluators:
            vals = e.finish()
            entry = getattr(e, "_config_entry", None)
            ename = (entry or {}).get("name", "")
            if ename and not ename.startswith("__"):
                # explicit evaluator names always prefix their metrics
                vals = {f"{ename}.{k}": v for k, v in vals.items()}
            else:
                # auto-named evaluators prefix only on collision, so two
                # same-type evaluators don't overwrite each other
                vals = {(k if k not in metrics
                         else f"{ename.strip('_')}.{k}"): v
                        for k, v in vals.items()}
            metrics.update(vals)
        return metrics

    def time_job(self, reader, feeder=None, warmup: int = 3,
                 batches: int = 20) -> Dict[str, float]:
        """``--job=time`` (TrainerBenchmark.cpp): steady-state ms/batch and
        samples/sec after compile+warmup."""
        it = iter(reader())
        feeds = []
        for _ in range(warmup + batches):
            try:
                batch = next(it)
            except StopIteration:
                break
            feeds.append(feeder.convert(batch) if feeder else batch)
        enforce(len(feeds) > warmup, "not enough batches to time")
        # float() forces a D2H sync; block_until_ready alone does not
        # reliably drain remote (tunneled) backends
        for f in feeds[:warmup]:
            loss = self.train_one_batch(f)
        float(loss)
        t0 = time.perf_counter()
        samples = 0
        for f in feeds[warmup:]:
            loss = self.train_one_batch(f)
            samples += _batch_size(f)
        float(loss)
        dt = time.perf_counter() - t0
        timed = len(feeds) - warmup
        return {
            "ms_per_batch": dt / timed * 1e3,
            "samples_per_sec": samples / dt,
            "batches": timed,
        }

    def check_gradients(self, feed: Dict[str, Any], eps: Optional[float] = None,
                        max_checks_per_param: int = 4,
                        rtol: float = 5e-2) -> bool:
        """``--job=checkgrad`` (Trainer::checkGradient): FD-check every
        parameter on one batch, fp32 forced."""
        from ..core.dtypes import full_precision

        eps = eps or FLAGS.checkgrad_eps
        ok = True
        with full_precision():
            loss_fn = lambda p: self.network.loss(
                p, feed, self.buffers, is_training=False)[0]
            grads = jax.grad(loss_fn)(self.params)
            for name, g in grads.items():
                p = self.params[name]
                idxs = np.random.RandomState(5).choice(
                    p.size, size=min(max_checks_per_param, p.size),
                    replace=False)
                for idx in idxs:
                    unit = np.zeros(p.size, np.float32)
                    unit[idx] = eps
                    unit = unit.reshape(p.shape)
                    lp = float(loss_fn({**self.params, name: p + unit}))
                    lm = float(loss_fn({**self.params, name: p - unit}))
                    fd = (lp - lm) / (2 * eps)
                    ag = float(np.asarray(g).reshape(-1)[idx])
                    if abs(ag - fd) > rtol * max(abs(fd), 1e-3):
                        log.warning("checkgrad FAIL %s[%d]: auto=%g fd=%g",
                                    name, idx, ag, fd)
                        ok = False
        return ok

    # -------------------------------------------------------- persistence
    def save(self, save_dir: str, pass_id: int) -> str:
        meta: Dict[str, Any] = {"samples_seen": self.samples_seen}
        if self._ls_state is not None:
            # persist the dynamic loss scale so resume keeps the warmed
            # scale instead of replaying the whole backoff search
            meta["loss_scale"] = {
                "scale": float(self._ls_state.scale),
                "growth_count": int(self._ls_state.growth_count),
                "skipped_total": int(self._ls_state.skipped_total),
            }
        return save_checkpoint(save_dir, pass_id, self.params,
                               self.opt_state, self.buffers, meta=meta,
                               shard=self._resolve_fsdp() is not None)

    def load(self, ckpt_dir: str, _verified: bool = False) -> None:
        # _verified: resume() already digest-checked this dir via
        # latest_valid_checkpoint — don't re-hash a multi-GB checkpoint
        if FLAGS.ckpt_verify and not _verified \
                and not verify_checkpoint(ckpt_dir):
            raise PaddleTpuError(
                f"checkpoint {ckpt_dir!r} failed integrity verification "
                "(manifest digest mismatch or torn files); pass "
                "--ckpt_verify=false to force the legacy blind load")
        loaded = load_params(ckpt_dir)
        missing = set(self.params) - set(loaded)
        if missing:
            strategy = FLAGS.load_missing_parameter_strategy
            if strategy == "fail":
                raise KeyError(f"checkpoint missing parameters: {missing}")
            log.warning("checkpoint missing %s (strategy=%s)", missing, strategy)
        self.params = {
            k: jnp.asarray(loaded[k]) if k in loaded else v
            for k, v in self.params.items()}
        bufs = load_buffers(ckpt_dir)
        if bufs:
            self.buffers = {k: jnp.asarray(v) for k, v in bufs.items()}
        opt = load_opt_state(ckpt_dir, self.opt_state)
        if opt is not None:
            self.opt_state = opt
        if self._resolve_fsdp() is not None:
            # resharding-on-load: checkpoints come back as FULL arrays
            # (shard files reassembled by the loader) whatever mesh
            # wrote them; re-place for THIS trainer's mesh so an FSDP
            # resume holds shards, not silent replicas
            self.params = self._place_params(self.params)
            self.opt_state = self._place_opt_state(self.opt_state,
                                                   self.params)
        try:
            man = load_manifest(ckpt_dir)
            self.samples_seen = man.get("samples_seen", 0)
            if self._ls_state is not None and "loss_scale" in man:
                m = man["loss_scale"]
                self._ls_state = ls.LossScaleState(
                    scale=jnp.asarray(float(m["scale"]), jnp.float32),
                    growth_count=jnp.asarray(
                        int(m.get("growth_count", 0)), jnp.int32),
                    skipped_total=jnp.asarray(
                        int(m.get("skipped_total", 0)), jnp.int32))
                self._skipped_reported = int(m.get("skipped_total", 0))
        except FileNotFoundError:
            pass
        if getattr(self, "_prune_masks", None):
            # regenerate pruning masks from the LOADED values (the
            # reference hook inits after any --init_model_path load)
            from ..optimizer.hooks import apply_prune_init, build_prune_masks
            self._prune_masks = build_prune_masks(
                self.network.param_specs, self.params)
            self.params = apply_prune_init(self.params, self._prune_masks)
            self._train_step = None  # re-capture the new masks

    def resume(self, save_dir: str) -> bool:
        """Load the newest checkpoint that passes digest verification,
        scanning backward past (and quarantining) corrupt dirs;
        ``--ckpt_verify=false`` restores the legacy blind-latest load."""
        if FLAGS.ckpt_verify:
            ckpt = latest_valid_checkpoint(save_dir)
        else:
            ckpt = latest_checkpoint(save_dir)
        if ckpt is None:
            return False
        self.load(ckpt, _verified=FLAGS.ckpt_verify)
        return True


def _batch_size(feed: Dict[str, Any]) -> int:
    for v in feed.values():
        return value_of(v).shape[0]
    return 0


def _default_event_handler(event) -> None:
    if isinstance(event, ev.EndIteration):
        log.info("pass %d batch %d cost=%.6f",
                 event.pass_id, event.batch_id, event.cost)
    elif isinstance(event, ev.EndPass):
        log.info("pass %d done: %s", event.pass_id, event.metrics)

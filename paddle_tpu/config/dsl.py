"""Layer-construction DSL → ModelConfig compiler.

Port of the v1/v2 user API surface: ``trainer_config_helpers/layers.py``
(131 layer functions compiled by ``config_parser.py``) and
``python/paddle/v2/layer.py`` (same functions as graph nodes).  Functions
here append :class:`LayerConfig` records to the active collector and return
:class:`LayerOutput` handles; ``topology(outputs)`` extracts the reachable
subgraph as a ModelConfig — the ``Topology.proto()`` equivalent
(``v2/topology.py:95``).

Naming parity: each function matches the reference DSL name (fc_layer is
``fc``, img_conv_layer is ``img_conv``, etc. — the v2 names, which drop the
``_layer`` suffix; v1 aliases are exported too).
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ..utils import ConfigError, enforce
from .model_config import (
    LayerConfig,
    LayerInput,
    ModelConfig,
    OptimizationConfig,
    ParameterConfig,
    ProjConfig,
    SubModelConfig,
)

# ----------------------------------------------------------- activations


class Activation:
    name = "linear"

    def __init__(self, name: Optional[str] = None):
        if name is not None:
            self.name = name


def _act_cls(act_name: str):
    return type(act_name.title().replace("_", "") + "Activation",
                (Activation,), {"name": act_name})


LinearActivation = _act_cls("")
ReluActivation = _act_cls("relu")
BReluActivation = _act_cls("brelu")
SigmoidActivation = _act_cls("sigmoid")
TanhActivation = _act_cls("tanh")
STanhActivation = _act_cls("stanh")
SoftmaxActivation = _act_cls("softmax")
SequenceSoftmaxActivation = _act_cls("sequence_softmax")
ExpActivation = _act_cls("exp")
LogActivation = _act_cls("log")
SquareActivation = _act_cls("square")
SqrtActivation = _act_cls("sqrt")
ReciprocalActivation = _act_cls("reciprocal")
AbsActivation = _act_cls("abs")
SoftReluActivation = _act_cls("soft_relu")


def _act_name(act) -> str:
    if act is None:
        return ""
    if isinstance(act, str):
        return act
    return act.name


# ------------------------------------------------------------ attributes


@dataclass
class HookAttribute:
    """``attrs.py`` HookAttribute — parameter updater hook spec.

    ``HookAttribute('pruning', sparsity_ratio=0.6)`` attaches the static
    pruning hook (``ParameterUpdaterHook.cpp:39`` StaticPruningHook): at
    init the smallest ``sparsity_ratio`` fraction of |w| is zeroed and the
    mask is applied to every subsequent gradient.
    """

    type: str = "pruning"
    sparsity_ratio: Optional[float] = 0.6

    def as_dict(self) -> Dict[str, Any]:
        enforce(self.type == "pruning",
                f"unknown parameter hook type {self.type!r}")
        if self.sparsity_ratio is not None:
            enforce(0.0 <= self.sparsity_ratio <= 1.0,
                    "sparsity_ratio must be in [0, 1]")
        return {"type": self.type, "sparsity_ratio": self.sparsity_ratio}


HookAttr = HookAttribute


@dataclass
class ParamAttr:
    """``attrs.py`` ParameterAttribute."""

    name: Optional[str] = None
    initial_mean: float = 0.0
    initial_std: Optional[float] = None
    learning_rate: float = 1.0
    momentum: float = 0.0
    l1_rate: float = 0.0
    l2_rate: float = 0.0
    is_static: bool = False
    sparse_update: bool = False
    initial_smart: bool = True
    update_hooks: Optional[Any] = None  # HookAttribute or list thereof


@dataclass
class ExtraAttr:
    """ExtraLayerAttribute: drop_rate, device (→ sharding hint),
    error_clipping_threshold (backward error clip)."""

    drop_rate: float = 0.0
    device: int = -1
    error_clipping_threshold: float = 0.0


# -------------------------------------------------------------- pooling


class BasePoolingType:
    name = "average"


class MaxPooling(BasePoolingType):
    name = "max"


class AvgPooling(BasePoolingType):
    name = "average"


class SumPooling(BasePoolingType):
    name = "sum"


class SqrtPooling(BasePoolingType):
    name = "sqrt"


# -------------------------------------------------------- the collector


class ConfigCollector(threading.local):
    def __init__(self):
        self.reset()

    def reset(self):
        self.layers: List[LayerConfig] = []
        self.by_name: Dict[str, LayerConfig] = {}
        self.parameters: List[ParameterConfig] = []
        self.sub_models: List[SubModelConfig] = []
        self.evaluators: List[Dict[str, Any]] = []
        self.counter = 0
        self.group_stack: List[SubModelConfig] = []
        # explicit input order from inputs() — empty means derive from
        # data layers in topological order
        self.declared_inputs: List[str] = []

    def unique_name(self, prefix: str) -> str:
        self.counter += 1
        return f"__{prefix}_{self.counter}__"

    def add(self, conf: LayerConfig) -> LayerConfig:
        if conf.name in self.by_name:
            raise ConfigError(f"duplicate layer name {conf.name!r}")
        self.layers.append(conf)
        self.by_name[conf.name] = conf
        if self.group_stack:
            self.group_stack[-1].layer_names.append(conf.name)
        return conf


_collector = ConfigCollector()


def reset_config() -> None:
    _collector.reset()


@dataclass
class LayerOutput:
    """Handle returned by every DSL function (v2 graph node)."""

    name: str
    layer_type: str
    size: int = 0
    # extra outputs (e.g. lstm step state) addressable as name.suffix
    parents: List["LayerOutput"] = field(default_factory=list)

    def __repr__(self):
        return f"LayerOutput({self.name}, {self.layer_type}, size={self.size})"


Input = Union[LayerOutput, Sequence[LayerOutput]]


def _as_list(x) -> List[LayerOutput]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _mk_inputs(inputs: List[LayerOutput],
               param_attrs: Optional[List[Optional[ParamAttr]]] = None,
               projs: Optional[List[Optional[ProjConfig]]] = None
               ) -> List[LayerInput]:
    out = []
    for i, li in enumerate(inputs):
        pa = param_attrs[i] if param_attrs else None
        out.append(LayerInput(
            input_layer_name=li.name,
            input_parameter_name=(pa.name if pa and pa.name else ""),
            proj=projs[i] if projs else None))
    return out


def _register_param_attr(owner_name: str, pa: Optional[ParamAttr],
                         idx: Optional[int], bias: bool = False) -> None:
    """Record a ParameterConfig override from a ParamAttr."""
    if pa is None:
        return
    name = pa.name or (f"_{owner_name}.wbias" if bias else f"_{owner_name}.w{idx}")
    pc = ParameterConfig(
        name=name,
        learning_rate=pa.learning_rate,
        momentum=pa.momentum,
        decay_rate=pa.l2_rate,
        decay_rate_l1=pa.l1_rate,
        initial_mean=pa.initial_mean,
        initial_std=pa.initial_std if pa.initial_std is not None else 0.01,
        initial_smart=pa.initial_smart and pa.initial_std is None,
        is_static=pa.is_static,
        sparse_update=pa.sparse_update,
        update_hooks=[h.as_dict() for h in _as_list(pa.update_hooks)]
        if pa.update_hooks else [],
    )
    _collector.parameters.append(pc)


def _bias_info(bias_attr) -> (bool, Optional[ParamAttr]):
    if bias_attr is False:
        return False, None
    if bias_attr is None or bias_attr is True:
        return True, None
    return True, bias_attr


def _extra(attrs: Dict[str, Any], layer_attr: Optional[ExtraAttr]
           ) -> Dict[str, Any]:
    return attrs


def _add_layer(name: Optional[str], ltype: str, size: int,
               inputs: List[LayerInput], act=None, bias_attr=False,
               attrs: Optional[Dict[str, Any]] = None,
               layer_attr: Optional[ExtraAttr] = None,
               param_attrs: Optional[List[Optional[ParamAttr]]] = None
               ) -> LayerOutput:
    name = name or _collector.unique_name(ltype)
    with_bias, bias_pa = _bias_info(bias_attr)
    conf = LayerConfig(
        name=name, type=ltype, size=size, active_type=_act_name(act),
        inputs=inputs, with_bias=with_bias,
        bias_parameter_name=(bias_pa.name if bias_pa and bias_pa.name
                             else ""),
        drop_rate=layer_attr.drop_rate if layer_attr else 0.0,
        device=layer_attr.device if layer_attr else -1,
        error_clipping_threshold=(layer_attr.error_clipping_threshold
                                  if layer_attr else 0.0),
        attrs=attrs or {})
    _collector.add(conf)
    if param_attrs:
        for i, pa in enumerate(param_attrs):
            _register_param_attr(name, pa, i)
    if bias_pa:
        _register_param_attr(name, bias_pa, None, bias=True)
    return LayerOutput(name=name, layer_type=ltype, size=size)


# ------------------------------------------------------------ data layer


def data(name: str, type=None, height: int = 0, width: int = 0,
         size: Optional[int] = None, **_ignored) -> LayerOutput:
    """``data_layer``.  Two calling conventions:

    - v2 style: ``type`` is a :class:`paddle_tpu.data.InputType`;
    - v1 style (reference configs): ``data_layer('x', size=N)`` — the
      actual input type comes from the data provider's input_types.
    """
    if isinstance(type, int):           # v1 positional: data_layer(name, size)
        size, type = type, None
    if type is None:
        enforce(size is not None, f"data layer {name!r}: pass type= or size=")
        from ..data.feeder import dense_vector
        type = dense_vector(size)
    conf = LayerConfig(name=name, type="data", size=type.dim,
                       attrs={"height": height, "width": width,
                              "seq_level": type.seq_level, "kind": type.kind})
    _collector.add(conf)
    return LayerOutput(name=name, layer_type="data", size=type.dim)


data_layer = data


# ----------------------------------------------------------- core layers


def fc(input: Input, size: int, act=None, name: Optional[str] = None,
       bias_attr=True, param_attr: Optional[ParamAttr] = None,
       layer_attr: Optional[ExtraAttr] = None) -> LayerOutput:
    ins = _as_list(input)
    pas = [param_attr] * len(ins) if param_attr else None
    return _add_layer(name, "fc", size, _mk_inputs(ins, pas), act,
                      bias_attr, layer_attr=layer_attr, param_attrs=pas)


fc_layer = fc


def embedding(input: Input, size: int, name: Optional[str] = None,
              param_attr: Optional[ParamAttr] = None,
              vocab_size: Optional[int] = None,
              sharded: bool = False) -> LayerOutput:
    inp = _as_list(input)[0]
    vocab = vocab_size or inp.size
    pas = [param_attr] if param_attr else None
    return _add_layer(None if name is None else name, "embedding", size,
                      _mk_inputs([inp], pas),
                      attrs={"vocab_size": vocab, "sharded": sharded},
                      param_attrs=pas)


embedding_layer = embedding


def addto(input: Input, act=None, name: Optional[str] = None,
          bias_attr=False, layer_attr=None) -> LayerOutput:
    ins = _as_list(input)
    return _add_layer(name, "addto", ins[0].size, _mk_inputs(ins), act,
                      bias_attr, layer_attr=layer_attr)


addto_layer = addto


def _proj_out_size(pc: ProjConfig) -> int:
    size = pc.resolved_output_size()
    enforce(size > 0,
            f"{pc.type} projection inside concat_layer needs an explicit "
            "size (pass size=N to the projection)")
    return size


def concat(input: Input, act=None, name: Optional[str] = None,
           bias_attr=False, layer_attr=None) -> LayerOutput:
    ins = _as_list(input)
    n_proj = sum(1 for i in ins if isinstance(i, tuple))
    if n_proj not in (0, len(ins)):
        raise ConfigError(
            "concat_layer inputs must be all layers or all projections, "
            f"got {n_proj} projection(s) among {len(ins)} inputs")
    if ins and isinstance(ins[0], tuple):
        # Projection inputs → 'concat2' (projection outputs concatenated;
        # reference layers.py:3309 CONCAT_PROJ_LAYER dispatch)
        lis = [t[0] for t in ins]
        pcs = [t[1] for t in ins]
        pas = [t[2] for t in ins]
        size = sum(_proj_out_size(pc) for pc in pcs)
        return _add_layer(name, "concat2", size, _mk_inputs(lis, pas, pcs),
                          act, bias_attr, layer_attr=layer_attr,
                          param_attrs=pas)
    return _add_layer(name, "concat", sum(i.size for i in ins),
                      _mk_inputs(ins), act, bias_attr,
                      layer_attr=layer_attr)


concat_layer = concat


def scaled_dot_product_attention(input: Input, size: int,
                                 num_heads: int = 1, causal: bool = False,
                                 name: Optional[str] = None, act=None,
                                 bias_attr=False,
                                 param_attr: Optional[ParamAttr] = None,
                                 layer_attr=None, block_q: int = 512,
                                 block_k: int = 512,
                                 packed: bool = False) -> LayerOutput:
    """Multi-head attention backed by the Pallas flash-attention kernel
    (``ops/pallas_attention.py``) — the kernel→layer→config wiring the
    reference used for ``hl_lstm``→``LstmLayer``→``lstmemory``.

    One input = self-attention; a ``[query, key, value]`` list =
    cross-attention.  Padded keys are masked from the sequence lengths.
    ``packed=True`` (self-attention only) runs the sequence-packing
    lowering: the padded batch shares one segment-id token axis and
    padding does zero work (``--attention_packing=false`` reverts).
    """
    ins = _as_list(input)
    if len(ins) not in (1, 3):
        raise ConfigError(
            "scaled_dot_product_attention takes 1 input (self-attention) "
            f"or 3 (query, key, value), got {len(ins)}")
    if packed and len(ins) != 1:
        raise ConfigError(
            "scaled_dot_product_attention(packed=True) is self-attention "
            f"only (1 input), got {len(ins)}")
    pas = [param_attr] + [None] * (len(ins) - 1) if param_attr else None
    return _add_layer(name, "scaled_dot_product_attention", size,
                      _mk_inputs(ins, pas), act, bias_attr,
                      attrs={"num_heads": num_heads, "causal": causal,
                             "block_q": block_q, "block_k": block_k,
                             "packed": packed},
                      layer_attr=layer_attr, param_attrs=pas)


multi_head_attention = scaled_dot_product_attention
scaled_dot_product_attention_layer = scaled_dot_product_attention


def layer_norm(input: Input, name: Optional[str] = None, act=None,
               bias_attr=True, epsilon: float = 1e-5,
               layer_attr=None) -> LayerOutput:
    """Layer normalization over the feature dim with learned gain/bias."""
    inp = _as_list(input)[0]
    return _add_layer(name, "layer_norm", inp.size, _mk_inputs([inp]),
                      act, bias_attr, attrs={"epsilon": epsilon},
                      layer_attr=layer_attr)


layer_norm_layer = layer_norm


def position_embedding(input: Input, max_len: int,
                       name: Optional[str] = None,
                       param_attr: Optional[ParamAttr] = None,
                       layer_attr=None) -> LayerOutput:
    """Adds a learned [max_len, size] position table to a sequence."""
    inp = _as_list(input)[0]
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "position_embedding", inp.size,
                      _mk_inputs([inp], pas),
                      attrs={"max_len": max_len},
                      layer_attr=layer_attr, param_attrs=pas)


position_embedding_layer = position_embedding


def dropout(input: Input, dropout_rate: float = 0.5,
            name: Optional[str] = None) -> LayerOutput:
    """v2 ``dropout`` = addto with drop_rate."""
    return addto(input, name=name,
                 layer_attr=ExtraAttr(drop_rate=dropout_rate))


def dropout_layer(input: Input, dropout_rate: float = 0.5,
                  name: Optional[str] = None) -> LayerOutput:
    return dropout(input, dropout_rate, name)


# ------------------------------------------------------------------ mixed


def full_matrix_projection(input: LayerOutput, size: int,
                           param_attr: Optional[ParamAttr] = None):
    return (input, ProjConfig(type="fc", input_size=input.size,
                              output_size=size), param_attr)


def identity_projection(input: LayerOutput, offset: Optional[int] = None,
                        size: Optional[int] = None):
    if offset is not None:
        end = offset + (size or input.size)
        return (input, ProjConfig(type="slice", input_size=input.size,
                                  slice_begin=offset, slice_end=end), None)
    return (input, ProjConfig(type="identity", input_size=input.size,
                              output_size=input.size), None)


def dotmul_projection(input: LayerOutput,
                      param_attr: Optional[ParamAttr] = None):
    return (input, ProjConfig(type="dot_mul", input_size=input.size,
                              output_size=input.size), param_attr)


def scaling_projection(input: LayerOutput,
                       param_attr: Optional[ParamAttr] = None):
    return (input, ProjConfig(type="scaling", input_size=input.size,
                              output_size=input.size), param_attr)


def table_projection(input: LayerOutput, size: int,
                     param_attr: Optional[ParamAttr] = None):
    return (input, ProjConfig(type="table", input_size=input.size,
                              output_size=size), param_attr)


def context_projection(input: LayerOutput, context_len: int,
                       context_start: Optional[int] = None,
                       padding_attr=False):
    start = context_start if context_start is not None \
        else -(context_len // 2)
    trainable = padding_attr is not False and padding_attr is not None
    return (input, ProjConfig(type="context", input_size=input.size,
                              context_start=start, context_length=context_len,
                              trainable_padding=trainable),
            padding_attr if trainable else None)


@dataclass
class Operator:
    """A mixed-layer operator (``conv_operator``/``dotmul_operator``):
    parameter-free, reads other layers' VALUES (``Operator.h``)."""

    kind: str
    op_inputs: List["LayerOutput"]
    attrs: Dict[str, Any]
    output_size: int = 0


class _MixedLayerBuilder(LayerOutput):
    """Context-manager form of ``mixed_layer`` (reference
    ``MixedLayerType``):

        with mixed_layer(size=n) as m:
            m += full_matrix_projection(input=x)
            m += dotmul_operator(a, b)

    Items collect via ``+=``; the real layer is built at ``__exit__``
    and this handle's LayerOutput fields are filled in place, so the
    ``as`` variable is usable downstream like any other output."""

    def __init__(self, **kw):
        super().__init__(name="<unfinished-mixed>", layer_type="mixed")
        self._kw = kw
        self._items: list = []
        self._finalized = False

    def __iadd__(self, other):
        if self._finalized:
            # the handle is an ordinary LayerOutput now; += means
            # layer_math addition like on any other output
            from .layer_math import add
            return add(self, other)
        self._items.append(other)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        enforce(self._items, "mixed_layer context added no projections")
        built = mixed(input=self._items, **self._kw)
        self._finalized = True
        self.name = built.name
        self.layer_type = built.layer_type
        self.size = built.size
        self.parents = built.parents
        return False


def mixed(input=None, size: int = 0, name: Optional[str] = None, act=None,
          bias_attr=False, layer_attr=None, operators=None) -> LayerOutput:
    """``mixed_layer``: input is a list of projection tuples; operators
    are :class:`Operator` objects appended as extra (projection-less)
    inputs.  Called with ``input=None`` it returns the context-manager
    builder (the reference's ``with mixed_layer(...) as m`` protocol)."""
    if input is None and operators is None:
        return _MixedLayerBuilder(size=size, name=name, act=act,
                                  bias_attr=bias_attr,
                                  layer_attr=layer_attr)
    items = _as_list(input)
    ins, pcs, pas = [], [], []
    op_list = []
    for item in items:
        if isinstance(item, Operator):
            op_list.append(item)
            continue
        li, pc, pa = item
        ins.append(li)
        pcs.append(pc)
        pas.append(pa)
    op_list.extend(_as_list(operators))
    op_attrs = []
    for op in op_list:
        idx = []
        for li in op.op_inputs:
            ins.append(li)
            pcs.append(None)
            pas.append(None)
            idx.append(len(ins) - 1)
        op_attrs.append({**op.attrs, "type": op.kind,
                         "input_indices": tuple(idx)})
        if size == 0 and op.output_size:
            size = op.output_size
    if size == 0:
        for pc in pcs:
            if pc is not None and pc.output_size:
                size = pc.output_size
                break
        else:
            enforce(pcs and pcs[0] is not None,
                    "mixed layer needs a size, a sized projection, or an "
                    "operator with a known output size")
            size = pcs[0].context_length * pcs[0].input_size
    attrs = {"operators": op_attrs} if op_attrs else None
    return _add_layer(name, "mixed", size, _mk_inputs(ins, pas, pcs), act,
                      bias_attr, attrs=attrs, layer_attr=layer_attr,
                      param_attrs=pas)


mixed_layer = mixed


# ------------------------------------------------------------------ image


def img_conv(input: Input, filter_size: int, num_filters: int,
             num_channels: Optional[int] = None, stride: int = 1,
             padding: int = 0, groups: int = 1, act=None,
             name: Optional[str] = None, bias_attr=True,
             param_attr: Optional[ParamAttr] = None,
             img_size: Optional[int] = None,
             img_size_y: Optional[int] = None,
             trans: bool = False, layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    img = img_size or int(round((inp.size / c) ** 0.5))
    img_y = img_size_y or img
    out_x = conv_out(img, filter_size, padding, stride)
    out_y = conv_out(img_y, filter_size, padding, stride)
    attrs = {"channels": c, "filter_size": filter_size,
             "num_filters": num_filters, "stride": stride, "padding": padding,
             "groups": groups, "img_size": img, "img_size_y": img_y,
             "output_x": out_x, "output_y": out_y}
    pas = [param_attr] if param_attr else None
    out = _add_layer(name, "exconvt" if trans else "exconv",
                     num_filters * out_x * out_y,
                     _mk_inputs([inp], pas), act, bias_attr, attrs,
                     layer_attr, pas)
    out.channels = num_filters
    out.img_size = out_x
    out.img_size_y = out_y
    return out


img_conv_layer = img_conv


def conv_projection(input: Input, filter_size: int, num_filters: int,
                    num_channels: Optional[int] = None, stride: int = 1,
                    padding: int = 0,
                    param_attr: Optional[ParamAttr] = None,
                    name: Optional[str] = None) -> LayerOutput:
    """``conv_projection`` (reference ``ConvProjection``): a bias-free
    linear convolution.  The reference materializes it inside the
    consuming concat/mixed layer; here it is its own conv layer — the
    concat of projection outputs is identical math."""
    return img_conv(input, filter_size, num_filters,
                    num_channels=num_channels, stride=stride,
                    padding=padding, act=LinearActivation(),
                    bias_attr=False, param_attr=param_attr, name=name)


def conv_out(img: int, filt: int, pad: int, stride: int) -> int:
    return (img + 2 * pad - filt) // stride + 1


def img_pool(input: Input, pool_size: int, num_channels: Optional[int] = None,
             pool_type: Optional[BasePoolingType] = None, stride: int = 2,
             padding: int = 0, name: Optional[str] = None,
             img_size: Optional[int] = None, img_size_y: Optional[int] = None,
             layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    img = img_size or getattr(inp, "img_size", int(round((inp.size / c) ** 0.5)))
    img_y = img_size_y or getattr(inp, "img_size_y", img)
    ptype = (pool_type or MaxPooling()).name
    out_x = conv_out(img, pool_size, padding, stride)
    out_y = conv_out(img_y, pool_size, padding, stride)
    attrs = {"channels": c, "pool_size": pool_size, "stride": stride,
             "padding": padding, "img_size": img, "img_size_y": img_y,
             "pool_type": ptype + "-projection"}
    out = _add_layer(name, "pool", c * out_x * out_y, _mk_inputs([inp]),
                     None, False, attrs, layer_attr)
    out.channels = c
    out.img_size = out_x
    out.img_size_y = out_y
    return out


img_pool_layer = img_pool


def batch_norm(input: Input, act=None, name: Optional[str] = None,
               num_channels: Optional[int] = None, bias_attr=True,
               param_attr=None, use_global_stats: Optional[bool] = None,
               moving_average_fraction: float = 0.9,
               layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", inp.size)
    attrs = {"channels": c,
             "moving_average_fraction": moving_average_fraction}
    if use_global_stats is not None:
        attrs["use_global_stats"] = use_global_stats
    if hasattr(inp, "img_size"):
        attrs["img_size"] = inp.img_size
        attrs["img_size_y"] = getattr(inp, "img_size_y", inp.img_size)
    pas = [param_attr] if param_attr else None
    out = _add_layer(name, "batch_norm", inp.size, _mk_inputs([inp], pas),
                     act, bias_attr, attrs, layer_attr, pas)
    for a in ("channels", "img_size", "img_size_y"):
        if hasattr(inp, a):
            setattr(out, a, getattr(inp, a))
    out.channels = c
    return out


batch_norm_layer = batch_norm


def img_cmrnorm(input: Input, size: int = 5, scale: float = 0.0128,
                power: float = 0.75, name: Optional[str] = None,
                num_channels: Optional[int] = None, layer_attr=None
                ) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    attrs = {"channels": c, "norm_size": size, "scale": scale / size,
             "pow": power,
             "img_size": getattr(inp, "img_size", None),
             "img_size_y": getattr(inp, "img_size_y", None)}
    out = _add_layer(name, "norm", inp.size, _mk_inputs([inp]), None, False,
                     attrs, layer_attr)
    for a in ("channels", "img_size", "img_size_y"):
        if hasattr(inp, a):
            setattr(out, a, getattr(inp, a))
    return out


img_cmrnorm_layer = img_cmrnorm


def maxout(input: Input, groups: int, num_channels: Optional[int] = None,
           name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    attrs = {"channels": c, "groups": groups,
             "img_size": getattr(inp, "img_size", None),
             "img_size_y": getattr(inp, "img_size_y", None)}
    out = _add_layer(name, "maxout", inp.size // groups, _mk_inputs([inp]),
                     None, False, attrs)
    out.channels = c // groups
    return out


maxout_layer = maxout


def spp(input: Input, pyramid_height: int, num_channels: Optional[int] = None,
        pool_type=None, name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    size = c * sum((2 ** i) ** 2 for i in range(pyramid_height))
    attrs = {"channels": c, "pyramid_height": pyramid_height,
             "pool_type": (pool_type or MaxPooling()).name,
             "img_size": getattr(inp, "img_size", None),
             "img_size_y": getattr(inp, "img_size_y", None)}
    return _add_layer(name, "spp", size, _mk_inputs([inp]), None, False, attrs)


spp_layer = spp


def bilinear_interp(input: Input, out_size_x: int, out_size_y: int,
                    num_channels: Optional[int] = None,
                    name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    attrs = {"channels": c, "out_size_x": out_size_x, "out_size_y": out_size_y,
             "img_size": getattr(inp, "img_size", None),
             "img_size_y": getattr(inp, "img_size_y", None)}
    out = _add_layer(name, "bilinear_interp", c * out_size_x * out_size_y,
                     _mk_inputs([inp]), None, False, attrs)
    out.channels = c
    out.img_size = out_size_x
    out.img_size_y = out_size_y
    return out


bilinear_interp_layer = bilinear_interp


# -------------------------------------------------------------- recurrent


def lstmemory(input: Input, name: Optional[str] = None, reverse: bool = False,
              act=None, gate_act=None, state_act=None, bias_attr=True,
              param_attr: Optional[ParamAttr] = None,
              size: Optional[int] = None, layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    h = size or inp.size // 4
    attrs = {"reversed": reverse,
             "active_gate_type": _act_name(gate_act) or "sigmoid",
             "active_state_type": _act_name(state_act) or "tanh"}
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "lstmemory", h, _mk_inputs([inp], pas),
                      act or TanhActivation(), bias_attr, attrs, layer_attr,
                      pas)


def grumemory(input: Input, name: Optional[str] = None, reverse: bool = False,
              act=None, gate_act=None, bias_attr=True,
              param_attr: Optional[ParamAttr] = None,
              size: Optional[int] = None, layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    h = size or inp.size // 3
    attrs = {"reversed": reverse,
             "active_gate_type": _act_name(gate_act) or "sigmoid"}
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "gated_recurrent", h, _mk_inputs([inp], pas),
                      act or TanhActivation(), bias_attr, attrs, layer_attr,
                      pas)


def gru_step_layer(input: Input, output_mem: LayerOutput,
                   size: Optional[int] = None, act=None, gate_act=None,
                   name: Optional[str] = None, bias_attr=True,
                   param_attr: Optional[ParamAttr] = None,
                   layer_attr=None) -> LayerOutput:
    """One GRU step for use inside recurrent groups (``GruStepLayer``);
    inputs: 3H projection of x, previous state (a memory link)."""
    inp = _as_list(input)[0]
    h = size or inp.size // 3
    # param_attr applies to the recurrent weight (input 0); the memory
    # link (input 1) carries no parameter
    pas = [param_attr, None] if param_attr else None
    return _add_layer(name, "gru_step", h,
                      _mk_inputs([inp, output_mem], pas),
                      act or TanhActivation(), bias_attr,
                      {"active_gate_type": _act_name(gate_act)
                       or "sigmoid"}, layer_attr, pas)


def lstm_step_layer(input: Input, state: LayerOutput,
                    size: Optional[int] = None, act=None, gate_act=None,
                    state_act=None, name: Optional[str] = None,
                    bias_attr=True, layer_attr=None) -> LayerOutput:
    """One LSTM step (``LstmStepLayer``); inputs: 4H projection, prev
    cell state.  Extra output ``.state`` is the new cell."""
    inp = _as_list(input)[0]
    h = size or inp.size // 4
    return _add_layer(name, "lstm_step", h, _mk_inputs([inp, state]),
                      act or TanhActivation(), bias_attr,
                      {"active_gate_type": _act_name(gate_act) or "sigmoid",
                       "active_state_type": _act_name(state_act)
                       or "tanh"}, layer_attr)


def recurrent(input: Input, act=None, bias_attr=True,
              param_attr: Optional[ParamAttr] = None, reverse: bool = False,
              name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "recurrent", inp.size, _mk_inputs([inp], pas),
                      act or TanhActivation(), bias_attr,
                      {"reversed": reverse}, None, pas)


recurrent_layer = recurrent


# -------------------------------------------------- recurrent groups


@dataclass
class StepInput:
    """Marks a sequence input scanned per-timestep inside a group."""

    layer: LayerOutput


class memory:
    """``memory(name=..., size=...)`` inside a recurrent group step
    (config_parser memory semantics: reads layer ``name``'s previous-step
    output; optional boot layer)."""

    def __init__(self, name: str, size: int, boot_layer: Optional[LayerOutput] = None,
                 boot_bias=None, is_seq: bool = False):
        enforce(_collector.group_stack, "memory() outside recurrent_group")
        group = _collector.group_stack[-1]
        self.link_name = f"{name}@pre@{group.name}"
        group.memories.append({
            "layer_name": name, "link_name": self.link_name, "size": size,
            "boot_layer_name": boot_layer.name if boot_layer else None,
        })
        self.out = LayerOutput(name=self.link_name, layer_type="memory",
                               size=size)

    def __getattr__(self, item):
        return getattr(self.out, item)


def recurrent_group(step: Callable, input, name: Optional[str] = None,
                    reverse: bool = False) -> Union[LayerOutput, List[LayerOutput]]:
    """``recurrent_group``: run ``step`` once to trace the per-step net.

    ``input``: StepInput(seq) entries are scanned; plain LayerOutputs are
    read-only (static) inputs visible at every step.
    """
    name = name or _collector.unique_name("recurrent_group")
    sub = SubModelConfig(name=name, reversed=reverse)
    ins = _as_list(input)
    step_args = []
    for i in ins:
        if isinstance(i, StepInput):
            sub.in_links.append(i.layer.name)
            # inside the group the step fn sees a per-frame view, same name
            step_args.append(LayerOutput(name=i.layer.name, layer_type="frame",
                                         size=i.layer.size))
        else:
            step_args.append(i)
    _collector.group_stack.append(sub)
    try:
        outs = step(*step_args)
    finally:
        _collector.group_stack.pop()
    out_list = _as_list(outs)
    sub.out_links = [o.name for o in out_list]
    _collector.sub_models.append(sub)
    results = [LayerOutput(name=o.name, layer_type="group_output", size=o.size)
               for o in out_list]
    return results[0] if len(results) == 1 else results


def simple_rnn_group(input, size, act=None, name=None, reverse=False):
    def step(x):
        mem = memory(name=f"{name or 'rnn'}_step", size=size)
        return fc([x, mem.out], size=size, act=act or TanhActivation(),
                  name=f"{name or 'rnn'}_step")

    return recurrent_group(step, [StepInput(_as_list(input)[0])],
                           name=name, reverse=reverse)


# ------------------------------------------------------- sequence layers


def pooling(input: Input, pooling_type: Optional[BasePoolingType] = None,
            name: Optional[str] = None, agg_level=None,
            stride: int = -1) -> LayerOutput:
    inp = _as_list(input)[0]
    ptype = (pooling_type or AvgPooling()).name
    lt = {"max": "max", "average": "average", "sum": "average",
          "sqrt": "average"}[ptype]
    attrs = {"stride": stride}
    if ptype in ("sum", "sqrt", "average"):
        attrs["average_strategy"] = {"average": "average", "sum": "sum",
                                     "sqrt": "squarerootn"}[ptype]
    return _add_layer(name, lt, inp.size, _mk_inputs([inp]), None, False,
                      attrs)


pooling_layer = pooling


def last_seq(input: Input, name: Optional[str] = None, agg_level=None,
             stride: int = -1, layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "seqlastins", inp.size, _mk_inputs([inp]),
                      None, False, {"stride": stride},
                      layer_attr=layer_attr)


def first_seq(input: Input, name: Optional[str] = None,
              agg_level=None, layer_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "seqfirstins", inp.size, _mk_inputs([inp]),
                      layer_attr=layer_attr)


def expand(input: Input, expand_as: LayerOutput, name: Optional[str] = None,
           expand_level=None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "expand", inp.size,
                      _mk_inputs([inp, expand_as]))


expand_layer = expand


def seq_concat(a: LayerOutput, b: LayerOutput,
               name: Optional[str] = None) -> LayerOutput:
    return _add_layer(name, "seqconcat", a.size, _mk_inputs([a, b]))


seq_concat_layer = seq_concat


def seq_reshape(input: Input, reshape_size: int,
                name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "seqreshape", reshape_size, _mk_inputs([inp]))


seq_reshape_layer = seq_reshape


def seq_slice(input: Input, starts=None, ends=None,
              name: Optional[str] = None) -> LayerOutput:
    ins = [_as_list(input)[0]]
    if starts is not None:
        ins.append(starts)
    if ends is not None:
        ins.append(ends)
    return _add_layer(name, "seq_slice", ins[0].size, _mk_inputs(ins))


seq_slice_layer = seq_slice


def sub_seq(input: Input, offsets: LayerOutput, sizes: LayerOutput,
            name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "subseq", inp.size,
                      _mk_inputs([inp, offsets, sizes]))


def kmax_seq_score(input: Input, beam_size: int = 1,
                   name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "kmax_seq_score", beam_size, _mk_inputs([inp]),
                      None, False, {"beam_size": beam_size})


kmax_sequence_score_layer = kmax_seq_score


def max_id(input: Input, name: Optional[str] = None,
           beam_size: int = 1) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "maxid", beam_size, _mk_inputs([inp]), None,
                      False, {"beam_size": beam_size})


maxid_layer = max_id


def sampling_id(input: Input, name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "sampling_id", 1, _mk_inputs([inp]))


sampling_id_layer = sampling_id


def eos(input: Input, eos_id: int, name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "eos_id", 1, _mk_inputs([inp]), None, False,
                      {"eos_id": eos_id})


eos_layer = eos


# --------------------------------------------------- beam-search generation


class GeneratedInput:
    """Marks the feedback input of a generating group: the embedding of the
    previous step's generated token (reference ``GeneratedInput`` in
    ``trainer_config_helpers/layers.py``; machinery
    ``RecurrentGradientMachine.cpp:539 generateSequence``)."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int):
        self.size = size                      # vocab size
        self.embedding_name = embedding_name  # shared table param name
        self.embedding_size = embedding_size


class StaticInput:
    """Read-only outer input visible at every generation step."""

    def __init__(self, input: LayerOutput, is_seq: bool = False):
        self.layer = _as_list(input)[0]
        self.is_seq = is_seq


def beam_search(step: Callable, input, bos_id: int, eos_id: int,
                beam_size: int = 5, max_length: int = 100,
                name: Optional[str] = None,
                num_results_per_sample: Optional[int] = None,
                candidate_adjust: Optional[Callable] = None,
                candidate_drop: Optional[Callable] = None) -> LayerOutput:
    """Build a generating recurrent group decoded by beam search
    (``beam_search`` in ``trainer_config_helpers/layers.py``; executed
    TPU-side as a fixed-trip ``lax.scan`` with top-k expansion in
    :mod:`paddle_tpu.layers.beam_search`).

    User candidate hooks — the ``beamSearchCandidateAdjust`` / drop
    callbacks of ``RecurrentGradientMachine.h:73-112``, re-designed as
    pure jax functions traced into the decode scan (no host
    round-trips):

    - ``candidate_adjust(logp, tokens, t) -> logp``: per-step token
      log-probs ``[B, K, V]`` (before beam scores are added), tokens
      decoded so far ``[B, K, max_length]``, scalar step ``t``; returns
      adjusted same-shape log-probs.
    - ``candidate_drop(logp, tokens, t) -> bool [B, K, V]``: True where
      a candidate must be pruned (its score is forced to −inf before
      top-k).
    """
    name = name or _collector.unique_name("beam_search")
    sub = SubModelConfig(name=name, is_generating=True)
    ins = _as_list(input) if not isinstance(input, (list, tuple)) else \
        list(input)
    gen: Optional[GeneratedInput] = None
    gen_pos = -1
    step_args: List[Any] = []
    static_names: List[str] = []
    placeholder = f"__{name}_gen_id__"
    for pos, i in enumerate(ins):
        if isinstance(i, GeneratedInput):
            enforce(gen is None, "beam_search allows one GeneratedInput")
            gen, gen_pos = i, pos
            step_args.append(None)  # filled inside the group scope
        elif isinstance(i, StaticInput):
            static_names.append(i.layer.name)
            step_args.append(i.layer)
        else:
            static_names.append(_as_list(i)[0].name)
            step_args.append(_as_list(i)[0])
    enforce(gen is not None, "beam_search needs a GeneratedInput")

    _collector.group_stack.append(sub)
    try:
        # previous generated token id (runtime-injected frame) → shared
        # embedding inside the group, so the table parameter is created
        # and shared with the training topology by name
        id_ph = LayerOutput(name=placeholder, layer_type="frame",
                            size=gen.size)
        prev_emb = embedding(id_ph, size=gen.embedding_size,
                             name=f"__{name}_gen_emb__",
                             param_attr=ParamAttr(name=gen.embedding_name),
                             vocab_size=gen.size)
        step_args[gen_pos] = prev_emb
        prob = _as_list(step(*step_args))[0]
    finally:
        _collector.group_stack.pop()

    sub.out_links = [prob.name]
    sub.generator = {
        "bos_id": bos_id, "eos_id": eos_id, "beam_size": beam_size,
        "max_length": max_length, "placeholder": placeholder,
        "embedding_name": gen.embedding_name,
        "embedding_size": gen.embedding_size,
        "vocab_size": gen.size, "prob_layer": prob.name,
        "num_results_per_sample": num_results_per_sample or beam_size,
        "static_inputs": static_names,
        "candidate_adjust": candidate_adjust,
        "candidate_drop": candidate_drop,
    }
    _collector.sub_models.append(sub)
    # the group's visible result: generated token sequences (+scores);
    # a real LayerConfig so topology() pulls the group in
    out = _add_layer(f"{name}__beam_gen__", "beam_gen", beam_size,
                     _mk_inputs([LayerOutput(prob.name, "group_output",
                                             prob.size)] +
                                [LayerOutput(s, "static", 0)
                                 for s in static_names]),
                     None, False, {"group_name": name})
    return out


# ------------------------------------------------------------ glue layers


def _simple(ltype: str, size_of=None):
    def f(input: Input, name: Optional[str] = None, act=None,
          **attrs) -> LayerOutput:
        ins = _as_list(input)
        size = size_of(ins) if size_of else ins[0].size
        return _add_layer(name, ltype, size, _mk_inputs(ins), act,
                          False, attrs or {})

    f.__name__ = ltype
    return f


# For the weighted glue layers input 0 is the (scalar-per-row) weight and
# input 1 carries the data, so the output size comes from input 1.
interpolation_layer = _simple("interpolation", lambda ins: ins[1].size)
power_layer = _simple("power", lambda ins: ins[1].size)
scaling_layer = _simple("scaling", lambda ins: ins[1].size)
trans_layer = _simple("trans")
row_l2_norm_layer = _simple("row_l2_norm")
sum_to_one_norm_layer = _simple("sum_to_one_norm")
dot_prod_layer = _simple("dot_prod", lambda ins: 1)
out_prod_layer = _simple("out_prod",
                         lambda ins: ins[0].size * ins[1].size)
# weights [B, K] select among K vectors packed in input 1 of size K*D → D
convex_comb_layer = _simple(
    "convex_comb", lambda ins: ins[1].size // max(ins[0].size, 1))


def slope_intercept(input: Input, slope: float = 1.0, intercept: float = 0.0,
                    name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "slope_intercept", inp.size, _mk_inputs([inp]),
                      None, False, {"slope": slope, "intercept": intercept})


slope_intercept_layer = slope_intercept


def clip(input: Input, min: float, max: float,
         name: Optional[str] = None) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "clip", inp.size, _mk_inputs([inp]), None, False,
                      {"min": min, "max": max})


clip_layer = clip


def scale_shift(input: Input, name: Optional[str] = None,
                bias_attr=True) -> LayerOutput:
    inp = _as_list(input)[0]
    return _add_layer(name, "scale_shift", inp.size, _mk_inputs([inp]),
                      None, bias_attr)


scale_shift_layer = scale_shift


def cos_sim(a: LayerOutput, b: LayerOutput, scale: float = 1.0,
            size: int = 1, name: Optional[str] = None) -> LayerOutput:
    lt = "cos" if size == 1 else "cos_vm"
    return _add_layer(name, lt, size, _mk_inputs([a, b]), None, False,
                      {"cos_scale": scale})


def prelu(input: Input, partial_sum: int = 1,
          name: Optional[str] = None, param_attr=None) -> LayerOutput:
    inp = _as_list(input)[0]
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "prelu", inp.size, _mk_inputs([inp], pas),
                      None, False, {"partial_sum": partial_sum},
                      param_attrs=pas)


prelu_layer = prelu


def multiplex(index: LayerOutput, inputs: Sequence[LayerOutput],
              name: Optional[str] = None) -> LayerOutput:
    ins = [index] + list(inputs)
    return _add_layer(name, "multiplex", inputs[0].size, _mk_inputs(ins))


multiplex_layer = multiplex


# ------------------------------------------------------------ cost layers


def classification_cost(input: LayerOutput, label: LayerOutput,
                        weight: Optional[LayerOutput] = None,
                        name: Optional[str] = None,
                        coeff: float = 1.0) -> LayerOutput:
    ins = [input, label] + ([weight] if weight else [])
    return _add_layer(name, "multi-class-cross-entropy", 1, _mk_inputs(ins),
                      None, False, {"coeff": coeff})


def cross_entropy_cost(input, label, name=None, coeff=1.0,
                       weight=None) -> LayerOutput:
    return classification_cost(input, label, weight, name, coeff)


cross_entropy = cross_entropy_cost


def cross_entropy_with_selfnorm_cost(input, label, name=None, coeff=1.0,
                                     softmax_selfnorm_alpha=0.1) -> LayerOutput:
    return _add_layer(name, "multi_class_cross_entropy_with_selfnorm", 1,
                      _mk_inputs([input, label]), None, False,
                      {"coeff": coeff,
                       "softmax_selfnorm_alpha": softmax_selfnorm_alpha})


def square_error_cost(input, label, name=None, coeff=1.0,
                      weight=None) -> LayerOutput:
    ins = [input, label] + ([weight] if weight else [])
    return _add_layer(name, "square_error", 1, _mk_inputs(ins), None, False,
                      {"coeff": coeff})


mse_cost = square_error_cost
regression_cost = square_error_cost


def multi_binary_label_cross_entropy_cost(input, label, name=None,
                                          coeff=1.0) -> LayerOutput:
    return _add_layer(name, "multi_binary_label_cross_entropy", 1,
                      _mk_inputs([input, label]), None, False, {"coeff": coeff})


def soft_binary_class_cross_entropy_cost(input, label, name=None,
                                         coeff=1.0) -> LayerOutput:
    return _add_layer(name, "soft_binary_class_cross_entropy", 1,
                      _mk_inputs([input, label]), None, False, {"coeff": coeff})


def rank_cost(left, right, label, weight=None, name=None,
              coeff=1.0) -> LayerOutput:
    ins = [left, right, label] + ([weight] if weight else [])
    return _add_layer(name, "rank-cost", 1, _mk_inputs(ins), None, False,
                      {"coeff": coeff})


def lambda_cost(input, score, name=None, NDCG_num=5,
                max_sort_size=-1) -> LayerOutput:
    return _add_layer(name, "lambda_cost", 1, _mk_inputs([input, score]),
                      None, False, {"NDCG_num": NDCG_num})


def huber_regression_cost(input, label, name=None, delta=1.0,
                          coeff=1.0) -> LayerOutput:
    return _add_layer(name, "huber_regression", 1, _mk_inputs([input, label]),
                      None, False, {"delta": delta, "coeff": coeff})


def huber_classification_cost(input, label, name=None,
                              coeff=1.0) -> LayerOutput:
    return _add_layer(name, "huber_classification", 1,
                      _mk_inputs([input, label]), None, False,
                      {"coeff": coeff})


def smooth_l1_cost(input, label, name=None, coeff=1.0) -> LayerOutput:
    return _add_layer(name, "smooth_l1", 1, _mk_inputs([input, label]),
                      None, False, {"coeff": coeff})


def sum_cost(input, name=None) -> LayerOutput:
    return _add_layer(name, "sum_cost", 1, _mk_inputs([_as_list(input)[0]]))


def crf(input: LayerOutput, label: LayerOutput, size: Optional[int] = None,
        weight=None, param_attr=None, name=None) -> LayerOutput:
    n = size or input.size
    ins = [input, label] + ([weight] if weight else [])
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "crf", n, _mk_inputs(ins, pas), None, False,
                      param_attrs=pas)


crf_layer = crf


def crf_decoding(input: LayerOutput, size: Optional[int] = None,
                 label: Optional[LayerOutput] = None, param_attr=None,
                 name=None) -> LayerOutput:
    n = size or input.size
    ins = [input] + ([label] if label else [])
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "crf_decoding", n, _mk_inputs(ins, pas), None,
                      False, param_attrs=pas)


crf_decoding_layer = crf_decoding


def ctc(input: LayerOutput, label: LayerOutput, size: Optional[int] = None,
        norm_by_times: bool = False, name=None) -> LayerOutput:
    return _add_layer(name, "ctc", size or input.size,
                      _mk_inputs([input, label]), None, False,
                      {"norm_by_times": norm_by_times})


ctc_layer = ctc


def warp_ctc(input: LayerOutput, label: LayerOutput, size=None, blank=0,
             norm_by_times=False, name=None) -> LayerOutput:
    return _add_layer(name, "warp_ctc", size or input.size,
                      _mk_inputs([input, label]), None, False,
                      {"blank": blank, "norm_by_times": norm_by_times})


warp_ctc_layer = warp_ctc


def nce(input: LayerOutput, label: LayerOutput, num_classes: int,
        num_neg_samples: int = 10, name=None, param_attr=None,
        bias_attr=True) -> LayerOutput:
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "nce", 1, _mk_inputs([input, label], pas), None,
                      bias_attr, {"num_classes": num_classes,
                                  "num_neg_samples": num_neg_samples},
                      param_attrs=pas)


nce_layer = nce


def hsigmoid(input: LayerOutput, label: LayerOutput, num_classes: int,
             name=None, param_attr=None, bias_attr=True) -> LayerOutput:
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "hsigmoid", 1, _mk_inputs([input, label], pas),
                      None, bias_attr, {"num_classes": num_classes},
                      param_attrs=pas)


hsigmoid_layer = hsigmoid


# --------------------------------------------------------------- topology


def topology(outputs: Input,
             extra_layers: Optional[Input] = None) -> ModelConfig:
    """Extract the reachable subgraph as a ModelConfig
    (``Topology``/``parse_network`` equivalent)."""
    outs = _as_list(outputs) + _as_list(extra_layers)
    by_name = _collector.by_name
    mem_links = {}
    for sm in _collector.sub_models:
        for m in sm.memories:
            mem_links.setdefault(m.get("link_name"), m["layer_name"])
    group_by_layer = {}
    for sm in _collector.sub_models:
        for ln in sm.layer_names:
            group_by_layer[ln] = sm

    needed: List[str] = []
    seen = set()

    def visit(name: str):
        if name in seen:
            return
        seen.add(name)
        if name in mem_links:
            visit(mem_links[name])
            return
        conf = by_name.get(name)
        if conf is None:
            return
        # pull the whole group when any member is needed
        sm = group_by_layer.get(name)
        if sm is not None:
            for l in sm.in_links:
                visit(l)
            for m in sm.memories:
                if m.get("boot_layer_name"):
                    visit(m["boot_layer_name"])
            for ln in sm.layer_names:
                if ln not in seen:
                    seen.add(ln)
                    for i in by_name[ln].inputs:
                        visit(i.input_layer_name)
                    needed.append(ln)
        for i in conf.inputs:
            visit(i.input_layer_name)
        needed.append(name)

    for o in outs:
        visit(o.name)
    # declared evaluators keep their input layers alive as extra graph
    # roots (reference: evaluator inputs are part of the model)
    for e in _collector.evaluators:
        for key in ("input_layer_name", "label_layer_name",
                    "weight_layer_name"):
            if e.get(key) in by_name:
                visit(e[key])

    # needed is already topologically ordered by the DFS append order
    layers = [by_name[n] for n in needed if n in by_name]
    used_groups = [sm for sm in _collector.sub_models
                   if any(ln in seen for ln in sm.layer_names)]
    layer_names = {l.name for l in layers}
    return ModelConfig(
        layers=layers,
        parameters=list(_collector.parameters),
        input_layer_names=(_validated_inputs(layers)
                           or [l.name for l in layers if l.type == "data"]),
        output_layer_names=[o.name for o in _as_list(outputs)],
        sub_models=([SubModelConfig(name="root")] + used_groups)
        if used_groups else [],
        evaluators=[e for e in _collector.evaluators
                    if e.get("input_layer_name") in layer_names],
    )


def inputs(layers, *args) -> None:
    """Declare the network input order explicitly
    (``networks.py:1485``) — the data provider must feed in this order."""
    ins = _as_list(layers) + list(args)
    _collector.declared_inputs = [
        l if isinstance(l, str) else l.name for l in ins]


def _validated_inputs(kept_layers) -> List[str]:
    """inputs() names checked against the final topology — a typo'd or
    pruned layer fails at config time, as the reference Inputs() does."""
    declared = _collector.declared_inputs
    if declared:
        kept = {l.name for l in kept_layers}
        unknown = [n for n in declared if n not in kept]
        if unknown:
            raise ConfigError(
                f"inputs() declares layers not in the topology: {unknown}")
    return list(declared)


@contextlib.contextmanager
def config_scope():
    """Isolated collector scope (parse one config independently)."""
    global _collector
    old = _collector
    _collector = ConfigCollector()
    try:
        yield _collector
    finally:
        _collector = old


# ---------------------------------------------------- v1 DSL parity layer
# The remaining ``trainer_config_helpers/layers.py`` ``__all__`` surface:
# thin wrappers over already-registered engine layer types (reference
# signatures kept; tests/test_dsl_parity.py asserts 1:1 name coverage).


class AggregateLevel:
    """``AggregateLevel`` (layers.py:275)."""

    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    # deprecated reference spellings
    EACH_TIMESTEP = "non-seq"
    EACH_SEQUENCE = "seq"


class ExpandLevel:
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = AggregateLevel.TO_NO_SEQUENCE


class LayerType:
    """Layer type-string constants (``layers.py LayerType``) — the subset
    configs actually reference, mapped to this engine's registered names."""

    DATA = "data"
    FC_LAYER = "fc"
    MIXED_LAYER = "mixed"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "gated_recurrent"
    SEQUENCE_LAST_INSTANCE = "seqlastins"
    SEQUENCE_FIRST_INSTANCE = "seqfirstins"
    POOLING_MAX = "max"
    POOLING_AVG = "average"
    CONV_LAYER = "exconv"
    CONVTRANS_LAYER = "exconvt"
    POOL_LAYER = "pool"
    BATCH_NORM_LAYER = "batch_norm"
    NORM_LAYER = "norm"
    COST = "cost"
    CRF_LAYER = "crf"
    CTC_LAYER = "ctc"

    @staticmethod
    def is_layer_type(type_name: str) -> bool:
        from ..layers import LAYERS
        return type_name in LAYERS


def layer_support(*attrs):
    """Reference decorator marking ExtraLayerAttribute support — the TPU
    engine accepts ExtraAttr uniformly, so this is a no-op passthrough."""

    def deco(fn):
        return fn

    return deco


@dataclass
class SubsequenceInput:
    """Marks a nested-sequence in-link of a recurrent group
    (``SubsequenceInput``): the group steps over subsequences.  The TPU
    group dispatches on the runtime NestedSequenceBatch type, so this is
    StepInput with intent documented."""

    layer: LayerOutput


class BaseGeneratedInput:
    """Base marker class (``layers.py BaseGeneratedInput``)."""


# ---- projections / operators


def trans_full_matrix_projection(input: LayerOutput, size: int = 0,
                                 param_attr: Optional[ParamAttr] = None):
    """``TransposedFullMatrixProjection``: y = x W^T with W [size, in]."""
    return (input, ProjConfig(type="trans_fc", input_size=input.size,
                              output_size=size), param_attr)


def slice_projection(input: LayerOutput, slices):
    """``SliceProjection``: concatenate [begin, end) column ranges."""
    slices = [tuple(s) for s in slices]
    for b, e in slices:
        enforce(0 <= b < e <= input.size,
                f"slice ({b}, {e}) out of range for input size {input.size}")
    return (input, ProjConfig(type="slice", input_size=input.size,
                              output_size=sum(e - b for b, e in slices),
                              slices=slices), None)


def dotmul_operator(a: LayerOutput = None, b: LayerOutput = None,
                    scale: float = 1.0, **kwargs) -> Operator:
    """``DotMulOperator``: elementwise a*b*scale inside a mixed layer."""
    a = a or kwargs.get("x")
    b = b or kwargs.get("y")
    enforce(a is not None and b is not None, "dotmul_operator needs a and b")
    return Operator(kind="dot_mul", op_inputs=[a, b],
                    attrs={"scale": scale}, output_size=a.size)


def conv_operator(img: LayerOutput, filter: LayerOutput, filter_size: int,
                  num_filters: int, num_channels: Optional[int] = None,
                  stride: int = 1, padding: int = 0,
                  filter_size_y: Optional[int] = None,
                  stride_y: Optional[int] = None,
                  padding_y: Optional[int] = None,
                  trans: bool = False) -> Operator:
    """``ConvOperator``: convolution whose per-sample filter comes from
    another layer's output (``ConvOperator.cpp``)."""
    enforce(not trans, "conv_operator: transposed conv operators are not "
            "supported (no reference config uses ConvTransOperator via "
            "the v1 DSL)")
    c = num_channels or getattr(img, "channels", 1)
    isz = getattr(img, "img_size", int(round((img.size / c) ** 0.5)))
    isz_y = getattr(img, "img_size_y", isz)
    fy = filter_size_y or filter_size
    sy = stride_y or stride
    py = padding_y if padding_y is not None else padding
    out_x = conv_out(isz, filter_size, padding, stride)
    out_y = conv_out(isz_y, fy, py, sy)
    return Operator(
        kind="conv", op_inputs=[img, filter],
        attrs={"channels": c, "img_size": isz, "img_size_y": isz_y,
               "filter_size": filter_size, "filter_size_y": fy,
               "num_filters": num_filters, "stride": stride, "stride_y": sy,
               "padding": padding, "padding_y": py},
        output_size=num_filters * out_x * out_y)


# ---- shape / image glue layers


def repeat_layer(input: LayerOutput, num_repeats: int,
                 as_row_vector: bool = True, act=None,
                 name: Optional[str] = None, layer_attr=None) -> LayerOutput:
    """``RepeatLayer`` (type featmap_expand): tile features num_repeats×."""
    inp = _as_list(input)[0]
    attrs = {"num_filters": num_repeats, "as_row_vector": as_row_vector}
    return _add_layer(name, "featmap_expand", inp.size * num_repeats,
                      _mk_inputs([inp]), act, False, attrs, layer_attr)


def rotate_layer(input: LayerOutput, height: int, width: int,
                 name: Optional[str] = None, layer_attr=None) -> LayerOutput:
    """``RotateLayer``: 90° CCW rotation of [H, W] feature matrices."""
    inp = _as_list(input)[0]
    return _add_layer(name, "rotate", inp.size, _mk_inputs([inp]), None,
                      False, {"height": height, "width": width}, layer_attr)


def resize_layer(input: LayerOutput, size: int,
                 name: Optional[str] = None) -> LayerOutput:
    """``ResizeLayer``: reshape the batch to rows of ``size``."""
    inp = _as_list(input)[0]
    return _add_layer(name, "resize", size, _mk_inputs([inp]), None, False)


def pad_layer(input: LayerOutput, pad_c=None, pad_h=None, pad_w=None,
              name: Optional[str] = None, layer_attr=None) -> LayerOutput:
    """``PadLayer``: zero-pad along channel/height/width."""
    inp = _as_list(input)[0]
    pad_c = list(pad_c or [0, 0])
    pad_h = list(pad_h or [0, 0])
    pad_w = list(pad_w or [0, 0])
    c = getattr(inp, "channels", 1)
    h = getattr(inp, "img_size_y", getattr(inp, "img_size", None))
    w = getattr(inp, "img_size", None)
    if w is None:
        w = h = int(round((inp.size / c) ** 0.5))
    oc, oh, ow = c + sum(pad_c), h + sum(pad_h), w + sum(pad_w)
    attrs = {"channels": c, "img_size": w, "img_size_y": h,
             "pad_c": pad_c, "pad_h": pad_h, "pad_w": pad_w}
    out = _add_layer(name, "pad", oc * oh * ow, _mk_inputs([inp]), None,
                     False, attrs, layer_attr)
    out.channels, out.img_size, out.img_size_y = oc, ow, oh
    return out


def crop_layer(input, offset, axis: int = 2, shape=None,
               name: Optional[str] = None, layer_attr=None) -> LayerOutput:
    """``CropLayer``: crop [H, W] windows (axis=2 → spatial crop, the only
    mode the reference demos use)."""
    inp = _as_list(input)[0]
    enforce(axis == 2 and shape is not None,
            "crop_layer: only spatial (axis=2) cropping with an explicit "
            "shape is supported")
    c = getattr(inp, "channels", 1)
    h = getattr(inp, "img_size_y", getattr(inp, "img_size", None))
    w = getattr(inp, "img_size", None)
    if w is None:
        w = h = int(round((inp.size / c) ** 0.5))
    ch, cw = shape[-2], shape[-1]
    attrs = {"channels": c, "img_size": w, "img_size_y": h,
             "crop_offsets": list(offset), "crop_shape": [ch, cw]}
    out = _add_layer(name, "crop", c * ch * cw, _mk_inputs([inp]), None,
                     False, attrs, layer_attr)
    out.channels, out.img_size, out.img_size_y = c, cw, ch
    return out


def switch_order_layer(input: LayerOutput, name: Optional[str] = None,
                       reshape_axis: Optional[int] = None, act=None,
                       layer_attr=None) -> LayerOutput:
    """``SwitchOrderLayer``: NCHW ↔ NHWC reorder (reshape_axis=3 ↔
    channels-last, the reference's only used mode)."""
    inp = _as_list(input)[0]
    to = "NHWC" if (reshape_axis or 3) == 3 else "NCHW"
    attrs = {"to": to, "channels": getattr(inp, "channels", 1),
             "img_size": getattr(inp, "img_size", None),
             "img_size_y": getattr(inp, "img_size_y", None)}
    return _add_layer(name, "switch_order", inp.size, _mk_inputs([inp]),
                      act, False, attrs, layer_attr)


def block_expand_layer(input: LayerOutput, block_x: int = 0, block_y: int = 0,
                       stride_x: int = 0, stride_y: int = 0,
                       padding_x: int = 0, padding_y: int = 0,
                       num_channels: Optional[int] = None,
                       name: Optional[str] = None,
                       layer_attr=None) -> LayerOutput:
    """``BlockExpandLayer``: im2col into a sequence of flattened blocks
    (OCR models; output is a sequence over block positions)."""
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    attrs = {"channels": c, "block_x": block_x, "block_y": block_y,
             "stride_x": stride_x, "stride_y": stride_y,
             "padding_x": padding_x, "padding_y": padding_y,
             "img_size": getattr(inp, "img_size", None),
             "img_size_y": getattr(inp, "img_size_y", None)}
    return _add_layer(name, "blockexpand", c * block_x * block_y,
                      _mk_inputs([inp]), None, False, attrs, layer_attr)


# ---- dense / misc layers


def tensor_layer(a: LayerOutput, b: LayerOutput, size: int, act=None,
                 name: Optional[str] = None,
                 param_attr: Optional[ParamAttr] = None, bias_attr=True,
                 layer_attr=None) -> LayerOutput:
    """``TensorLayer``: out_k = a W_k b^T."""
    pas = [param_attr, None] if param_attr else None  # one weight, on input 0
    return _add_layer(name, "tensor", size, _mk_inputs([a, b], pas), act,
                      bias_attr, layer_attr=layer_attr, param_attrs=pas)


def selective_fc_layer(input, size: int, select: Optional[LayerOutput] = None,
                       act=None, name: Optional[str] = None,
                       pass_generation: bool = False,
                       has_selected_colums: bool = True,
                       mul_ratio: float = 0.02,
                       param_attr: Optional[ParamAttr] = None,
                       bias_attr=True, layer_attr=None) -> LayerOutput:
    """``SelectiveFullyConnectedLayer``: fc evaluated only on selected
    output columns."""
    ins = _as_list(input)
    if select is not None:
        ins = ins + [select]
    pas = [param_attr] * len(ins) if param_attr else None
    return _add_layer(name, "selective_fc", size, _mk_inputs(ins, pas), act,
                      bias_attr, layer_attr=layer_attr, param_attrs=pas)


def linear_comb_layer(weights: LayerOutput, vectors: LayerOutput,
                      size: Optional[int] = None, name: Optional[str] = None,
                      layer_attr=None) -> LayerOutput:
    """``ConvexCombinationLayer`` (type convex_comb): out = w · reshaped
    vectors."""
    size = size or vectors.size // max(weights.size, 1)
    return _add_layer(name, "convex_comb", size,
                      _mk_inputs([weights, vectors]), None, False,
                      layer_attr=layer_attr)


def conv_shift_layer(a: LayerOutput, b: LayerOutput,
                     name: Optional[str] = None, layer_attr=None
                     ) -> LayerOutput:
    """``ConvShiftLayer``: circular convolution (NTM addressing); b's
    width must be odd."""
    enforce(b.size % 2 == 1, "conv_shift: filter width must be odd")
    return _add_layer(name, "conv_shift", a.size, _mk_inputs([a, b]), None,
                      False, layer_attr=layer_attr)


def row_conv_layer(input: LayerOutput, context_len: int, act=None,
                   name: Optional[str] = None,
                   param_attr: Optional[ParamAttr] = None,
                   layer_attr=None) -> LayerOutput:
    """``RowConvLayer``: lookahead convolution (DeepSpeech2)."""
    pas = [param_attr] if param_attr else None
    return _add_layer(name, "row_conv", input.size, _mk_inputs([input], pas),
                      act, False, {"context_length": context_len},
                      layer_attr, pas)


def gated_unit_layer(input: LayerOutput, size: int, act=None,
                     name: Optional[str] = None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=True,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=True, layer_attr=None) -> LayerOutput:
    """Gated linear unit (``gated_unit_layer``, Dauphin et al.): a
    composite of two fc layers joined by a dotmul operator — the
    reference builds the identical three-layer graph."""
    name = name or _collector.unique_name("gated_unit")
    proj = fc(input, size, act=act, name=f"{name}_input_proj",
              param_attr=inproj_param_attr, bias_attr=inproj_bias_attr,
              layer_attr=inproj_attr)
    gate = fc(input, size, act=SigmoidActivation(), name=f"{name}_gate",
              param_attr=gate_param_attr, bias_attr=gate_bias_attr,
              layer_attr=gate_attr)
    return mixed(operators=[dotmul_operator(proj, gate)], size=size,
                 name=name, layer_attr=layer_attr)


def print_layer(input, format: Optional[str] = None,
                name: Optional[str] = None) -> LayerOutput:
    """``PrintLayer``: host-side debug print of layer values."""
    ins = _as_list(input)
    return _add_layer(name, "print", ins[0].size, _mk_inputs(ins), None,
                      False, {"format": format})


printer_layer = print_layer


# ------------------------------------------------- config-time evaluators
# trainer_config_helpers/evaluators.py __all__: each call registers an
# EvaluatorConfig on the model; the Trainer instantiates and streams them
# during --job=test (reference: Evaluator::create from ModelConfig,
# paddle/gserver/evaluators/Evaluator.h:42).

def evaluator_base(input, type: str, label=None, name: Optional[str] = None,
                   weight=None, **attrs) -> None:
    inp = _as_list(input)[0]
    entry: Dict[str, Any] = {
        "type": type,
        "name": name or f"__{type}_evaluator_{len(_collector.evaluators)}__",
        "input_layer_name": inp.name if isinstance(inp, LayerOutput) else inp,
    }
    if label is not None:
        entry["label_layer_name"] = label.name \
            if isinstance(label, LayerOutput) else label
    if weight is not None:
        entry["weight_layer_name"] = weight.name \
            if isinstance(weight, LayerOutput) else weight
    entry.update({k: v for k, v in attrs.items() if v is not None})
    _collector.evaluators.append(entry)


def _mk_evaluator_fn(public: str, registry: str):
    def fn(input, label=None, name: Optional[str] = None, **kw) -> None:
        evaluator_base(input, registry, label=label, name=name, **kw)

    fn.__name__ = public
    fn.__doc__ = f"``{public}``: registers a ``{registry}`` evaluator " \
                 "on the model config."
    return fn


_EVALUATOR_NAME_MAP = {
    "classification_error_evaluator": "classification_error",
    "auc_evaluator": "auc",
    "pnpair_evaluator": "pnpair",
    "precision_recall_evaluator": "precision_recall",
    "ctc_error_evaluator": "ctc_edit_distance",
    "chunk_evaluator": "chunk",
    "sum_evaluator": "sum",
    "column_sum_evaluator": "column_sum",
    "value_printer_evaluator": "value_printer",
    "gradient_printer_evaluator": "gradient_printer",
    "maxid_printer_evaluator": "maxid_printer",
    "maxframe_printer_evaluator": "maxframe_printer",
    "seqtext_printer_evaluator": "seq_text_printer",
    "classification_error_printer_evaluator": "classification_error_printer",
    "detection_map_evaluator": "detection_map",
}
for _pub, _reg in _EVALUATOR_NAME_MAP.items():
    globals()[_pub] = _mk_evaluator_fn(_pub, _reg)


def get_output_layer(input: LayerOutput, arg_name: str,
                     name: Optional[str] = None, layer_attr=None
                     ) -> LayerOutput:
    """``GetOutputLayer``: select a named extra output (e.g. lstm ``state``)
    of a layer — addressed here as the dotted value ``layer.arg_name``."""
    src = input.name if arg_name in ("", "out") else f"{input.name}.{arg_name}"
    return _add_layer(name, "get_output", input.size,
                      [LayerInput(input_layer_name=src)], None, False,
                      layer_attr=layer_attr)


def gru_step_naive_layer(input: LayerOutput, output_mem: LayerOutput,
                         size: Optional[int] = None,
                         name: Optional[str] = None, act=None,
                         gate_act=None, bias_attr=None, param_attr=None,
                         layer_attr=None) -> LayerOutput:
    """``gru_step_naive_layer``: the reference re-derives the GRU step from
    primitive layers (identical math to the fused ``gru_step``); here both
    names drive the same fused TPU step kernel."""
    return gru_step_layer(input, output_mem, size=size, name=name, act=act,
                          gate_act=gate_act, bias_attr=bias_attr,
                          param_attr=param_attr, layer_attr=layer_attr)


def sub_nested_seq_layer(input: LayerOutput, selected_indices: LayerOutput,
                         name: Optional[str] = None) -> LayerOutput:
    """``SubNestedSequenceLayer``: select subsequences by index."""
    return _add_layer(name, "sub_nested_seq", input.size,
                      _mk_inputs([input, selected_indices]), None, False)


kmax_seq_score_layer = kmax_sequence_score_layer


# ---- SSD detection layers


def priorbox_layer(input: LayerOutput, image: LayerOutput, aspect_ratio,
                   variance, min_size, max_size=[],
                   name: Optional[str] = None) -> LayerOutput:
    """``PriorBoxLayer`` (SSD): generate prior boxes over the feature map
    grid of ``input`` relative to ``image`` dimensions."""
    from ..ops.detection_ops import num_priors_per_cell

    c = getattr(input, "channels", 1)
    lw = getattr(input, "img_size", int(round((input.size / c) ** 0.5)))
    lh = getattr(input, "img_size_y", lw)
    img_conf = _collector.by_name.get(image.name)
    iw = ih = None
    if img_conf is not None:
        iw = img_conf.attrs.get("width") or None
        ih = img_conf.attrs.get("height") or None
    if not iw:
        ic = getattr(image, "channels", 3)
        iw = ih = int(round((image.size / ic) ** 0.5))
    n = lh * lw * num_priors_per_cell(min_size, max_size, aspect_ratio)
    attrs = {"layer_width": lw, "layer_height": lh,
             "image_width": iw, "image_height": ih,
             "min_size": list(min_size), "max_size": list(max_size),
             "aspect_ratio": list(aspect_ratio), "variance": list(variance)}
    return _add_layer(name, "priorbox", n * 8, _mk_inputs([input, image]),
                      None, False, attrs)


def cross_channel_norm_layer(input: LayerOutput, name: Optional[str] = None,
                             param_attr: Optional[ParamAttr] = None
                             ) -> LayerOutput:
    """``CrossChannelNormLayer`` (SSD conv4_3 L2 norm with learned scale)."""
    c = getattr(input, "channels", 1)
    pas = [param_attr] if param_attr else None
    out = _add_layer(name, "cross-channel-norm", input.size,
                     _mk_inputs([input], pas), None, False,
                     {"channels": c}, param_attrs=pas)
    for a in ("channels", "img_size", "img_size_y"):
        if hasattr(input, a):
            setattr(out, a, getattr(input, a))
    return out


def multibox_loss_layer(input_loc, input_conf, priorbox: LayerOutput,
                        label: LayerOutput, num_classes: int,
                        overlap_threshold: float = 0.5,
                        neg_pos_ratio: float = 3.0,
                        neg_overlap: float = 0.5, background_id: int = 0,
                        name: Optional[str] = None) -> LayerOutput:
    """``MultiBoxLossLayer`` (SSD training loss)."""
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    enforce(len(locs) == len(confs),
            "multibox_loss: need matching loc/conf input lists")
    attrs = {"num_classes": num_classes, "input_num": len(locs),
             "overlap_threshold": overlap_threshold,
             "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
             "background_id": background_id}
    return _add_layer(name, "multibox_loss", 1,
                      _mk_inputs([priorbox, label] + locs + confs), None,
                      False, attrs)


def detection_output_layer(input_loc, input_conf, priorbox: LayerOutput,
                           num_classes: int, nms_threshold: float = 0.45,
                           nms_top_k: int = 400, keep_top_k: int = 200,
                           confidence_threshold: float = 0.01,
                           background_id: int = 0,
                           name: Optional[str] = None) -> LayerOutput:
    """``DetectionOutputLayer`` (SSD inference: decode + NMS)."""
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    enforce(len(locs) == len(confs),
            "detection_output: need matching loc/conf input lists")
    attrs = {"num_classes": num_classes, "input_num": len(locs),
             "nms_threshold": nms_threshold, "nms_top_k": nms_top_k,
             "keep_top_k": keep_top_k,
             "confidence_threshold": confidence_threshold,
             "background_id": background_id}
    return _add_layer(name, "detection_output", keep_top_k * 7,
                      _mk_inputs([priorbox] + locs + confs), None, False,
                      attrs)


# ---- 3-D image layers


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def img_conv3d_layer(input: LayerOutput, filter_size, num_filters: int,
                     name: Optional[str] = None,
                     num_channels: Optional[int] = None, act=None,
                     groups: int = 1, stride=1, padding=0, bias_attr=None,
                     param_attr: Optional[ParamAttr] = None,
                     shared_biases: bool = True, layer_attr=None,
                     trans: bool = False,
                     layer_type: Optional[str] = None) -> LayerOutput:
    """``Conv3DLayer``/``DeConv3DLayer`` over NDHWC volumes."""
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    fz, fy, fx = _triple(filter_size)
    sz, sy, sx = _triple(stride)
    pz, py, px = _triple(padding)
    d = getattr(inp, "img_size_z", None)
    h = getattr(inp, "img_size_y", None)
    w = getattr(inp, "img_size", None)
    if w is None:
        side = int(round((inp.size / c) ** (1.0 / 3.0)))
        d = h = w = side
    from ..layers.image3d import conv3d_out_shape

    od, oh, ow = conv3d_out_shape(d, h, w, (fz, fy, fx), (pz, py, px),
                                  (sz, sy, sx))
    attrs = {"channels": c, "num_filters": num_filters, "groups": groups,
             "filter_size": fx, "filter_size_y": fy, "filter_size_z": fz,
             "stride": sx, "stride_y": sy, "stride_z": sz,
             "padding": px, "padding_y": py, "padding_z": pz,
             "img_size": w, "img_size_y": h, "img_size_z": d}
    pas = [param_attr] if param_attr else None
    ltype = layer_type or ("deconv3d" if trans else "conv3d")
    out = _add_layer(name, ltype, num_filters * od * oh * ow,
                     _mk_inputs([inp], pas), act,
                     True if bias_attr is None else bias_attr, attrs,
                     layer_attr, pas)
    out.channels = num_filters
    out.img_size, out.img_size_y, out.img_size_z = ow, oh, od
    return out


def img_pool3d_layer(input: LayerOutput, pool_size,
                     name: Optional[str] = None,
                     num_channels: Optional[int] = None, pool_type=None,
                     stride=2, padding=0, layer_attr=None,
                     pool_size_y=None, stride_y=None, padding_y=None,
                     pool_size_z=None, stride_z=None, padding_z=None,
                     ceil_mode: bool = True) -> LayerOutput:
    """``Pool3DLayer`` over NDHWC volumes."""
    inp = _as_list(input)[0]
    c = num_channels or getattr(inp, "channels", 1)
    kx = pool_size if isinstance(pool_size, int) else pool_size[-1]
    ky = pool_size_y or kx
    kz = pool_size_z or kx
    sx = stride if isinstance(stride, int) else stride[-1]
    sy = stride_y or sx
    sz = stride_z or sx
    px = padding if isinstance(padding, int) else padding[-1]
    py = padding_y if padding_y is not None else px
    pz = padding_z if padding_z is not None else px
    d = getattr(inp, "img_size_z", None)
    h = getattr(inp, "img_size_y", None)
    w = getattr(inp, "img_size", None)
    if w is None:
        side = int(round((inp.size / c) ** (1.0 / 3.0)))
        d = h = w = side
    from ..layers.image3d import conv3d_out_shape

    od, oh, ow = conv3d_out_shape(d, h, w, (kz, ky, kx), (pz, py, px),
                                  (sz, sy, sx), caffe_mode=not ceil_mode)
    attrs = {"channels": c, "pool_type": (pool_type or MaxPooling()).name,
             "pool_size": kx, "pool_size_y": ky, "pool_size_z": kz,
             "stride": sx, "stride_y": sy, "stride_z": sz,
             "padding": px, "padding_y": py, "padding_z": pz,
             "img_size": w, "img_size_y": h, "img_size_z": d}
    out = _add_layer(name, "pool3d", c * od * oh * ow, _mk_inputs([inp]),
                     None, False, attrs, layer_attr)
    out.channels = c
    out.img_size, out.img_size_y, out.img_size_z = ow, oh, od
    return out


# ---- beam cost


@dataclass
class BeamInput:
    """One beam-expansion triple for :func:`cross_entropy_over_beam`
    (``layers.py:6014``)."""

    candidate_scores: LayerOutput
    selected_candidates: LayerOutput
    gold: LayerOutput


def cross_entropy_over_beam(input, name: Optional[str] = None) -> LayerOutput:
    """``cross_entropy_over_beam`` (globally-normalized beam CE,
    ``CrossEntropyOverBeam.cpp``): input is a list of BeamInput triples."""
    beams = _as_list(input)
    ins: List[LayerOutput] = []
    for bi in beams:
        ins.extend([bi.candidate_scores, bi.selected_candidates, bi.gold])
    return _add_layer(name, "cross_entropy_over_beam", 1, _mk_inputs(ins),
                      None, False)


# ---- cost-name aliases (reference __all__ spellings)

cross_entropy_with_selfnorm = cross_entropy_with_selfnorm_cost
multi_binary_label_cross_entropy = multi_binary_label_cross_entropy_cost

"""``layer_math`` — arithmetic sugar over ``LayerOutput``.

Reference: ``python/paddle/trainer_config_helpers/layer_math.py`` —
unary math ops as activation-carrying mixed layers, plus operator
overloads (`+ - *` with scalars and layers) installed ON LayerOutput.
Used by the VAE demo config (``v1_api_demo/vae/vae_conf.py``) among
others; imported into the v1 config namespace as ``layer_math``.
"""

from __future__ import annotations

from . import dsl
from .dsl import LayerOutput
from ..utils import ConfigError

__all__ = []


def _register_unary(op_name: str, act_cls_name: str) -> None:
    act_cls = getattr(dsl, act_cls_name)

    def op(input, name=None):
        return dsl.mixed_layer(
            input=[dsl.identity_projection(input=input)], name=name,
            act=act_cls())

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


_register_unary("exp", "ExpActivation")
_register_unary("log", "LogActivation")
_register_unary("abs", "AbsActivation")
_register_unary("sigmoid", "SigmoidActivation")
_register_unary("tanh", "TanhActivation")
_register_unary("square", "SquareActivation")
_register_unary("relu", "ReluActivation")
_register_unary("sqrt", "SqrtActivation")
_register_unary("reciprocal", "ReciprocalActivation")


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def add(layeroutput, other):
    if _is_number(other):
        return dsl.slope_intercept_layer(input=layeroutput,
                                         intercept=float(other))
    if not isinstance(other, LayerOutput):
        raise ConfigError("LayerOutput can only be added with another "
                          "LayerOutput or a number")
    if layeroutput.size == other.size:
        return dsl.mixed_layer(input=[
            dsl.identity_projection(input=layeroutput),
            dsl.identity_projection(input=other)])
    if other.size != 1 and layeroutput.size != 1:
        raise ConfigError(
            "two LayerOutputs can be added only with equal sizes or one "
            f"size-1 operand; sizes are {layeroutput.size} and {other.size}")
    if layeroutput.size == 1:
        layeroutput, other = other, layeroutput
    other = dsl.repeat_layer(other, layeroutput.size)
    return dsl.mixed_layer(input=[
        dsl.identity_projection(input=layeroutput),
        dsl.identity_projection(input=other)])


def sub(layeroutput, other):
    if _is_number(other):
        return dsl.slope_intercept_layer(input=layeroutput,
                                         intercept=-float(other))
    if not isinstance(other, LayerOutput):
        raise ConfigError("LayerOutput can only be subtracted with "
                          "another LayerOutput or a number")
    neg = dsl.slope_intercept_layer(input=other, slope=-1.0)
    return add(layeroutput, neg)


def rsub(layeroutput, other):
    neg = dsl.slope_intercept_layer(input=layeroutput, slope=-1.0)
    return add(neg, other)


def mul(layeroutput, other):
    if _is_number(other):
        return dsl.slope_intercept_layer(input=layeroutput,
                                         slope=float(other))
    if not isinstance(other, LayerOutput):
        raise ConfigError("LayerOutput can only be multiplied with "
                          "another LayerOutput or a number")
    if layeroutput.size == 1:
        return dsl.scaling_layer(input=other, weight=layeroutput)
    if other.size == 1:
        return dsl.scaling_layer(input=layeroutput, weight=other)
    raise ConfigError("at least one operand of '*' must be a number or a "
                      "LayerOutput with size=1")


LayerOutput.__add__ = add
LayerOutput.__radd__ = add
LayerOutput.__sub__ = sub
LayerOutput.__rsub__ = rsub
LayerOutput.__mul__ = mul
LayerOutput.__rmul__ = mul

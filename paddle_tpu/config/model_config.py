"""Model configuration IR.

The reference routes every model through protobuf ``ModelConfig``
(``proto/ModelConfig.proto:637``, ``LayerConfig:347``, ``ParameterConfig``),
produced by the Python DSLs and consumed by the C++ engine.  Here the IR is
plain dataclasses with the same field vocabulary (names follow the proto) —
serializable to/from JSON for checkpoint metadata and inspection.  The v1/v2
layer DSLs in :mod:`paddle_tpu.config.layers_v2` compile to this IR, and
:class:`paddle_tpu.layers.network.NeuralNetwork` executes it.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils import ConfigError, enforce


@dataclass
class ParameterConfig:
    """Mirror of ``proto/ParameterConfig.proto`` (the trainable-weight spec)."""

    name: str = ""
    size: int = 0
    dims: List[int] = field(default_factory=list)
    learning_rate: float = 1.0          # per-parameter lr scale
    momentum: float = 0.0
    decay_rate: float = 0.0             # L2
    decay_rate_l1: float = 0.0          # L1
    initial_mean: float = 0.0
    initial_std: float = 0.01
    initial_strategy: int = 0           # 0: normal, 1: uniform
    initial_smart: bool = False         # std = 1/sqrt(fan_in)
    is_static: bool = False
    is_sparse: bool = False
    sparse_update: bool = False
    sharded: bool = False               # TPU: shard over 'model' axis
    # ParameterUpdaterHookConfig list, e.g.
    # [{"type": "pruning", "sparsity_ratio": 0.6}]
    update_hooks: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class ProjConfig:
    """Projection/operator inside a mixed layer (``ProjectionConfig``)."""

    type: str = "fc"                    # fc|identity|dot_mul|scaling|table|context|slice
    input_size: int = 0
    output_size: int = 0
    context_start: int = 0
    context_length: int = 0
    trainable_padding: bool = False
    slice_begin: int = 0
    slice_end: int = 0
    # multi-slice form (SliceProjection concatenates selected ranges)
    slices: Optional[List[Tuple[int, int]]] = None

    def resolved_output_size(self) -> int:
        """Projection output width, derived from the type when
        ``output_size`` is unset; 0 when underdetermined (an unsized
        fc/trans_fc/table)."""
        if self.output_size:
            return self.output_size
        if self.type == "context":
            return self.context_length * self.input_size
        if self.type == "slice":
            slices = self.slices or [(self.slice_begin, self.slice_end)]
            return sum(e - b for b, e in slices)
        if self.type in ("identity", "dot_mul", "scaling"):
            return self.input_size
        return 0


@dataclass
class LayerInput:
    """One input edge of a layer (``LayerInputConfig``)."""

    input_layer_name: str = ""
    input_parameter_name: str = ""
    proj: Optional[ProjConfig] = None
    # conv/pool/norm/image-specific geometry (ConvConfig/PoolConfig/NormConfig)
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class LayerConfig:
    """Mirror of ``proto/ModelConfig.proto:347`` LayerConfig."""

    name: str = ""
    type: str = ""
    size: int = 0
    active_type: str = ""
    inputs: List[LayerInput] = field(default_factory=list)
    bias_parameter_name: str = ""
    with_bias: bool = False
    drop_rate: float = 0.0
    # clip the output-gradient (error) to ±t in backward (Layer.cpp
    # backwardActivation error clipping); 0 = off
    error_clipping_threshold: float = 0.0
    # free-form per-type attributes (pool type, conv geometry, context, ...)
    attrs: Dict[str, Any] = field(default_factory=dict)
    # device hint (--parallel_nn per-layer placement → sharding annotation)
    device: int = -1

    def input_names(self) -> List[str]:
        return [i.input_layer_name for i in self.inputs]


@dataclass
class SubModelConfig:
    """Recurrent-group sub-model (``SubModelConfig`` — in/out links,
    memories; reference ``config_parser.py:367`` RecurrentLayerGroupBegin)."""

    name: str = ""
    layer_names: List[str] = field(default_factory=list)
    in_links: List[str] = field(default_factory=list)
    out_links: List[str] = field(default_factory=list)
    memories: List[Dict[str, Any]] = field(default_factory=list)
    reversed: bool = False
    is_generating: bool = False
    generator: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelConfig:
    """Mirror of ``proto/ModelConfig.proto:637``."""

    layers: List[LayerConfig] = field(default_factory=list)
    parameters: List[ParameterConfig] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    sub_models: List[SubModelConfig] = field(default_factory=list)
    # EvaluatorConfig entries: {"type", "name", "input_layer_name",
    # "label_layer_name", **extra attrs} (``ModelConfig.proto`` evaluators)
    evaluators: List[Dict[str, Any]] = field(default_factory=list)

    def layer_map(self) -> Dict[str, LayerConfig]:
        return {l.name: l for l in self.layers}

    def param_map(self) -> Dict[str, ParameterConfig]:
        return {p.name: p for p in self.parameters}

    def find_layer(self, name: str) -> LayerConfig:
        for l in self.layers:
            if l.name == name:
                return l
        raise ConfigError(f"no layer named {name!r}")

    def find_size(self, name: str) -> int:
        """Size of a layer output OR a recurrent-group memory link."""
        for l in self.layers:
            if l.name == name:
                return l.size
        for sm in self.sub_models:
            for mem in sm.memories:
                if mem.get("link_name") == name or \
                        mem.get("layer_name") + "@pre" == name:
                    size = mem.get("size", 0)
                    return size or self.find_layer(mem["layer_name"]).size
        raise ConfigError(f"no layer or memory link named {name!r}")

    def to_json(self) -> str:
        # beam-search candidate hooks (and any other runtime callables a
        # config may carry) are code, not configuration — a dumped
        # config regains them only from its source .py, so serialize a
        # marker instead of crashing json.dumps
        def scrub(v):
            if callable(v):
                return f"<callable {getattr(v, '__name__', 'fn')}>"
            if isinstance(v, dict):
                return {k: scrub(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [scrub(x) for x in v]
            return v

        return json.dumps(scrub(dataclasses.asdict(self)), indent=1)

    @staticmethod
    def from_json(text: str) -> "ModelConfig":
        raw = json.loads(text)

        def mk_input(d):
            proj = ProjConfig(**d["proj"]) if d.get("proj") else None
            return LayerInput(
                input_layer_name=d.get("input_layer_name", ""),
                input_parameter_name=d.get("input_parameter_name", ""),
                proj=proj, attrs=d.get("attrs", {}))

        return ModelConfig(
            layers=[
                LayerConfig(
                    **{**l, "inputs": [mk_input(i) for i in l.get("inputs", [])]})
                for l in raw.get("layers", [])
            ],
            parameters=[ParameterConfig(**p) for p in raw.get("parameters", [])],
            input_layer_names=raw.get("input_layer_names", []),
            output_layer_names=raw.get("output_layer_names", []),
            sub_models=[SubModelConfig(**s) for s in raw.get("sub_models", [])],
            evaluators=raw.get("evaluators", []),
        )


@dataclass
class OptimizationConfig:
    """Mirror of ``proto/TrainerConfig.proto`` OptimizationConfig +
    ``OptimizerConfig.proto``."""

    batch_size: int = 32
    learning_rate: float = 0.01
    learning_method: str = "sgd"
    learning_rate_schedule: str = "constant"
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_args: str = ""
    momentum: float = 0.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    ada_epsilon: float = 1e-6
    ada_rou: float = 0.95
    l1_weight_decay: float = 0.0
    l2_weight_decay: float = 0.0
    gradient_clipping_threshold: float = 0.0
    average_window: float = 0.0
    max_average_window: int = 0
    num_batches_per_send_parameter: int = 1
    num_batches_per_get_parameter: int = 1
    # Training precision policy: "fp32" | "bf16" | "" (empty = inherit
    # the --precision flag, whose default is fp32).  bf16 = fp32 master
    # weights with bf16 compute casts at the train-step boundary, fp32
    # optimizer state/gradient accumulation, and dynamic loss scaling —
    # see core/dtypes.resolve_precision and trainer/trainer.py.
    precision: str = ""
    # Async-SGD re-expression (ParameterServer2.h:468 lock-free async
    # apply; doOperation AVERAGE_PARAMETER, ParameterService.proto:24-110):
    # each data-parallel shard applies K local optimizer steps without
    # gradient synchronization, then parameters are averaged across the
    # mesh.  0 = synchronous all-reduce DP (default).  K=1 with plain SGD
    # is numerically identical to sync DP (tests/test_local_sgd.py).
    local_sgd_steps: int = 0


@dataclass
class TrainerConfig:
    """Mirror of ``proto/TrainerConfig.proto:140``."""

    model_config: ModelConfig = field(default_factory=ModelConfig)
    opt_config: OptimizationConfig = field(default_factory=OptimizationConfig)
    num_passes: int = 1
    save_dir: str = "./output"
    test_period: int = 0
    log_period: int = 100

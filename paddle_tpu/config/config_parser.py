"""v1 config-file protocol (``config_parser.py:4208 parse_config``).

A reference config file does ``from paddle.trainer_config_helpers import *``
then calls ``settings(...)``, ``define_py_data_sources2(...)``, builds
layers and calls ``outputs(...)``; ``get_config_arg`` reads
``--config_args``.  This module executes such files in a namespace exposing
the TPU-native DSL so reference-style configs (benchmark/paddle/*) run
with minimal edits, producing (ModelConfig, OptimizationConfig, data
sources).
"""

from __future__ import annotations

import importlib
import os
import runpy
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils import ConfigError, enforce
from . import dsl
from .model_config import ModelConfig, OptimizationConfig


# ---------------------------------------------------------- settings DSL
class _OptSetting:
    name = "sgd"
    extra: Dict[str, Any] = {}

    def apply(self, oc: OptimizationConfig) -> None:
        oc.learning_method = self.name
        for k, v in self.extra.items():
            setattr(oc, k, v)


class MomentumOptimizer(_OptSetting):
    name = "momentum"

    def __init__(self, momentum: float = 0.9, sparse: bool = False):
        self.extra = {"momentum": momentum}


class AdamOptimizer(_OptSetting):
    name = "adam"

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.extra = {"adam_beta1": beta1, "adam_beta2": beta2,
                      "adam_epsilon": epsilon}


class AdamaxOptimizer(_OptSetting):
    name = "adamax"

    def __init__(self, beta1=0.9, beta2=0.999):
        self.extra = {"adam_beta1": beta1, "adam_beta2": beta2}


class AdaGradOptimizer(_OptSetting):
    name = "adagrad"


class AdaDeltaOptimizer(_OptSetting):
    name = "adadelta"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.extra = {"ada_rou": rho, "ada_epsilon": epsilon}


class RMSPropOptimizer(_OptSetting):
    name = "rmsprop"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.extra = {"ada_rou": rho, "ada_epsilon": epsilon}


class DecayedAdaGradOptimizer(_OptSetting):
    name = "decayed_adagrad"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.extra = {"ada_rou": rho, "ada_epsilon": epsilon}


class BaseRegularization:
    rate = 0.0


class L2Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate


class L1Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate


@dataclass
class DataSources:
    train_list: Optional[str] = None
    test_list: Optional[str] = None
    module: Optional[str] = None
    obj: Optional[str] = None
    args: Dict[str, Any] = field(default_factory=dict)


class _ParseState(threading.local):
    def __init__(self):
        self.reset()

    def reset(self):
        self.opt = OptimizationConfig()
        self.outputs: List[dsl.LayerOutput] = []
        self.data_sources = DataSources()
        self.config_args: Dict[str, str] = {}


_state = _ParseState()


def get_config_arg(name: str, type_, default=None):
    v = _state.config_args.get(name)
    if v is None:
        return default
    if type_ is bool:
        return str(v).lower() in ("1", "true", "yes")
    return type_(v)


def settings(batch_size: int = 32, learning_rate: float = 0.01,
             learning_method: Optional[_OptSetting] = None,
             regularization: Optional[BaseRegularization] = None,
             gradient_clipping_threshold: float = 0.0,
             learning_rate_decay_a: float = 0.0,
             learning_rate_decay_b: float = 0.0,
             learning_rate_schedule: str = "constant",
             average_window: float = 0.0,
             max_average_window: int = 0,
             local_sgd_steps: int = 0, **_ignored) -> None:
    oc = _state.opt
    oc.local_sgd_steps = local_sgd_steps
    oc.batch_size = batch_size
    oc.learning_rate = learning_rate
    oc.gradient_clipping_threshold = gradient_clipping_threshold
    oc.learning_rate_decay_a = learning_rate_decay_a
    oc.learning_rate_decay_b = learning_rate_decay_b
    oc.learning_rate_schedule = learning_rate_schedule
    oc.average_window = average_window
    oc.max_average_window = max_average_window
    (learning_method or _OptSetting()).apply(oc)
    if isinstance(regularization, L2Regularization):
        oc.l2_weight_decay = regularization.rate
    elif isinstance(regularization, L1Regularization):
        oc.l1_weight_decay = regularization.rate


def outputs(*layers) -> None:
    for group in layers:
        if isinstance(group, (list, tuple)):
            _state.outputs.extend(group)
        else:
            _state.outputs.append(group)


def define_py_data_sources2(train_list, test_list, module, obj,
                            args=None) -> None:
    _state.data_sources = DataSources(train_list, test_list, module, obj,
                                      dict(args or {}))


def parse_config_args(s: str) -> Dict[str, str]:
    out = {}
    for part in (s or "").split(","):
        part = part.strip()
        if part and "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def config_namespace() -> Dict[str, Any]:
    """Names a config file sees (the ``import *`` surface)."""
    ns: Dict[str, Any] = {}
    for k in dir(dsl):
        if not k.startswith("_"):
            ns[k] = getattr(dsl, k)
    # trainer_config_helpers.networks composites (vgg.py/rnn.py use them)
    from ..v2 import networks as _networks
    for k in dir(_networks):
        if not k.startswith("_") and callable(getattr(_networks, k)) \
                and k not in ns:
            ns[k] = getattr(_networks, k)
    from . import layer_math
    ns["layer_math"] = layer_math
    # trainer/recurrent_units.py helpers (v1 config-parser level)
    from . import recurrent_units as _ru
    for k in _ru.__all__:
        ns.setdefault(k, getattr(_ru, k))
    from ..data import feeder
    for k in ("dense_vector", "integer_value", "integer_value_sequence",
              "sparse_binary_vector", "sparse_float_vector",
              "dense_vector_sequence"):
        ns[k] = getattr(feeder, k)
    ns.update(
        settings=settings, outputs=outputs, get_config_arg=get_config_arg,
        define_py_data_sources2=define_py_data_sources2,
        MomentumOptimizer=MomentumOptimizer, AdamOptimizer=AdamOptimizer,
        AdamaxOptimizer=AdamaxOptimizer, AdaGradOptimizer=AdaGradOptimizer,
        AdaDeltaOptimizer=AdaDeltaOptimizer,
        RMSPropOptimizer=RMSPropOptimizer,
        DecayedAdaGradOptimizer=DecayedAdaGradOptimizer,
        L2Regularization=L2Regularization, L1Regularization=L1Regularization,
    )
    return ns


def parse_config(config_path: str, config_args: str = ""):
    """Execute a config file → (ModelConfig, OptimizationConfig,
    DataSources).  The reference embeds CPython to do this
    (``TrainerConfigHelper`` → ``parse_config``); here it's just exec."""
    from ..compat import install as _install_compat
    _install_compat()   # 'from paddle.trainer_config_helpers import *'
    _state.reset()
    _state.config_args = parse_config_args(config_args)
    with dsl.config_scope():
        ns = config_namespace()
        ns["__file__"] = os.path.abspath(config_path)
        sys.path.insert(0, os.path.dirname(os.path.abspath(config_path)))
        try:
            with open(config_path) as f:
                code = compile(f.read(), config_path, "exec")
            exec(code, ns)
        finally:
            sys.path.pop(0)
        enforce(_state.outputs, f"config {config_path} calls no outputs()")
        model = dsl.topology(_state.outputs)
    return model, _state.opt, _state.data_sources

"""v1 ``recurrent_units`` helpers.

Reference: ``python/paddle/trainer/recurrent_units.py`` — config-parser-
level LSTM/GRU step builders usable inside recurrent groups, with
``para_prefix``-controlled parameter names so two units with the same
prefix share weights.  Bodies are re-expressed over this package's DSL
primitives (mixed projections + lstm_step/gru_step + memory); the
``*Naive`` variants, which the reference expands into per-gate mixed
layers purely as a CPU-kernel workaround, map to the same fused step —
on TPU the fused form IS the naive form's math (one XLA fusion).
"""

from __future__ import annotations

from typing import List, Optional

from . import dsl
from .dsl import (
    ParamAttr,
    StepInput,
    full_matrix_projection,
    identity_projection,
    memory,
    mixed,
    recurrent_group,
)

__all__ = [
    "LstmRecurrentUnit", "LstmRecurrentUnitNaive",
    "LstmRecurrentLayerGroup", "GatedRecurrentUnit",
    "GatedRecurrentUnitNaive", "GatedRecurrentLayerGroup",
]


def LstmRecurrentUnit(name: str, size: int, active_type: str,
                      state_active_type: str, gate_active_type: str,
                      inputs: List, para_prefix: Optional[str] = None,
                      error_clipping_threshold: float = 0,
                      out_memory=None):
    """One LSTM step (``recurrent_units.py:35``): gates = Σ inputs +
    W·h_prev (+ bias), fed with the previous cell state."""
    if para_prefix is None:
        para_prefix = name
    if out_memory is None:
        out_memory = memory(name=name, size=size)
    state_memory = memory(name=f"{name}.state", size=size)
    gates = mixed(
        list(inputs) + [full_matrix_projection(
            out_memory.out if hasattr(out_memory, "out") else out_memory,
            size=size * 4,
            param_attr=ParamAttr(name=para_prefix + "_input_recurrent.w"))],
        size=size * 4, name=f"{name}_input_recurrent",
        bias_attr=ParamAttr(name=para_prefix + "_input_recurrent.b",
                            initial_std=0),
        layer_attr=dsl.ExtraAttr(
            error_clipping_threshold=error_clipping_threshold))
    out = dsl.lstm_step_layer(
        gates, state_memory.out, size=size, name=name,
        act=active_type, gate_act=gate_active_type,
        state_act=state_active_type,
        bias_attr=ParamAttr(name=para_prefix + "_check.b"))
    # the reference exposes the cell state as a named layer
    # (GetOutputLayer '{name}_state', recurrent_units.py:72) so configs
    # can consume it by name
    dsl.get_output_layer(out, "state", name=f"{name}_state")
    return out


# the reference's Naive variant exists only to avoid the fused CUDA
# kernel on CPU; the math is identical
LstmRecurrentUnitNaive = LstmRecurrentUnit


def LstmRecurrentLayerGroup(name: str, size: int, active_type: str,
                            state_active_type: str, gate_active_type: str,
                            inputs: List,
                            para_prefix: Optional[str] = None,
                            error_clipping_threshold: float = 0,
                            seq_reversed: bool = False):
    """LSTM over a sequence as a recurrent group
    (``recurrent_units.py:159``); ``inputs`` are projections of the
    sequence layer."""
    transformed = mixed(list(inputs), size=size * 4,
                        name=f"{name}_transform_input", bias_attr=False)

    def step(ipt):
        return LstmRecurrentUnit(
            name=name, size=size, active_type=active_type,
            state_active_type=state_active_type,
            gate_active_type=gate_active_type,
            inputs=[identity_projection(ipt)], para_prefix=para_prefix,
            error_clipping_threshold=error_clipping_threshold)

    return recurrent_group(step, [StepInput(transformed)],
                           name=f"{name}_layer_group",
                           reverse=seq_reversed)


def GatedRecurrentUnit(name: str, size: int, active_type: str,
                       gate_active_type: str, inputs,
                       para_prefix: Optional[str] = None,
                       error_clipping_threshold: float = 0,
                       out_memory=None):
    """One GRU step (``recurrent_units.py:205``); ``inputs`` is either a
    3H-projected step layer (group use) or a list of projections."""
    if para_prefix is None:
        para_prefix = name
    if isinstance(inputs, dsl.LayerOutput):
        projected = inputs
    else:
        projected = mixed(list(inputs), size=size * 3,
                          name=f"{name}_transform_input", bias_attr=False,
                          layer_attr=dsl.ExtraAttr(
                              error_clipping_threshold=error_clipping_threshold))
    if out_memory is None:
        out_memory = memory(name=name, size=size)
    return dsl.gru_step_layer(
        projected,
        out_memory.out if hasattr(out_memory, "out") else out_memory,
        size=size, name=name, act=active_type,
        gate_act=gate_active_type,
        param_attr=ParamAttr(name=para_prefix + "_gate.w"),
        bias_attr=ParamAttr(name=para_prefix + "_gate.b"),
        layer_attr=dsl.ExtraAttr(
            error_clipping_threshold=error_clipping_threshold))


GatedRecurrentUnitNaive = GatedRecurrentUnit


def GatedRecurrentLayerGroup(name: str, size: int, active_type: str,
                             gate_active_type: str, inputs: List,
                             para_prefix: Optional[str] = None,
                             error_clipping_threshold: float = 0,
                             seq_reversed: bool = False):
    """GRU over a sequence as a recurrent group — equivalent to
    ``GatedRecurrentLayer`` (``recurrent_units.py:300``)."""
    transformed = mixed(list(inputs), size=size * 3,
                        name=f"{name}_transform_input", bias_attr=False)

    def step(ipt):
        return GatedRecurrentUnit(
            name=name, size=size, active_type=active_type,
            gate_active_type=gate_active_type, inputs=ipt,
            para_prefix=para_prefix,
            error_clipping_threshold=error_clipping_threshold)

    return recurrent_group(step, [StepInput(transformed)],
                           name=f"{name}_layer_group",
                           reverse=seq_reversed)

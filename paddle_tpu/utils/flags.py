"""Central runtime-flag registry.

Equivalent of the reference's gflags hub (``paddle/utils/Flags.cpp:18-84``):
one process-wide table of named knobs, settable from the CLI
(``--name=value``), the environment (``PADDLE_TPU_<NAME>``), or code.  The
reference defines 109 flags; we keep the ones that still mean something on
TPU (device selection is a mesh, not ``--gpu_id``) and add TPU-specific ones.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


@dataclass
class _FlagSpec:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


class FlagRegistry:
    def __init__(self) -> None:
        self._specs: Dict[str, _FlagSpec] = {}
        self._values: Dict[str, Any] = {}

    def define(self, name: str, default: Any, help: str = "") -> None:
        if name in self._specs:
            # a silent re-registration wins the table and erases the
            # first definition's default/help — always a collision bug
            # (two modules claiming one knob), never intentional
            raise ValueError(
                f"flag {name!r} is already registered "
                f"(default={self._specs[name].default!r}); duplicate "
                "registration would silently replace it")
        if isinstance(default, bool):
            parser: Callable[[str], Any] = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
        self._specs[name] = _FlagSpec(name, default, help, parser)
        env = os.environ.get("PADDLE_TPU_" + name.upper())
        self._values[name] = parser(env) if env is not None else default

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["_values"][name]
        except KeyError:
            raise AttributeError(f"unknown flag {name!r}") from None

    def set(self, name: str, value: Any) -> None:
        if name not in self._specs:
            raise KeyError(f"unknown flag {name!r}")
        self._values[name] = value

    def get(self, name: str) -> Any:
        return self._values[name]

    def parse_argv(self, argv: List[str]) -> List[str]:
        """Consume ``--name=value`` / ``--name value`` args; return the rest."""
        rest: List[str] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            if arg.startswith("--"):
                body = arg[2:]
                if "=" in body:
                    name, val = body.split("=", 1)
                else:
                    name = body
                    if (
                        name in self._specs
                        and not isinstance(self._specs[name].default, bool)
                        and i + 1 < len(argv)
                    ):
                        i += 1
                        val = argv[i]
                    else:
                        val = "true"
                name = name.replace("-", "_")
                if name in self._specs:
                    self._values[name] = self._specs[name].parser(val)
                else:
                    rest.append(arg)
            else:
                rest.append(arg)
            i += 1
        return rest

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)


FLAGS = FlagRegistry()

# Core knobs (reference: paddle/utils/Flags.cpp).
FLAGS.define("use_tpu", True, "run compute on the TPU backend (else CPU)")
FLAGS.define("trainer_count", 1, "data-parallel replicas (mesh 'data' axis size)")
FLAGS.define("trainer_id", 0, "index of this host in a multi-host job")
FLAGS.define("num_hosts", 1, "number of hosts in the job")
FLAGS.define("log_period", 100, "log every N batches")
FLAGS.define("test_period", 0, "test every N batches (0: per pass)")
FLAGS.define("show_parameter_stats_period", 0, "dump param stats every N batches")
FLAGS.define("checkgrad_eps", 1e-2, "finite-difference step for --job=checkgrad")
FLAGS.define("seed", 1, "global RNG seed (0: nondeterministic)")
FLAGS.define("dot_period", 1, "print a progress dot every N batches")
FLAGS.define("saving_period", 1, "checkpoint every N passes")
FLAGS.define("load_missing_parameter_strategy", "fail", "fail|rand|zero")
FLAGS.define("init_model_path", "", "checkpoint dir to warm-start from")
FLAGS.define("start_pass", 0, "first pass number (resume)")
FLAGS.define("save_dir", "./output", "checkpoint output dir")
FLAGS.define("config_args", "", "comma-sep k=v pairs visible to configs")
FLAGS.define("precision", "fp32",
             "end-to-end training precision policy: fp32 | bf16.  "
             "bf16 = mixed precision — fp32 master weights cast to "
             "bfloat16 compute at the train-step boundary, fp32 "
             "optimizer state and gradient accumulation, dynamic loss "
             "scaling with skipped-step semantics on non-finite grads "
             "(trainer/trainer.py + optimizer/loss_scale.py), and the "
             "op-level compute policy (core/dtypes.py) forced to bf16 "
             "regardless of --use_bf16.  fp32 (the default) leaves the "
             "legacy --use_bf16/--bf16_activations resolution untouched "
             "byte-for-byte")
FLAGS.define("loss_scale_init", 32768.0,
             "initial dynamic loss scale under --precision=bf16 "
             "(2^15; grows 2x every --loss_scale_growth_interval "
             "overflow-free steps, halves — floor 1.0 — and skips the "
             "step on inf/nan gradients)")
FLAGS.define("loss_scale_growth_interval", 2000,
             "overflow-free steps between dynamic loss-scale doublings")
FLAGS.define("use_bf16", True, "run matmul/conv compute in bfloat16 on TPU")
FLAGS.define("bf16_activations", False,
             "store layer activations in bfloat16 (halves activation HBM "
             "traffic; params/losses stay fp32)")
FLAGS.define("conv_bn_fuse", True,
             "fuse linear-conv→batch_norm pairs through the Pallas "
             "backward-data kernel (ops/pallas_conv.py); off = the "
             "plain composition, for A/B traffic measurement")
FLAGS.define("conv_bn_fuse_fwd", True,
             "fuse batch_norm(+relu)→conv pairs on the FORWARD side: "
             "the BN's per-channel affine + ReLU stream through the "
             "consuming conv's input pipeline (Pallas 3x3 kernel / 1x1 "
             "GEMM prologue, ops/pallas_conv.py + ops/nn_ops.py) "
             "instead of materializing the normalized activation in "
             "HBM; off = the exact round-6 lowering, for A/B traffic "
             "measurement")
FLAGS.define("flash_kernel", True,
             "run attention through the Pallas flash kernel "
             "(ops/pallas_attention.py); off = the exact dense XLA "
             "attention composition, for A/B traffic measurement")
FLAGS.define("flash_block_sparse", True,
             "block-sparse flash attention: compact the KV grid per "
             "q-block so blocks fully above the causal diagonal or past "
             "a row's scalar-prefetched length are neither DMA'd nor "
             "visited (fwd + both backward kernels); off = the legacy "
             "full (B*H, q_blocks, k_blocks) grid that fetched every "
             "block and only skipped the compute, for one-flag revert / "
             "A/B traffic measurement")
FLAGS.define("attention_packing", True,
             "sequence packing for attention layers with packed=True: "
             "mixed-length rows share one [total_tokens] segment-id "
             "layout where padding and cross-sequence blocks do zero "
             "work; off = the layer ignores the packed attr and runs "
             "the exact padded per-row lowering")
FLAGS.define("fused_rnn_hblock", True,
             "enable the hidden-blocked fused RNN tier (ops/"
             "pallas_lstm.py, ops/pallas_gru.py): 512 < H shapes run "
             "the whole-sequence Pallas kernels with w_hh streamed as "
             "[H, gates*128] column blocks instead of falling back to "
             "lax.scan; off = the round-7 H<=512 gate, for one-flag "
             "revert / A/B measurement")
FLAGS.define("master_retry_max", 5,
             "reconnect attempts per master RPC: on connection loss the "
             "TCP MasterClient re-dials with exponential backoff + jitter "
             "and replays the request up to this many times; 0 restores "
             "the legacy fail-fast behavior (first drop raises "
             "PaddleTpuError)")
FLAGS.define("ckpt_keep", 5,
             "checkpoint retention: keep the newest N pass-* dirs after "
             "each save and delete older ones; 0 disables the sweep "
             "(keep everything, the legacy behavior)")
FLAGS.define("ckpt_verify", True,
             "verify per-file SHA-256 digests from the checkpoint "
             "manifest on load, and make resume scan backward past "
             "corrupt checkpoints (quarantined as .corrupt-*); off = "
             "the legacy blind latest-checkpoint load")
FLAGS.define("log_level", "",
             "framework log level: debug|info|warning|error|fatal "
             "(empty = PADDLE_TPU_LOG_LEVEL env var, else INFO); "
             "applied by the entry points after flag parsing via "
             "utils.logger.set_log_level")
FLAGS.define("metrics_jsonl", "",
             "telemetry JSONL sink path: when set, a background "
             "reporter appends one self-describing snapshot line "
             "(typed metrics + StatSet timer table) every "
             "--metrics_interval_s seconds (paddle_tpu/observe/); "
             "empty = no sink, instrumentation stays near-zero cost "
             "and the trainer skips its step-fencing time split")
FLAGS.define("metrics_interval_s", 10.0,
             "flush interval for the --metrics_jsonl reporter")
FLAGS.define("trace_jsonl", "",
             "span-trace sink path (paddle_tpu/observe/trace.py): when "
             "set, every span (trainer step phases, pipeline workers, "
             "checkpoint ops, master RPCs incl. the server-side echo, "
             "serving requests) streams to this file as Chrome "
             "trace-event JSON — load it directly in Perfetto / "
             "chrome://tracing; empty = no stream, span() is a shared "
             "no-op and the hot path pays <50 us/step")
FLAGS.define("trace_ring_size", 4096,
             "flight-recorder capacity: the last N spans of a live run "
             "kept in a bounded in-memory ring, served by the "
             "--metrics_port /trace endpoint and the SIGUSR2 debug "
             "dump")
FLAGS.define("metrics_port", 0,
             "live observability endpoint (paddle_tpu/observe/http.py):"
             " serve GET /metrics (Prometheus text), /healthz "
             "(liveness JSON) and /trace (flight-recorder dump as "
             "Chrome trace-event JSON) on this loopback port; 0 (the "
             "default) starts no server thread")
FLAGS.define("debug_dump_signal", False,
             "install a SIGUSR2 handler that dumps Prometheus text + "
             "the flight-recorder trace of the LIVE run to timestamped "
             "files under --debug_dump_dir (kill -USR2 <pid>) — "
             "post-mortem for wedged runs without a debugger")
FLAGS.define("debug_dump_dir", "/tmp",
             "output directory for --debug_dump_signal dumps")
FLAGS.define("metrics_bind", "",
             "bind address for the --metrics_port observability "
             "endpoint (empty = 127.0.0.1).  Non-loopback is an "
             "EXPLICIT opt-in for same-host-only/container scraping "
             "and logs a loud structured warning: the endpoint is "
             "diagnostics, NOT an external API — no auth, no TLS, "
             "never expose it past a trusted network boundary")
FLAGS.define("fleet_addr", "",
             "fleet aggregator address (host:port, observe/fleet.py): "
             "when set, this process pushes one self-describing "
             "telemetry frame — metrics snapshot, recent "
             "flight-recorder spans, health digest — every "
             "--metrics_interval_s seconds from the reporter thread.  "
             "A dead/version-skewed aggregator degrades the push sink "
             "(warn-once, exponential backoff + jitter) and never "
             "touches the training loop; empty (default) = no push "
             "client, no reporter thread, zero new work")
FLAGS.define("fleet_port", 0,
             "host the fleet aggregator in THIS process on this port "
             "(observe/fleet.py): serves GET /fleet/metrics (merged "
             "Prometheus with role/pid/node labels), /fleet/healthz "
             "(cluster rollup with staleness detection), /fleet/trace "
             "(all processes' spans merged into one Chrome trace-event "
             "timeline) and /fleet/topology, plus POST /fleet/push "
             "frame intake; 0 (default) hosts nothing")
FLAGS.define("fleet_bind", "",
             "bind address for the --fleet_port aggregator (empty = "
             "127.0.0.1).  Non-loopback is an explicit opt-in and "
             "warns loudly — same not-an-external-API rule as "
             "--metrics_bind")
FLAGS.define("fleet_id", "",
             "logical fleet identity of this process (e.g. trainer-0):"
             " the key the aggregator's staleness tracking uses, so a "
             "restarted process with the same id supersedes its dead "
             "entry and the /fleet/healthz rollup recovers.  Empty = "
             "derived role@node:pid (a restart then registers as a "
             "NEW process and the old entry stays missing)")
FLAGS.define("fleet_role", "trainer",
             "fleet role this process registers as (trainer | "
             "master-client | serving | bench by convention); the "
             "elastic trainer, serving loader and bench override this "
             "programmatically")
FLAGS.define("fleet_stale_factor", 3.0,
             "staleness multiplier for the /fleet/healthz rollup: a "
             "process that has not pushed for this many multiples of "
             "its own advertised interval is reported 'missing' "
             "(a restarted process pushing under the same --fleet_id "
             "flips it back to ok)")
FLAGS.define("fleet_ring_size", 4096,
             "per-process span retention in the hosted aggregator: "
             "the newest N spans of each registered process kept for "
             "the merged /fleet/trace timeline")
FLAGS.define("fleet_push_timeout_s", 2.0,
             "socket timeout for one fleet push POST; a slow or dead "
             "aggregator costs the reporter thread at most this long "
             "before the degrade/backoff path takes over")
FLAGS.define("sigterm_flush", True,
             "install a chaining SIGTERM hook when any telemetry "
             "surface is configured (observe/shutdown.py): the final "
             "metrics interval is flushed, a last going-down fleet "
             "frame is pushed, and the --trace_jsonl array is "
             "finalized before the previous handler (or the default "
             "die-by-signal disposition) runs; off = the legacy "
             "atexit-only flush, which a SIGTERM-then-SIGKILL "
             "orchestrator window can lose")
FLAGS.define("health_interval", 0,
             "training-health telemetry (observe/health.py): every N "
             "steps drain the on-device per-layer accumulators — "
             "gradient/parameter norms, update ratios ||dw||/||w||, "
             "non-finite localization — into observe gauges, /metrics "
             "and the host-side detectors (loss spike/plateau, "
             "dead/exploding layers).  The aux path is fused into the "
             "jitted train step and keyed to the same layer names as "
             "the roofline attribution; the drain's small D2H fetch is "
             "the only fence, amortized over N steps.  0 (default) = "
             "off: the step is built without any aux outputs, "
             "byte-for-byte the legacy program")
FLAGS.define("health_window", 32,
             "rolling window (in drains) for the loss median/MAD "
             "robust statistics behind the spike/plateau detectors")
FLAGS.define("health_spike_mad", 8.0,
             "loss-spike threshold: alert when loss exceeds the "
             "rolling median by this many robust sigmas (1.4826*MAD)")
FLAGS.define("health_plateau_rtol", 1e-4,
             "loss-plateau threshold: alert when the loss window's "
             "full range stays within this relative tolerance of the "
             "median for a whole window")
FLAGS.define("health_dead_ratio", 1e-10,
             "dead-layer threshold: alert when a layer's update ratio "
             "||dw||/||w|| stays at or below this for "
             "--health_patience consecutive drains")
FLAGS.define("health_explode_ratio", 0.5,
             "exploding-layer threshold: alert when a layer's update "
             "ratio exceeds this for --health_patience consecutive "
             "drains")
FLAGS.define("health_patience", 2,
             "consecutive drains a dead/exploding condition must "
             "persist before its alert fires")
FLAGS.define("roofline_dump", "",
             "write the attributed per-region roofline/cost report of "
             "the compiled train step (observe/costmodel.py: FLOPs / "
             "HBM bytes / compute-vs-memory verdict per network layer, "
             "keyed through the layer named_scopes) to this JSON path "
             "at the end of the first training pass; empty = off")
FLAGS.define("roofline_peak_flops", 0.0,
             "override the detected peak FLOP/s for roofline/MFU "
             "verdicts (0 = auto-detect from the device kind)")
FLAGS.define("roofline_peak_gbps", 0.0,
             "override the detected HBM bandwidth (GB/s) for roofline "
             "verdicts (0 = auto-detect from the device kind)")
FLAGS.define("serve_port", 0,
             "serving HTTP endpoint (serving/server.py): POST "
             "/v1/generate with {'prompt': [token ids], "
             "'max_new_tokens': n} blocks until generation completes "
             "and returns the tokens; GET /healthz reports queue depth "
             "and page-pool occupancy.  0 picks a free port when the "
             "server is started with serve_http=True; the loopback/"
             "trusted-bind rules of --metrics_bind apply via "
             "--serve_bind")
FLAGS.define("serve_bind", "",
             "bind host for the serving endpoint; empty = loopback "
             "only (same trust contract as --metrics_bind: 0.0.0.0 "
             "requires PADDLE_TPU_TRUST_NETWORK=1)")
FLAGS.define("serve_max_batch", 8,
             "continuous-batching decode width (serving/server.py): "
             "at most this many requests share one "
             "paged_decode_attention launch; new admissions join "
             "between decode steps up to this cap")
FLAGS.define("serve_continuous", True,
             "continuous batching in the inference server: requests "
             "join the in-flight decode batch between steps and "
             "prefill is packed across admissions "
             "(flash_attention_packed).  false = the kill switch — "
             "sequential single-request serving (admit one, prefill "
             "alone, decode to completion, then the next), "
             "byte-for-byte the same generated tokens")
FLAGS.define("kv_pool_pages", 128,
             "physical pages in the shared serving KV pool "
             "(serving/pagepool.py); each request holds "
             "ceil(context/--kv_page_size) pages via its page table "
             "and returns them on completion for recycling")
FLAGS.define("kv_page_size", 16,
             "tokens per KV page (the paged_decode_attention page "
             "axis); pool capacity in tokens is kv_pool_pages x "
             "kv_page_size")
FLAGS.define("serve_slo_ms", 0.0,
             "optional p99 TTFT SLO in milliseconds: when > 0 the "
             "server's /healthz and the bench serving lane report "
             "ttft_p99_ms and slo_met from the serve_ttft_seconds "
             "WINDOWED reservoir p99 (last ~60s), so a recovered "
             "server stops advertising a stale lifetime p99; 0 "
             "(default) leaves /healthz byte-identical")
FLAGS.define("slo", "",
             "declarative serving SLOs evaluated continuously on the "
             "reporter thread (observe/slo.py): objectives joined "
             "with ',' or ';' in metric:statOPthreshold:window "
             "grammar, e.g. 'serve_ttft_seconds:p99<0.5:60s' (stat "
             "pNN windowed quantile or rate events/s, OP < or >, "
             "window Ns/Nm).  Each yields ok/breach plus fast+slow "
             "multi-window burn rates on slo_status/slo_burn_rate "
             "gauges, /slo, /healthz, and the fleet plane.  Empty "
             "(default) = no engine, every surface byte-identical")
FLAGS.define("rollout", True,
             "the zero-downtime train->serve pipeline "
             "(serving/rollout.py): checkpoint watcher + atomic "
             "hot-swap of exported artifacts into the live "
             "InferenceServer between decode steps, with automatic "
             "rollback on a failed verify/load/probe.  false is the "
             "kill switch: request_swap refuses, POST /v1/swap is an "
             "unknown path, and /healthz carries exactly the PR-15 "
             "body — the server is byte-identical to pre-rollout "
             "behavior")
FLAGS.define("rollout_poll_s", 5.0,
             "checkpoint-watcher poll interval (serving/rollout.py): "
             "how often the watcher rescans --save_dir for a new "
             "digest-verified retained checkpoint to export")
FLAGS.define("rollout_inflight", "drain",
             "what happens to in-flight sequences at the hot-swap "
             "pointer flip: 'drain' finishes them on the OLD model "
             "before flipping (admissions pause, zero recompute); "
             "'reprefill' flips immediately and restarts their "
             "generation from the prompt on the NEW model (tokens "
             "generated so far are discarded — a response always "
             "comes from exactly one model under BOTH policies)")
FLAGS.define("rollout_quantize", "int8",
             "serving-artifact quantization the watcher's export uses "
             "(int8 per-channel weights-only, or 'none' for raw fp32 "
             "— same schemes as export_decoder)")
FLAGS.define("rollout_export_dir", "",
             "directory the checkpoint watcher writes serving "
             "artifacts into (model-<digest> dirs, atomic tmp+rename; "
             "empty = <save_dir>/export)")
FLAGS.define("rollout_canary", False,
             "canary bake policy for rollouts (serving/rollout.py): "
             "the RollingCoordinator swaps ONE replica first and "
             "bakes it for --rollout_bake_s, comparing the canary's "
             "windowed p99 TTFT and error rate against the pooled "
             "baseline replicas via the fleet aggregator; on breach "
             "the canary is auto-rolled-back and the rollout HALTS "
             "(reason on /healthz, rollout_canary_total{result}), "
             "otherwise the remaining replicas swap.  Single-server "
             "swaps get the same bake-then-commit window.  false "
             "(default) = PR-18 behavior, byte-identical")
FLAGS.define("rollout_bake_s", 0.0,
             "canary bake duration in seconds (--rollout_canary): "
             "how long a freshly swapped canary serves traffic "
             "before its windowed p99 TTFT / error rate is compared "
             "against the baseline pool and the rollout commits or "
             "rolls back; 0 with --rollout_canary still does the "
             "one-replica-first walk but skips the bake wait")
FLAGS.define("rollout_canary_factor", 2.0,
             "canary breach threshold (--rollout_canary): the bake "
             "fails when canary windowed p99 TTFT > factor x pooled "
             "baseline p99, or canary error rate > factor x baseline "
             "error rate (any canary errors breach when the baseline "
             "pool is error-free)")
FLAGS.define("ckpt_export_lease_s", 600.0,
             "stale-mtime expiry for .exporting-<pid> checkpoint pin "
             "markers (trainer/checkpoint.py): the retention sweep "
             "honors a fresher marker (never reaps a checkpoint "
             "mid-export) and ignores older ones — a SIGKILLed "
             "exporter cannot pin a checkpoint forever")
FLAGS.define("sparse_grads", True,
             "sparse gradient exchange for ParamAttr(sparse_update="
             "True) embedding tables (parallel/sparse.py): the jitted "
             "train step carries each table's gradient as a fixed-"
             "capacity (rows, values) pair — batch ids deduped once, "
             "row cotangents segment-summed by autodiff — and applies "
             "it as a shard-local scatter-add through "
             "Optimizer.apply_rows, so the dense [V, D] gradient is "
             "never materialized or all-reduced.  false is the kill "
             "switch: the legacy dense gradient + lazy row masking, "
             "byte-for-byte")
FLAGS.define("sparse_grad_rows", 0,
             "fixed row capacity K of the sparse gradient exchange "
             "per table (the SelectedRows prefetch-buffer budget): "
             "rows/values ship as [K]/[K, D] whatever the batch "
             "touches.  0 (default) = auto — the batch's total id "
             "count, which can never overflow.  A manual K below the "
             "unique-id count of a batch drops the LARGEST ids from "
             "the update (jnp.unique keeps the smallest K) — size it "
             ">= the worst-case unique ids per batch")
FLAGS.define("embedding_kernel", True,
             "gather embedding rows through the Pallas scalar-prefetch "
             "kernel (ops/pallas_embedding.py): the deduped row-index "
             "table rides the grid spec's scalar prefetch so only "
             "touched rows are DMA'd HBM->VMEM; false = the plain XLA "
             "take gather, byte-for-byte, for one-flag revert / A/B "
             "traffic measurement")
FLAGS.define("embedding_kernel_interpret", False,
             "run the Pallas embedding gather in interpret mode on "
             "non-TPU backends (numerics-contract tests at tiny "
             "shapes).  Off (default), CPU/GPU dispatch falls back to "
             "the XLA gather with reason no_tpu — interpret mode "
             "emulates the grid one step at a time and costs seconds "
             "per call at production row counts")
FLAGS.define("mesh_shape", "", "mesh as 'data=8' or 'data=4,model=2' (auto if empty)")
FLAGS.define("fsdp", False,
             "shard parameters AND optimizer slots over the 'data' "
             "mesh axis (FSDP): per-chip params/opt_state HBM drops "
             "by the data-axis extent while XLA turns the gradient "
             "all-reduce into an all-gather/reduce-scatter pair; "
             "placement comes from the trainer's fsdp_rules table "
             "(parallel/rule_tables.py for zoo models) else the "
             "largest-divisible-dim heuristic.  --fsdp=false is the "
             "kill switch: the replicated path, byte-for-byte")
FLAGS.define("fsdp_min_size", 1024,
             "parameters below this many elements stay replicated "
             "under the FSDP auto heuristic (norm gains, biases): "
             "sharding KiB-scale tensors fragments collectives for "
             "no memory win; rule-table entries are exempt — a "
             "committed table says exactly what it means")
FLAGS.define("prefetch_depth", 2,
             "async input pipeline depth (data/pipeline.py): max "
             "batches in flight between the reader and the train step "
             "— reader IO, DataFeeder.convert, and the host->device "
             "transfer run on worker threads and overlap the running "
             "step; 0 restores the fully synchronous loop "
             "(read -> convert -> step, byte-for-byte)")
FLAGS.define("reader_workers", 2,
             "reader/convert worker threads per async input pipeline "
             "(clamped to prefetch_depth; reading from the source is "
             "serialized, convert+transfer parallelize)")
FLAGS.define("parallel_nn", False, "per-layer device placement (sharding annotations)")
FLAGS.define("enable_timers", True, "collect named wall timers (Stat.h equivalent)")
FLAGS.define("port", 7164, "data-task coordinator service port")
FLAGS.define("ports_num", 1, "kept for config compatibility; unused on TPU")
FLAGS.define("num_gradient_servers", 1, "kept for config compatibility")
FLAGS.define("rdma_tcp", "tcp", "kept for config compatibility; unused on TPU")

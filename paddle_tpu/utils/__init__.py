from .error import ConfigError, PaddleTpuError, ShapeError, enforce, enforce_eq, layer_stack
from .flags import FLAGS
from .logger import get_logger, reset_warn_once, set_log_level, warn_once
from .registry import Registry
from .stat import StatSet, global_stat

__all__ = [
    "ConfigError",
    "PaddleTpuError",
    "ShapeError",
    "enforce",
    "enforce_eq",
    "layer_stack",
    "FLAGS",
    "get_logger",
    "set_log_level",
    "warn_once",
    "reset_warn_once",
    "Registry",
    "StatSet",
    "global_stat",
]

"""Logging setup (glog-equivalent: ``paddle/utils/Logging.h``)."""

from __future__ import annotations

import logging
import sys

_FMT = "%(levelname).1s %(asctime)s.%(msecs)03d %(name)s] %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_root = logging.getLogger("paddle_tpu")
if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(_FMT, _DATEFMT))
    _root.addHandler(h)
    _root.setLevel(logging.INFO)
    _root.propagate = False


def get_logger(name: str = "") -> logging.Logger:
    return _root.getChild(name) if name else _root

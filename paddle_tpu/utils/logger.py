"""Logging setup (glog-equivalent: ``paddle/utils/Logging.h``).

Level selection (first match wins):

1. ``set_log_level("debug")`` in code,
2. ``--log_level`` CLI flag (applied by the entry points after flag
   parsing — :mod:`paddle_tpu.cli`, ``bench.py``),
3. ``PADDLE_TPU_LOG_LEVEL`` environment variable at import,
4. INFO.

:func:`warn_once` is the process-wide one-time structured warning
(keyed): dispatch-tier fallbacks and similar per-shape diagnostics log
each distinct situation exactly once per process instead of flooding the
training loop (the hand-rolled ``_fallback_warned`` sets this replaces).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Optional, Set, Union

from ..analysis.lockorder import named_lock

_FMT = "%(levelname).1s %(asctime)s.%(msecs)03d %(name)s] %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "warn": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "critical": logging.CRITICAL,
}


def _parse_level(level: Union[str, int]) -> int:
    if isinstance(level, int):
        return level
    try:
        return _LEVELS[level.strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level {level!r} "
            f"(choose from {sorted(set(_LEVELS))})") from None


_root = logging.getLogger("paddle_tpu")
if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(_FMT, _DATEFMT))
    _root.addHandler(h)
    # a typo'd fleet-wide env var must not make the package
    # unimportable: degrade to INFO with a warning (the explicit
    # set_log_level / --log_level paths stay strict)
    try:
        _root.setLevel(_parse_level(
            os.environ.get("PADDLE_TPU_LOG_LEVEL") or "info"))
    except ValueError as e:
        _root.setLevel(logging.INFO)
        _root.warning("PADDLE_TPU_LOG_LEVEL ignored (%s); using INFO", e)
    _root.propagate = False


def get_logger(name: str = "") -> logging.Logger:
    return _root.getChild(name) if name else _root


def set_log_level(level: Union[str, int]) -> None:
    """Set the framework-wide level ("debug"|"info"|"warning"|"error"|
    "fatal", or a :mod:`logging` constant)."""
    _root.setLevel(_parse_level(level))


_warned: Set[str] = set()
_warned_lock = named_lock("logger.warn_once")


def warn_once(key: str, msg: str, *args,
              logger: Optional[logging.Logger] = None) -> bool:
    """Log ``msg % args`` as a warning the FIRST time ``key`` is seen in
    this process; later calls are no-ops.  Returns True iff it logged.

    Key per distinct situation (e.g. ``f"fused_lstm_fallback:{B}x{H}"``)
    so a hot loop reports each shape once, not once per step.
    """
    with _warned_lock:
        if key in _warned:
            return False
        _warned.add(key)
    (logger or _root).warning(msg, *args)
    return True


def reset_warn_once(key: Optional[str] = None) -> None:
    """Forget every warn_once key (tests) — or, with ``key``, re-arm
    just that one: a sink that RECOVERED from degradation wants its
    failure warning to fire again on the next incident, not stay
    silenced for the process lifetime."""
    with _warned_lock:
        if key is None:
            _warned.clear()
        else:
            _warned.discard(key)

"""Name → factory registries.

TPU-native equivalent of the reference's ``ClassRegistrar``
(``paddle/utils/ClassRegistrar.h``) and the various ``REGISTER_*`` macro
families (``REGISTER_LAYER``, ``REGISTER_OP``, activation registry, evaluator
registry).  One generic registry class is enough in Python; each subsystem
instantiates its own.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Generic, Iterable, List, Optional, TypeVar

from .error import PaddleTpuError

T = TypeVar("T")


class Registry(Generic[T]):
    """A named collection of factories.

    Unlike the C++ original, registration is usually done with the
    :meth:`register` decorator at module import time.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}

    def register(self, name: str, *aliases: str) -> Callable[[T], T]:
        def deco(obj: T) -> T:
            if name in self._entries:
                raise PaddleTpuError(
                    f"duplicate {self.kind} registration: {name!r}"
                )
            self._entries[name] = obj
            for a in aliases:
                self._aliases[a] = name
            return obj

        return deco

    def register_value(self, name: str, obj: T, *aliases: str) -> T:
        self.register(name, *aliases)(obj)
        return obj

    def contains(self, name: str) -> bool:
        return name in self._entries or name in self._aliases

    __contains__ = contains

    def get(self, name: str) -> T:
        key = self._aliases.get(name, name)
        try:
            return self._entries[key]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise PaddleTpuError(
                f"unknown {self.kind} {name!r}; registered: {known}"
            ) from None

    def names(self) -> List[str]:
        return sorted(self._entries)

    def items(self) -> Iterable[tuple]:
        return self._entries.items()

    def create(self, name: str, *args: Any, **kwargs: Any) -> Any:
        """Instantiate a registered factory/class."""
        return self.get(name)(*args, **kwargs)

"""Import PyTorch-trained weights — the ``torch2paddle`` answer.

Reference: ``python/paddle/utils/torch2paddle.py`` converted serialized
Torch7 ``.t7`` models into reference ``Parameter`` files.  The modern
equivalent: take a ``torch.nn`` state_dict (torch-cpu is available in
this stack) and emit either our parameter dict or a reference-layout
model dir (``trainer/interop.py`` raw buffers), with the layout
conversions the two frameworks disagree on handled here:

- ``nn.Linear.weight`` is ``[out, in]`` (y = x Wᵀ + b); our fc weights
  are ``[in, out]`` → transposed.
- ``nn.Conv2d.weight`` is ``[out, in, kh, kw]`` (NCHW/OIHW); our convs
  are NHWC/HWIO → permuted to ``[kh, kw, in, out]``.
- biases/norm scales carry over unchanged.  NOTE auto-detection treats
  EVERY 2-D ``*.weight`` as a Linear weight — for ``nn.Embedding``
  (also 2-D, but already ``[vocab, dim]``) pass
  ``kinds={"emb.weight": "raw"}``.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import numpy as np


def convert_tensor(name: str, value, kind: Optional[str] = None
                   ) -> np.ndarray:
    """Convert one state_dict tensor to our layout.

    ``kind`` overrides auto-detection: "linear_weight", "conv_weight",
    or "raw".
    """
    arr = np.asarray(value.detach().cpu().numpy()
                     if hasattr(value, "detach") else value)
    if kind is None:
        if name.endswith(".weight") and arr.ndim == 2:
            kind = "linear_weight"
        elif name.endswith(".weight") and arr.ndim == 4:
            kind = "conv_weight"
        else:
            kind = "raw"
    if kind == "linear_weight":
        return np.ascontiguousarray(arr.T)          # [out,in] -> [in,out]
    if kind == "conv_weight":
        return np.ascontiguousarray(
            arr.transpose(2, 3, 1, 0))              # OIHW -> HWIO
    return arr


def torch_state_dict_to_params(
        state_dict: Mapping[str, Any],
        name_map: Mapping[str, str],
        kinds: Optional[Mapping[str, str]] = None
        ) -> Dict[str, np.ndarray]:
    """Map a torch state_dict into our parameter dict.

    ``name_map``: {torch_name: our_param_name}; entries absent from the
    state_dict raise.  ``kinds`` optionally overrides per-torch-name
    layout conversion.
    """
    out: Dict[str, np.ndarray] = {}
    for tname, pname in name_map.items():
        if tname not in state_dict:
            raise KeyError(f"torch state_dict lacks {tname!r} "
                           f"(has {sorted(state_dict)[:8]}...)")
        out[pname] = convert_tensor(
            tname, state_dict[tname],
            (kinds or {}).get(tname))
    return out


def import_torch_model(module_or_state_dict,
                       name_map: Mapping[str, str],
                       save_dir: Optional[str] = None,
                       kinds: Optional[Mapping[str, str]] = None
                       ) -> Dict[str, np.ndarray]:
    """state_dict (or nn.Module) → our params; optionally also write a
    reference-layout model dir (``Parameter::save`` raw buffers) so the
    result feeds ``merge_model`` / ``--init_model_path`` directly."""
    sd = module_or_state_dict
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    params = torch_state_dict_to_params(sd, name_map, kinds)
    if save_dir:
        from ..trainer.interop import save_reference_model_dir
        save_reference_model_dir(save_dir, params)
    return params

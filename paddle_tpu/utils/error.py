"""Error types and enforce helpers.

Equivalent of the reference's ``paddle/utils/Error.h`` and the next-gen
``PADDLE_ENFORCE*`` macros (``paddle/platform/enforce.h``).  Python exceptions
replace status codes; ``enforce`` gives the same "check with formatted
message" ergonomics and ``layer_stack`` mirrors ``CustomStackTrace`` —
the per-thread stack of layer names dumped when a forward/backward fails
(``paddle/utils/CustomStackTrace.h:51``).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator, List


class PaddleTpuError(RuntimeError):
    """Base error for the framework."""


class ShapeError(PaddleTpuError):
    """Tensor shape/rank mismatch."""


class ConfigError(PaddleTpuError):
    """Bad model/trainer configuration."""


def enforce(cond: Any, msg: str = "", *args: Any) -> None:
    if not cond:
        text = msg % args if args else msg
        stack = layer_stack.current()
        if stack:
            text += f"\n  while executing layer stack: {' -> '.join(stack)}"
        raise PaddleTpuError(text)


def enforce_eq(a: Any, b: Any, msg: str = "") -> None:
    enforce(a == b, f"{msg} (got {a!r} != {b!r})" if msg else f"{a!r} != {b!r}")


class _LayerStack(threading.local):
    """Per-thread stack of layer names for error context."""

    def __init__(self) -> None:
        self.stack: List[str] = []

    def current(self) -> List[str]:
        return list(self.stack)

    @contextlib.contextmanager
    def guard(self, name: str) -> Iterator[None]:
        self.stack.append(name)
        try:
            yield
        except Exception as e:
            if not getattr(e, "_pt_stack_noted", False):
                e._pt_stack_noted = True  # type: ignore[attr-defined]
                e.args = (
                    (e.args[0] if e.args else "")
                    + f"\n  [layer stack: {' -> '.join(self.stack)}]",
                ) + tuple(e.args[1:])
            raise
        finally:
            self.stack.pop()


layer_stack = _LayerStack()

"""Profiling & tracing (SURVEY §5 aux subsystems).

The reference aggregates RAII wall timers per named section
(``REGISTER_TIMER``/``StatSet``, ``paddle/utils/Stat.h:63-242``) and opens
nvprof windows via ``hl_profiler_start/end``
(``hl_cuda_device.cc:675-677``).  TPU equivalents:

- named wall timers: :mod:`paddle_tpu.utils.stat` (already per-section);
- device traces: :func:`trace` wraps ``jax.profiler`` so a window of
  steps lands in an xprof/TensorBoard trace directory;
- FP-fault trapping (``feenableexcept`` in ``TrainerMain.cpp:49``):
  :func:`enable_fp_exceptions` flips ``jax_debug_nans``/``jax_debug_infs``
  so the first NaN/Inf inside a jitted computation raises at the op.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

import jax

from ..analysis.lockorder import named_lock
from .logger import get_logger, warn_once

log = get_logger("profiler")

# open-window bookkeeping: jax.profiler.start_trace is NOT re-entrant
# (a nested start raises), so only the outermost trace() opens/closes
# the window and inner uses are warn-once no-ops.  The depth doubles as
# the "is an xprof window open" signal observe.trace keys on to wrap
# spans in TraceAnnotations (host-span <-> XLA-op correlation).
_depth_lock = named_lock("profiler.depth")
_trace_depth = 0


def trace_active() -> bool:
    """True while an xprof window opened by :func:`trace` is live."""
    return _trace_depth > 0


@contextlib.contextmanager
def trace(logdir: str = "/tmp/paddle_tpu_trace") -> Iterator[None]:
    """``with profiler.trace(dir): ...`` — xprof window (nvprof-window
    equivalent); view with TensorBoard's profile plugin.

    Re-entrancy-safe: a nested ``trace`` (e.g. bench's ``--profile``
    around a code path that opens its own window) warns once and rides
    the already-open window instead of raising.  Windows are
    tick-counted (``profiler_trace_windows_total``) so a run's artifact
    records how many xprof dumps it produced."""
    global _trace_depth
    with _depth_lock:
        nested = _trace_depth > 0
        _trace_depth += 1
    try:
        if nested:
            warn_once("profiler_trace_nested",
                      "nested profiler.trace(%r): jax.profiler windows "
                      "don't nest — riding the already-open window "
                      "(reported once)", logdir, logger=log)
            yield
            return
        from .. import observe

        jax.profiler.start_trace(logdir)
        observe.counter("profiler_trace_windows_total",
                        "xprof/jax.profiler trace windows opened"
                        ).inc()
        log.info("profiler trace started → %s", logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", logdir)
    finally:
        with _depth_lock:
            _trace_depth -= 1


def annotate(name: str):
    """Named sub-trace region (``REGISTER_TIMER_INFO`` equivalent inside
    traced code)."""
    return jax.profiler.TraceAnnotation(name)


def enable_fp_exceptions(enable: bool = True) -> None:
    """Trap NaN/Inf produced by jitted computations — the
    ``feenableexcept(FE_INVALID|FE_DIVBYZERO|FE_OVERFLOW)`` equivalent."""
    jax.config.update("jax_debug_nans", enable)
    jax.config.update("jax_debug_infs", enable)


def parameter_stats(params) -> str:
    """Per-parameter |value| stats line (``--show_parameter_stats_period``,
    ``TrainerInternal.cpp:99-111``)."""
    import numpy as np

    # ONE device_get over the whole dict: per-param serial gets pay a
    # D2H round-trip each (hundreds of sync points on a big model);
    # batching lets jax gather every leaf in a single transfer
    values = jax.device_get(dict(params))
    rows = []
    for name in sorted(values):
        v = np.asarray(values[name])
        rows.append(f"{name}: shape={tuple(v.shape)} "
                    f"absmax={np.abs(v).max():.4g} "
                    f"mean={v.mean():.4g} std={v.std():.4g}")
    return "\n".join(rows)

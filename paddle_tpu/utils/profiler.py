"""Profiling & tracing (SURVEY §5 aux subsystems).

The reference aggregates RAII wall timers per named section
(``REGISTER_TIMER``/``StatSet``, ``paddle/utils/Stat.h:63-242``) and opens
nvprof windows via ``hl_profiler_start/end``
(``hl_cuda_device.cc:675-677``).  TPU equivalents:

- named wall timers: :mod:`paddle_tpu.utils.stat` (already per-section);
- device traces: :func:`trace` wraps ``jax.profiler`` so a window of
  steps lands in an xprof/TensorBoard trace directory;
- FP-fault trapping (``feenableexcept`` in ``TrainerMain.cpp:49``):
  :func:`enable_fp_exceptions` flips ``jax_debug_nans``/``jax_debug_infs``
  so the first NaN/Inf inside a jitted computation raises at the op.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

from .logger import get_logger

log = get_logger("profiler")


@contextlib.contextmanager
def trace(logdir: str = "/tmp/paddle_tpu_trace") -> Iterator[None]:
    """``with profiler.trace(dir): ...`` — xprof window (nvprof-window
    equivalent); view with TensorBoard's profile plugin."""
    jax.profiler.start_trace(logdir)
    log.info("profiler trace started → %s", logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", logdir)


def annotate(name: str):
    """Named sub-trace region (``REGISTER_TIMER_INFO`` equivalent inside
    traced code)."""
    return jax.profiler.TraceAnnotation(name)


def enable_fp_exceptions(enable: bool = True) -> None:
    """Trap NaN/Inf produced by jitted computations — the
    ``feenableexcept(FE_INVALID|FE_DIVBYZERO|FE_OVERFLOW)`` equivalent."""
    jax.config.update("jax_debug_nans", enable)
    jax.config.update("jax_debug_infs", enable)


def parameter_stats(params) -> str:
    """Per-parameter |value| stats line (``--show_parameter_stats_period``,
    ``TrainerInternal.cpp:99-111``)."""
    import numpy as np

    rows = []
    for name in sorted(params):
        v = np.asarray(jax.device_get(params[name]))
        rows.append(f"{name}: shape={tuple(v.shape)} "
                    f"absmax={np.abs(v).max():.4g} "
                    f"mean={v.mean():.4g} std={v.std():.4g}")
    return "\n".join(rows)

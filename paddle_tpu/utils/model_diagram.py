"""Graphviz model diagrams (``python/paddle/utils/make_model_diagram.py``).

Emits DOT text from a parsed :class:`ModelConfig` — no graphviz binary
needed to generate; render with ``dot -Tpng`` wherever available.
"""

from __future__ import annotations

from typing import List

from ..config.model_config import ModelConfig

_COLORS = {
    "data": "lightblue",
    "fc": "lightyellow",
    "exconv": "lightsalmon",
    "mixed": "lightcyan",
}


def _node(name: str) -> str:
    return '"' + name.replace('"', "'") + '"'


def model_to_dot(model: ModelConfig, name: str = "model") -> str:
    lines: List[str] = [f"digraph {name} {{", "  rankdir=BT;"]
    costs = set()
    for l in model.layers:
        color = _COLORS.get(l.type)
        if "cost" in l.type or "entropy" in l.type:
            color = "tomato"
        shape = "box" if l.type != "data" else "ellipse"
        style = f', style=filled, fillcolor="{color}"' if color else ""
        lines.append(
            f"  {_node(l.name)} [shape={shape}, "
            f'label="{l.name}\\n{l.type} ({l.size})"{style}];')
    for l in model.layers:
        for i in l.input_names():
            src = i.split(".", 1)[0]
            lines.append(f"  {_node(src)} -> {_node(l.name)};")
    for sm in model.sub_models:
        if sm.name == "root" or not sm.layer_names:
            continue
        lines.append(f'  subgraph "cluster_{sm.name}" {{')
        lines.append(f'    label="{sm.name}"; color=gray;')
        for ln in sm.layer_names:
            lines.append(f"    {_node(ln)};")
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines)

"""Named wall-clock timers with aggregated stats.

Equivalent of the reference's ``StatSet``/``REGISTER_TIMER`` RAII timers
(``paddle/utils/Stat.h:63-242``): every scope accumulates count/total/max
under a name, and ``print_all_status`` dumps the table.  The trainer wraps
each layer's forward/backward in one of these, exactly like
``NeuralNetwork.cpp:258,298``.

On TPU the async dispatch model means a timer around a jitted call measures
dispatch unless the value is blocked on; ``timer(..., block_on=x)`` calls
``x.block_until_ready()`` before stopping the clock.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional


@dataclass
class StatItem:
    name: str
    count: int = 0
    total: float = 0.0
    max: float = 0.0
    min: float = float("inf")

    def add(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.max = max(self.max, seconds)
        self.min = min(self.min, seconds)

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0


class StatSet:
    def __init__(self, name: str = "global"):
        self.name = name
        self._items: Dict[str, StatItem] = {}
        self._lock = threading.Lock()
        self.enabled = True

    def item(self, name: str) -> StatItem:
        with self._lock:
            if name not in self._items:
                self._items[name] = StatItem(name)
            return self._items[name]

    @contextlib.contextmanager
    def timer(self, name: str, block_on: Any = None) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                try:
                    import jax

                    jax.block_until_ready(block_on)
                except Exception:
                    pass
            self.item(name).add(time.perf_counter() - t0)

    def reset(self) -> None:
        with self._lock:
            self._items.clear()

    def print_all_status(self, log=print) -> None:
        with self._lock:
            items = sorted(self._items.values(), key=lambda i: -i.total)
        if not items:
            return
        log(f"======= StatSet: [{self.name}] status ======")
        log(f"{'name':<40} {'calls':>8} {'total(ms)':>12} {'avg(ms)':>10} {'max(ms)':>10}")
        for it in items:
            log(
                f"{it.name:<40} {it.count:>8} {it.total * 1e3:>12.2f} "
                f"{it.avg * 1e3:>10.3f} {it.max * 1e3:>10.3f}"
            )


global_stat = StatSet()

"""Named wall-clock timers with aggregated stats.

Equivalent of the reference's ``StatSet``/``REGISTER_TIMER`` RAII timers
(``paddle/utils/Stat.h:63-242``): every scope accumulates count/total/max
under a name, and ``print_all_status`` dumps the table.  The trainer wraps
each layer's forward/backward in one of these, exactly like
``NeuralNetwork.cpp:258,298``.

On TPU the async dispatch model means a timer around a jitted call measures
dispatch unless the value is blocked on; ``timer(..., block_on=x)`` calls
``x.block_until_ready()`` before stopping the clock.

Export: :meth:`StatSet.snapshot` returns a lock-consistent copy of the
table — the :mod:`paddle_tpu.observe.report` reporter ships it on every
JSONL line and into the Prometheus dump alongside the typed metrics, so
timers and histograms share one export path.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional

from ..analysis.lockorder import named_lock


@dataclass
class StatItem:
    name: str
    count: int = 0
    total: float = 0.0
    max: float = 0.0
    min: float = float("inf")
    # updates and snapshots race (timer threads vs the reporter flush
    # thread); a per-item lock keeps count/total/max/min one consistent
    # tuple instead of a field-by-field torn read
    _lock: threading.Lock = field(
        default_factory=lambda: named_lock("stat.item"),
        repr=False, compare=False)

    def add(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self.max = max(self.max, seconds)
            self.min = min(self.min, seconds)

    def snapshot(self) -> Dict[str, Any]:
        """Lock-consistent copy: every field read under the same lock
        acquisition, so count/total/avg always agree."""
        with self._lock:
            count, total = self.count, self.total
            mx, mn = self.max, self.min
        return {"name": self.name, "count": count, "total": total,
                "avg": total / count if count else 0.0,
                "max": mx, "min": mn if count else 0.0}

    @property
    def avg(self) -> float:
        with self._lock:
            return self.total / self.count if self.count else 0.0


class StatSet:
    def __init__(self, name: str = "global"):
        self.name = name
        self._items: Dict[str, StatItem] = {}
        self._lock = named_lock("stat.set")
        self.enabled = True

    def item(self, name: str) -> StatItem:
        with self._lock:
            if name not in self._items:
                self._items[name] = StatItem(name)
            return self._items[name]

    @contextlib.contextmanager
    def timer(self, name: str, block_on: Any = None) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                try:
                    import jax

                    jax.block_until_ready(block_on)
                except Exception as e:  # noqa: BLE001 — timing fence is
                    # best-effort: a deleted buffer must not kill the
                    # timed computation
                    from .logger import get_logger
                    get_logger("stat").debug(
                        "block_until_ready fence failed for timer %r: "
                        "%s: %s", name, type(e).__name__, e)
            self.item(name).add(time.perf_counter() - t0)

    def reset(self) -> None:
        with self._lock:
            self._items.clear()

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """``{name: {count,total,avg,max,min}}`` — each item's fields
        read atomically (the export path's view of the table)."""
        with self._lock:
            items = list(self._items.values())
        return {it.name: it.snapshot() for it in items}

    def print_all_status(self, log=print) -> None:
        snaps = sorted(self.snapshot().values(),
                       key=lambda s: -s["total"])
        if not snaps:
            return
        log(f"======= StatSet: [{self.name}] status ======")
        log(f"{'name':<40} {'calls':>8} {'total(ms)':>12} {'avg(ms)':>10} "
            f"{'max(ms)':>10} {'min(ms)':>10}")
        for s in snaps:
            log(
                f"{s['name']:<40} {s['count']:>8} "
                f"{s['total'] * 1e3:>12.2f} {s['avg'] * 1e3:>10.3f} "
                f"{s['max'] * 1e3:>10.3f} {s['min'] * 1e3:>10.3f}"
            )


global_stat = StatSet()

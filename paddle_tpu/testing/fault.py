"""Composable fault injectors for chaos-testing the elastic path.

Production TPU fleets are preemption-driven, so every recovery path in
this repo is exercised by an injected fault rather than assumed to work
(the verification spine of the robustness pass).  Injectors here are
deterministic — they fire on call counts or explicit triggers, never on
wall-clock or RNG draws — so chaos tests stay reproducible:

- :func:`drop_master_connection` — sever a ``MasterClient``'s TCP socket
  before (request lost) or after (response lost → granted-but-unheard
  lease) every Nth call.
- :class:`MasterServerProcess` — the TCP master in a child process that
  can be SIGKILLed and restarted from its snapshot on the same port.
- :func:`poison_load_fn` — raise inside ``load_fn`` on chosen shards a
  bounded number of times.
- :func:`corrupt_checkpoint` — truncate or bit-flip a checkpoint file.
- :func:`failing_saves` — make ``trainer.save`` raise a disk-full
  ``OSError`` for the next N calls.
- :class:`FleetPusherProcess` — a telemetry-pushing "trainer" child
  (real process, real fleet push client) that can be SIGKILLed,
  SIGTERMed (exercising the graceful-shutdown flush) and restarted
  under the same logical fleet id — the chaos driver for the fleet
  observatory's staleness/recovery rollup.
- :class:`ServeServerProcess` — a continuous-batching inference server
  child (real :class:`~paddle_tpu.serving.server.InferenceServer`,
  real page-pool snapshots) serving an endless request stream, built
  to be SIGKILLed mid-decode so a restart from the same snapshot path
  must prove the allocator state was never torn.
- :func:`corrupt_artifact` / :func:`resign_artifact_manifest` — damage
  a serving artifact after its digests were recorded (torn weights, or
  a manifest re-signed with a wrong digest) so the rollout pipeline's
  verify gate is the thing under test, mirroring
  :func:`corrupt_checkpoint`.
- :class:`TrainerLoopProcess` / :class:`ExporterProcess` /
  :class:`RolloutServeProcess` — the three stages of the zero-downtime
  train→serve pipeline (ISSUE 19) as SIGKILL-able children: a trainer
  saving real checkpoints in a loop, an exporter running the real
  :class:`~paddle_tpu.serving.rollout.CheckpointWatcher`, and a
  serving replica that hot-swaps every new artifact while serving an
  endless request stream — the chaos gauntlet kills each mid-flight.

Everything is loopback/local-fs only; no real network is ever touched.
"""

from __future__ import annotations

import contextlib
import errno
import os
import socket
import subprocess
import sys
import time
from typing import Callable, Iterable, Optional

from ..utils import get_logger

log = get_logger("fault")


# --------------------------------------------------------- TCP faults
def _kill_socket(sock: Optional[socket.socket]) -> None:
    """Hard-sever a socket: subsequent send/recv on it raise OSError."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


@contextlib.contextmanager
def drop_master_connection(client, every: int = 3, limit: Optional[int] = None,
                           when: str = "request"):
    """Sever ``client``'s TCP connection around every ``every``-th call.

    ``when="request"`` kills the socket *before* the request is sent (the
    request is lost; replay is trivially safe).  ``when="response"``
    first pushes the request bytes to the master, then kills the socket
    (the master processes it but the response is lost — for GET this
    manufactures a granted-but-unheard lease that must time out and
    re-queue server-side).  ``limit`` bounds the number of injected
    drops.  Yields a stats dict: ``{"calls": n, "dropped": n}``.
    """
    orig = client._call
    stats = {"calls": 0, "dropped": 0}

    def faulty_call(line: str, **kw) -> str:
        stats["calls"] += 1
        if stats["calls"] % every == 0 and \
                (limit is None or stats["dropped"] < limit):
            stats["dropped"] += 1
            if when == "response" and client._sock is not None:
                try:
                    client._sock.sendall(line.encode() + b"\n")
                except OSError:
                    pass
            _kill_socket(client._sock)
            log.info("injected connection drop #%d (%s) before %r",
                     stats["dropped"], when, line.split("\t", 1)[0])
        return orig(line, **kw)

    client._call = faulty_call
    try:
        yield stats
    finally:
        client._call = orig


# --------------------------------------------------- master processes
# The child runs the C++ service via ctypes directly — no paddle_tpu /
# jax import, so spawn is fast and a SIGKILL cannot corrupt anything
# but the master's own snapshot (which is what we are testing).
_SERVER_SCRIPT = r"""
import ctypes, sys, time
so, snap, port, timeout_s, failure_max = sys.argv[1:6]
lib = ctypes.CDLL(so)
lib.ptpu_master_create.restype = ctypes.c_void_p
lib.ptpu_master_create.argtypes = [
    ctypes.c_double, ctypes.c_int, ctypes.c_char_p]
lib.ptpu_master_serve.restype = ctypes.c_int
lib.ptpu_master_serve.argtypes = [
    ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
h = lib.ptpu_master_create(float(timeout_s), int(failure_max),
                           snap.encode() if snap else None)
p = lib.ptpu_master_serve(h, int(port), 0)
print(p, flush=True)
while True:
    time.sleep(3600)
"""


class MasterServerProcess:
    """A TCP master service in a SIGKILL-able child process.

    ``start()`` binds (remembering the port so a restart reuses it, which
    keeps the client's address stable across kills), ``kill()`` sends
    SIGKILL — no shutdown hooks run, exactly like a preempted VM — and a
    later ``start()`` recovers from the snapshot path.
    """

    def __init__(self, snapshot_path: str, timeout_s: float = 5.0,
                 failure_max: int = 3, port: int = 0):
        from ..distributed.master import _SO, _load_lib
        _load_lib()  # ensure the .so is built before the child needs it
        self._so = _SO
        self.snapshot_path = snapshot_path
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.port = port
        self.proc: Optional[subprocess.Popen] = None

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self, wait_ready_s: float = 10.0) -> "MasterServerProcess":
        assert self.proc is None or self.proc.poll() is not None, \
            "master process already running"
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _SERVER_SCRIPT, self._so,
             self.snapshot_path, str(self.port), str(self.timeout_s),
             str(self.failure_max)],
            stdout=subprocess.PIPE, text=True)
        port = int(self.proc.stdout.readline())
        assert port > 0, "master serve failed in child"
        self.port = port
        self._wait_ready(wait_ready_s)
        return self

    def _wait_ready(self, budget_s: float) -> None:
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", self.port),
                                              timeout=1.0) as s:
                    s.sendall(b"PING\n")
                    if s.recv(64).startswith(b"PONG"):
                        return
            except OSError:
                time.sleep(0.02)
        raise TimeoutError("master child never answered PING")

    def kill(self) -> None:
        """SIGKILL — the preemption model: no cleanup code runs."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None

    def __enter__(self) -> "MasterServerProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.kill()


# ---------------------------------------------- fleet pusher processes
# The child runs the REAL fleet push client (observe/fleet.py folded
# into the reporter) against a REAL aggregator: it registers with its
# role/pid/node identity, bumps a counter and closes one span per
# tick (spans parented under an optional CTX header handed over by the
# parent — the PR-8 cross-process propagation, so every process's
# spans share one trace id on the merged /fleet/trace timeline), and
# relies on the default SIGTERM hook for its goodbye frame.
_PUSHER_SCRIPT = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
(addr, fleet_id, interval_s, parent_ctx, jsonl, role, trace_jsonl,
 master_addr) = sys.argv[1:9]
from paddle_tpu.utils import FLAGS
from paddle_tpu import observe
from paddle_tpu.observe import trace

FLAGS.set("fleet_addr", addr)
FLAGS.set("fleet_id", fleet_id)
FLAGS.set("fleet_role", role)
FLAGS.set("metrics_interval_s", float(interval_s))
if jsonl:
    FLAGS.set("metrics_jsonl", jsonl)
if trace_jsonl:
    FLAGS.set("trace_jsonl", trace_jsonl)
trace.ensure_ring()          # ring-only: spans ride the push frames
observe.start_from_flags()   # reporter + pusher + SIGTERM flush hook
ctx = trace.parse_header(parent_ctx) if parent_ctx else None
print("READY", os.getpid(), flush=True)
step = 0
with trace.span("child_pass", remote_parent=ctx, child=fleet_id):
    if master_addr:          # one RPC: the C++ master echoes our CTX
        from paddle_tpu.distributed.master import MasterClient
        c = MasterClient(master_addr, retry_max=2)
        c.ping()             # -> master_rpc + master.handle spans
        c.close()
    while True:
        with trace.span("child_step", step=step, child=fleet_id):
            observe.counter("fleet_child_steps_total",
                            "chaos pusher ticks").inc()
        step += 1
        time.sleep(float(interval_s) / 4.0)
"""


class FleetPusherProcess:
    """A real fleet-pushing child process for chaos tests.

    ``start()`` spawns it and waits for the READY line (printed after
    the first registration push), ``kill()`` SIGKILLs it (the
    preemption model — no goodbye frame, the aggregator must notice
    via staleness), ``terminate()`` SIGTERMs it (the orchestrator
    grace path — the shutdown hook flushes and pushes the going-down
    frame), and a later ``start()`` re-registers under the SAME
    ``fleet_id``, flipping the rollup back to ok."""

    def __init__(self, aggregator_addr: str, fleet_id: str,
                 interval_s: float = 0.2, parent_ctx: str = "",
                 jsonl_path: str = "", role: str = "trainer",
                 trace_jsonl: str = "", master_addr: str = ""):
        self.aggregator_addr = aggregator_addr
        self.fleet_id = fleet_id
        self.interval_s = interval_s
        self.parent_ctx = parent_ctx
        self.jsonl_path = jsonl_path
        self.role = role
        self.trace_jsonl = trace_jsonl
        self.master_addr = master_addr
        self.proc: Optional[subprocess.Popen] = None

    def start(self, ready_timeout_s: float = 60.0) -> "FleetPusherProcess":
        assert self.proc is None or self.proc.poll() is not None, \
            "pusher process already running"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _PUSHER_SCRIPT,
             self.aggregator_addr, self.fleet_id, str(self.interval_s),
             self.parent_ctx, self.jsonl_path, self.role,
             self.trace_jsonl, self.master_addr],
            stdout=subprocess.PIPE, text=True, env=env)
        line = self.proc.stdout.readline()   # blocks until READY
        assert line.startswith("READY"), \
            f"pusher child failed to start: {line!r}"
        return self

    @property
    def pid(self) -> int:
        assert self.proc is not None
        return self.proc.pid

    def kill(self) -> None:
        """SIGKILL — preemption: no shutdown hook runs, no goodbye
        frame; the aggregator flips this process to 'missing' only
        via staleness."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def terminate(self, wait_s: float = 30.0) -> int:
        """SIGTERM — the orchestrator grace path: the default
        shutdown hook flushes the final interval and pushes the
        going-down frame, then the process dies BY the signal.
        Returns the child's returncode (-SIGTERM on the default
        disposition)."""
        assert self.proc is not None
        if self.proc.poll() is None:
            self.proc.terminate()
            self.proc.wait(timeout=wait_s)
        return self.proc.returncode

    def __enter__(self) -> "FleetPusherProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.kill()


# --------------------------------------------- serving server process
# The child runs a REAL InferenceServer over a REAL page pool with
# atomic snapshots, serving an endless request stream — so a SIGKILL
# lands between (or inside) pool mutations with high probability.  The
# decoder is deliberately tiny: the chaos under test is allocator
# persistence, not the math.
_SERVE_SCRIPT = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
snap, max_batch, n_pages, page_size = sys.argv[1:5]
from paddle_tpu.serving.model import (DecoderConfig, DecoderModel,
                                      init_decoder_params)
from paddle_tpu.serving.server import InferenceServer

cfg = DecoderConfig(vocab=64, dim=32, heads=2, layers=1, ffn=64,
                    max_context=64, eos_id=1)
model = DecoderModel(init_decoder_params(cfg, seed=0), cfg)
srv = InferenceServer(model, max_batch=int(max_batch),
                      n_pages=int(n_pages), page_size=int(page_size),
                      continuous=True, snapshot_path=snap).start()
print("READY", os.getpid(), flush=True)
i = 0
while True:      # endless churn: every finish releases pages and
    r = srv.submit([2 + (i % 60)] * (2 + i % 10),   # rewrites the
                   max_new_tokens=6)                # snapshot
    srv.result(r, timeout=60.0)
    print("SERVED", i, flush=True)
    i += 1
"""


class ServeServerProcess:
    """A continuous-batching inference server in a SIGKILL-able child.

    ``start()`` spawns the child and blocks on its READY line (server
    thread up, pool snapshotting to ``snapshot_path``);
    :meth:`wait_served` blocks until N requests completed — guaranteeing
    the snapshot has been rewritten through real alloc/release churn
    before the fault lands; ``kill()`` SIGKILLs (the preemption model:
    no flush hook, a snapshot write may be mid-flight — exactly the torn
    state :class:`~paddle_tpu.serving.pagepool.TornSnapshot` exists
    for).  The restarted server is built by the TEST in-process from the
    same snapshot path with the same geometry (``max_batch``,
    ``n_pages``, ``page_size`` attributes) and must verify clean."""

    def __init__(self, snapshot_path: str, max_batch: int = 4,
                 n_pages: int = 32, page_size: int = 8):
        self.snapshot_path = snapshot_path
        self.max_batch = max_batch
        self.n_pages = n_pages
        self.page_size = page_size
        self.proc: Optional[subprocess.Popen] = None

    def start(self, ready_timeout_s: float = 120.0) -> "ServeServerProcess":
        assert self.proc is None or self.proc.poll() is not None, \
            "serve process already running"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _SERVE_SCRIPT, self.snapshot_path,
             str(self.max_batch), str(self.n_pages),
             str(self.page_size)],
            stdout=subprocess.PIPE, text=True, env=env)
        line = self.proc.stdout.readline()   # blocks until READY
        assert line.startswith("READY"), \
            f"serve child failed to start: {line!r}"
        return self

    def wait_served(self, n: int = 5, timeout_s: float = 120.0) -> int:
        """Block until the child reports ``n`` completed requests (so
        the snapshot demonstrably went through churn).  Returns the
        last completed request index."""
        assert self.proc is not None
        deadline = time.monotonic() + timeout_s
        last = -1
        while last + 1 < n:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"serve child completed only {last + 1}/{n} "
                    f"requests in {timeout_s}s")
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError("serve child died before serving")
            if line.startswith("SERVED"):
                last = int(line.split()[1])
        return last

    def kill(self) -> None:
        """SIGKILL — preemption: no shutdown hook, no final snapshot
        flush; whatever bytes were mid-write stay mid-written."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None

    def __enter__(self) -> "ServeServerProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.kill()


# -------------------------------------------- rollout chaos processes
# The three stages of the train→serve pipeline as real child processes
# (real save_checkpoint, real CheckpointWatcher, real InferenceServer
# hot-swap), each killable at any instant.  They share the line
# protocol of the harnesses above: a READY line on startup, then one
# progress line per unit of work, read by the parent with a deadline.
def _child_env() -> dict:
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


class _LineChild:
    """Popen wrapper with a deadline-checked line reader; subclasses
    dispatch the child's progress lines in :meth:`_dispatch`."""

    proc: Optional[subprocess.Popen] = None

    def _spawn(self, script: str, args: Iterable[str],
               ready_timeout_s: float) -> None:
        assert self.proc is None or self.proc.poll() is not None, \
            "child process already running"
        self.proc = subprocess.Popen(
            [sys.executable, "-c", script, *[str(a) for a in args]],
            stdout=subprocess.PIPE, text=True, env=_child_env())
        line = self.proc.stdout.readline()   # blocks until READY
        assert line.startswith("READY"), \
            f"{type(self).__name__} child failed to start: {line!r}"
        self._on_ready(line.split())

    def _on_ready(self, fields: list) -> None:
        pass

    def _dispatch(self, fields: list) -> None:
        pass

    def _pump_until(self, done: Callable[[], bool],
                    timeout_s: float, what: str) -> None:
        assert self.proc is not None
        deadline = time.monotonic() + timeout_s
        while not done():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{type(self).__name__}: {what} not reached "
                    f"in {timeout_s}s")
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"{type(self).__name__} child died before {what}")
            self._dispatch(line.split())

    @property
    def pid(self) -> int:
        assert self.proc is not None
        return self.proc.pid

    def kill(self) -> None:
        """SIGKILL — preemption: no cleanup code runs; whatever write
        was mid-flight stays mid-written."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None

    def __enter__(self):
        return self.start()          # type: ignore[attr-defined]

    def __exit__(self, *exc) -> None:
        self.kill()


_TRAINER_SCRIPT = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
(save_dir, n_passes, interval_s, keep, seed_base,
 fleet_addr, fleet_id, parent_ctx) = sys.argv[1:9]
from paddle_tpu.utils import FLAGS
from paddle_tpu import observe
from paddle_tpu.observe import trace
if fleet_addr:
    FLAGS.set("fleet_addr", fleet_addr)
    FLAGS.set("fleet_id", fleet_id)
    FLAGS.set("fleet_role", "trainer")
    FLAGS.set("metrics_interval_s", 0.2)
    trace.ensure_ring()          # spans ride the push frames
    observe.start_from_flags()
from paddle_tpu.serving.model import DecoderConfig, init_decoder_params
from paddle_tpu.trainer.checkpoint import save_checkpoint
cfg = DecoderConfig(vocab=64, dim=32, heads=2, layers=1, ffn=64,
                    max_context=64, eos_id=1)
ctx = trace.parse_header(parent_ctx) if parent_ctx else None
print("READY", os.getpid(), flush=True)
i = 0
while int(n_passes) < 0 or i < int(n_passes):
    # a fresh seed per pass: every checkpoint has a distinct digest, so
    # the watcher's exactly-once set is actually exercised (seed_base
    # shifts a RESTARTED trainer onto digests it never saved before)
    params = init_decoder_params(cfg, seed=int(seed_base) + i)
    with trace.context_scope(ctx):
        save_checkpoint(save_dir, i, params, keep=int(keep))
    print("SAVED", i, flush=True)
    i += 1
    time.sleep(float(interval_s))
while True:
    time.sleep(3600)
"""


class TrainerLoopProcess(_LineChild):
    """A trainer child saving real (tiny-decoder) checkpoints in a
    loop — one ``SAVED n`` line per pass, each pass a distinct digest.
    ``kill()`` lands SIGKILL mid-loop (often mid-save: a ``.tmp-ckpt-*``
    dir in flight), which the checkpoint format must shrug off."""

    def __init__(self, save_dir: str, n_passes: int = -1,
                 interval_s: float = 0.05, keep: int = 3,
                 seed_base: int = 0,
                 fleet_addr: str = "", fleet_id: str = "",
                 parent_ctx: str = ""):
        self.save_dir = save_dir
        self.n_passes = n_passes
        self.interval_s = interval_s
        self.keep = keep
        self.seed_base = seed_base
        self.fleet_addr = fleet_addr
        self.fleet_id = fleet_id
        self.parent_ctx = parent_ctx
        self.saved = 0          # SAVED lines seen so far

    def start(self, ready_timeout_s: float = 120.0
              ) -> "TrainerLoopProcess":
        self.saved = 0
        self._spawn(_TRAINER_SCRIPT,
                    [self.save_dir, self.n_passes, self.interval_s,
                     self.keep, self.seed_base, self.fleet_addr,
                     self.fleet_id, self.parent_ctx], ready_timeout_s)
        return self

    def _dispatch(self, fields: list) -> None:
        if fields and fields[0] == "SAVED":
            self.saved = int(fields[1]) + 1

    def wait_saved(self, n: int, timeout_s: float = 120.0) -> int:
        """Block until the child has completed ``n`` checkpoint saves;
        returns the number completed."""
        self._pump_until(lambda: self.saved >= n, timeout_s,
                         f"{n} checkpoint saves")
        return self.saved


_EXPORTER_SCRIPT = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
(save_dir, export_dir, poll_s, quantize,
 fleet_addr, fleet_id, parent_ctx) = sys.argv[1:8]
from paddle_tpu.utils import FLAGS
from paddle_tpu import observe
from paddle_tpu.observe import trace
if fleet_addr:
    FLAGS.set("fleet_addr", fleet_addr)
    FLAGS.set("fleet_id", fleet_id)
    FLAGS.set("fleet_role", "exporter")
    FLAGS.set("metrics_interval_s", 0.2)
    trace.ensure_ring()
    observe.start_from_flags()
from paddle_tpu.serving.model import DecoderConfig
from paddle_tpu.serving.rollout import CheckpointWatcher
cfg = DecoderConfig(vocab=64, dim=32, heads=2, layers=1, ffn=64,
                    max_context=64, eos_id=1)
w = CheckpointWatcher(save_dir, cfg, export_dir=export_dir,
                      poll_s=float(poll_s), quantize=quantize or None)
ctx = trace.parse_header(parent_ctx) if parent_ctx else None
print("READY", os.getpid(), flush=True)
while True:
    with trace.context_scope(ctx):
        arts = w.poll_once()
    for a in arts:
        print("EXPORTED", a, flush=True)
    time.sleep(float(poll_s))
"""


class ExporterProcess(_LineChild):
    """An exporter child running the real
    :class:`~paddle_tpu.serving.rollout.CheckpointWatcher` poll loop
    (export only — no server attached) — one ``EXPORTED <dir>`` line
    per artifact.  ``kill()`` lands SIGKILL mid-export (a
    ``.tmp-export-*`` dir in flight); a restarted exporter must
    re-derive its exactly-once set from the artifacts themselves and
    never re-export or half-publish."""

    def __init__(self, save_dir: str, export_dir: str,
                 poll_s: float = 0.1, quantize: str = "int8",
                 fleet_addr: str = "", fleet_id: str = "",
                 parent_ctx: str = ""):
        self.save_dir = save_dir
        self.export_dir = export_dir
        self.poll_s = poll_s
        self.quantize = quantize
        self.fleet_addr = fleet_addr
        self.fleet_id = fleet_id
        self.parent_ctx = parent_ctx
        self.exported: list = []     # artifact dirs, in export order

    def start(self, ready_timeout_s: float = 120.0) -> "ExporterProcess":
        self.exported = []
        self._spawn(_EXPORTER_SCRIPT,
                    [self.save_dir, self.export_dir, self.poll_s,
                     self.quantize, self.fleet_addr, self.fleet_id,
                     self.parent_ctx], ready_timeout_s)
        return self

    def _dispatch(self, fields: list) -> None:
        if fields and fields[0] == "EXPORTED":
            self.exported.append(fields[1])

    def wait_exported(self, n: int, timeout_s: float = 120.0) -> list:
        """Block until ``n`` artifacts have been exported (counted from
        this start()); returns the artifact dir list so far."""
        self._pump_until(lambda: len(self.exported) >= n, timeout_s,
                         f"{n} artifact exports")
        return list(self.exported)


_ROLLOUT_SERVE_SCRIPT = r"""
import os, sys, threading, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
(export_dir, poll_s, inflight, serve_load,
 fleet_addr, fleet_id, parent_ctx) = sys.argv[1:8]
from paddle_tpu.utils import FLAGS
from paddle_tpu import observe
from paddle_tpu.observe import trace
if fleet_addr:
    FLAGS.set("fleet_addr", fleet_addr)
    FLAGS.set("fleet_id", fleet_id)
    FLAGS.set("fleet_role", "serving")
    FLAGS.set("metrics_interval_s", 0.2)
    trace.ensure_ring()
    observe.start_from_flags()
from paddle_tpu.serving.loader import artifact_digest, read_manifest
from paddle_tpu.serving.model import (DecoderConfig, DecoderModel,
                                      init_decoder_params)
from paddle_tpu.serving.rollout import (latest_valid_artifact,
                                        swap_from_artifact)
from paddle_tpu.serving.server import InferenceServer
cfg = DecoderConfig(vocab=64, dim=32, heads=2, layers=1, ffn=64,
                    max_context=64, eos_id=1)
# boot from the newest digest-valid artifact when one exists — the
# restart-resumes-the-pipeline property the gauntlet asserts
art = latest_valid_artifact(export_dir)
if art:
    model = DecoderModel.from_artifact(art)
    version = artifact_digest(read_manifest(art))
else:
    model = DecoderModel(init_decoder_params(cfg, seed=0), cfg)
    version = "seed"
srv = InferenceServer(model, max_batch=4, n_pages=64, page_size=8,
                      continuous=True, model_version=version).start()
port = srv.start_http(0)
ctx = trace.parse_header(parent_ctx) if parent_ctx else None

def _watch():
    while True:
        time.sleep(float(poll_s))
        a = latest_valid_artifact(export_dir)
        if not a:
            continue
        with trace.context_scope(ctx):
            rep = swap_from_artifact(srv, a, inflight=inflight or None)
        if rep.get("result") == "ok":
            print("SWAPPED", rep.get("version"), flush=True)

threading.Thread(target=_watch, name="ptpu-rollout-swapper",
                 daemon=True).start()
print("READY", os.getpid(), port, version, flush=True)
i = 0
while serve_load == "1":
    with trace.context_scope(ctx), trace.span("serve_request", i=i):
        r = srv.submit([2 + (i % 60)] * (2 + i % 10), max_new_tokens=6)
        toks = srv.result(r, timeout=60.0)
    assert toks, "empty generation"
    print("SERVED", i, srv.model_version, flush=True)
    i += 1
while True:
    time.sleep(3600)
"""


class RolloutServeProcess(_LineChild):
    """A serving replica child that hot-swaps every new artifact while
    serving an endless request stream.

    Boots from the newest digest-valid artifact in ``export_dir`` (or
    seed weights when empty) and exposes the real HTTP front on an
    ephemeral port (``.port``), so a :class:`RollingCoordinator` can
    POST ``/v1/swap`` at it; a watcher thread inside the child also
    swaps in whatever :func:`latest_valid_artifact` finds, so
    ``kill()`` can land SIGKILL mid-swap.  Progress lines:
    ``SWAPPED <version>`` per completed hot-swap and ``SERVED <i>
    <version>`` per completed request — every response is stamped with
    the version that served it, which is how the gauntlet proves
    responses never mix model versions."""

    def __init__(self, export_dir: str, poll_s: float = 0.1,
                 inflight: str = "drain", serve_load: bool = True,
                 fleet_addr: str = "", fleet_id: str = "",
                 parent_ctx: str = ""):
        self.export_dir = export_dir
        self.poll_s = poll_s
        self.inflight = inflight
        self.serve_load = serve_load
        self.fleet_addr = fleet_addr
        self.fleet_id = fleet_id
        self.parent_ctx = parent_ctx
        self.port = 0
        self.boot_version = ""
        self.served = 0
        self.swaps: list = []            # versions, in swap order
        self.served_versions: list = []  # (request index, version)

    def start(self, ready_timeout_s: float = 120.0
              ) -> "RolloutServeProcess":
        self.served = 0
        self.swaps = []
        self.served_versions = []
        self._spawn(_ROLLOUT_SERVE_SCRIPT,
                    [self.export_dir, self.poll_s, self.inflight,
                     "1" if self.serve_load else "0", self.fleet_addr,
                     self.fleet_id, self.parent_ctx], ready_timeout_s)
        return self

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _on_ready(self, fields: list) -> None:
        self.port = int(fields[2])
        self.boot_version = fields[3]

    def _dispatch(self, fields: list) -> None:
        if not fields:
            return
        if fields[0] == "SWAPPED":
            self.swaps.append(fields[1])
        elif fields[0] == "SERVED":
            self.served = int(fields[1]) + 1
            self.served_versions.append((int(fields[1]), fields[2]))

    def wait_served(self, n: int, timeout_s: float = 120.0) -> int:
        """Block until ``n`` requests completed; returns the count."""
        self._pump_until(lambda: self.served >= n, timeout_s,
                         f"{n} served requests")
        return self.served

    def wait_swapped(self, n: int = 1, timeout_s: float = 120.0) -> list:
        """Block until ``n`` hot-swaps completed (counted from this
        start()); returns the swapped-in version list so far."""
        self._pump_until(lambda: len(self.swaps) >= n, timeout_s,
                         f"{n} hot-swaps")
        return list(self.swaps)


# ------------------------------------------------------- data faults
class ShardFault(RuntimeError):
    """Raised by a poisoned ``load_fn`` (distinct type so tests can
    assert the fault propagated through the right path)."""


def poison_load_fn(load_fn: Callable, bad_payloads: Iterable[str],
                   times: int = 1) -> Callable:
    """Wrap ``load_fn`` to raise :class:`ShardFault` the first ``times``
    times each payload in ``bad_payloads`` is loaded; later attempts
    pass through (a transiently bad shard).  ``times < 0`` poisons the
    shard permanently."""
    bad = set(bad_payloads)
    hits: dict = {}

    def wrapped(payload):
        if payload in bad:
            n = hits.get(payload, 0)
            if times < 0 or n < times:
                hits[payload] = n + 1
                raise ShardFault(
                    f"injected shard fault on {payload!r} (hit {n + 1})")
        return load_fn(payload)

    wrapped.hits = hits
    return wrapped


# ------------------------------------------------- checkpoint faults
def corrupt_checkpoint(ckpt_dir: str, fname: str = "params.npz",
                       mode: str = "truncate") -> str:
    """Damage one file of a checkpoint dir in place.

    ``mode="truncate"`` chops the file to half its size (a torn write /
    partial flush); ``mode="bitflip"`` XOR-flips one byte in the middle
    (silent media corruption — the case only digests can catch).
    Returns the damaged path.
    """
    path = os.path.join(ckpt_dir, fname)
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    elif mode == "bitflip":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    log.info("injected %s corruption into %s", mode, path)
    return path


# --------------------------------------------------- artifact faults
def corrupt_artifact(artifact_dir: str, fname: str = "weights.npz",
                     mode: str = "truncate") -> str:
    """Damage one file of a serving artifact AFTER its digests were
    recorded in the manifest — the torn-artifact case the rollout
    verify gate (``loader.verify_artifact``) exists for.  Same damage
    modes as :func:`corrupt_checkpoint`; returns the damaged path."""
    return corrupt_checkpoint(artifact_dir, fname=fname, mode=mode)


def resign_artifact_manifest(artifact_dir: str,
                             fname: str = "weights.npz") -> str:
    """Re-sign an artifact manifest with a WRONG digest for ``fname``
    (sizes stay correct, so only the sha256 comparison can catch it) —
    the malicious/buggy-writer case: the weights are intact but the
    manifest lies about them.  Returns the manifest path."""
    import json as _json

    path = os.path.join(artifact_dir, "manifest.json")
    with open(path) as f:
        manifest = _json.load(f)
    files = manifest.get("files") or {}
    if fname not in files:
        raise ValueError(f"manifest has no digest entry for {fname!r}")
    files[fname]["sha256"] = "0" * 64
    with open(path, "w") as f:
        _json.dump(manifest, f, indent=1)
    log.info("re-signed %s with wrong digest for %s", path, fname)
    return path


@contextlib.contextmanager
def failing_saves(trainer, times: int = 1,
                  exc: Optional[OSError] = None):
    """Make ``trainer.save`` raise a disk-full ``OSError`` for the next
    ``times`` calls (``times < 0``: every call), then pass through.
    Yields a stats dict ``{"failed": n, "succeeded": n}``."""
    orig = trainer.save
    stats = {"failed": 0, "succeeded": 0}

    def faulty_save(save_dir, pass_id):
        if times < 0 or stats["failed"] < times:
            stats["failed"] += 1
            raise exc or OSError(errno.ENOSPC,
                                 "injected: no space left on device")
        out = orig(save_dir, pass_id)
        stats["succeeded"] += 1
        return out

    trainer.save = faulty_save
    try:
        yield stats
    finally:
        trainer.save = orig

"""Composable fault injectors for chaos-testing the elastic path.

Production TPU fleets are preemption-driven, so every recovery path in
this repo is exercised by an injected fault rather than assumed to work
(the verification spine of the robustness pass).  Injectors here are
deterministic — they fire on call counts or explicit triggers, never on
wall-clock or RNG draws — so chaos tests stay reproducible:

- :func:`drop_master_connection` — sever a ``MasterClient``'s TCP socket
  before (request lost) or after (response lost → granted-but-unheard
  lease) every Nth call.
- :class:`MasterServerProcess` — the TCP master in a child process that
  can be SIGKILLed and restarted from its snapshot on the same port.
- :func:`poison_load_fn` — raise inside ``load_fn`` on chosen shards a
  bounded number of times.
- :func:`corrupt_checkpoint` — truncate or bit-flip a checkpoint file.
- :func:`failing_saves` — make ``trainer.save`` raise a disk-full
  ``OSError`` for the next N calls.
- :class:`FleetPusherProcess` — a telemetry-pushing "trainer" child
  (real process, real fleet push client) that can be SIGKILLed,
  SIGTERMed (exercising the graceful-shutdown flush) and restarted
  under the same logical fleet id — the chaos driver for the fleet
  observatory's staleness/recovery rollup.
- :class:`ServeServerProcess` — a continuous-batching inference server
  child (real :class:`~paddle_tpu.serving.server.InferenceServer`,
  real page-pool snapshots) serving an endless request stream, built
  to be SIGKILLed mid-decode so a restart from the same snapshot path
  must prove the allocator state was never torn.

Everything is loopback/local-fs only; no real network is ever touched.
"""

from __future__ import annotations

import contextlib
import errno
import os
import socket
import subprocess
import sys
import time
from typing import Callable, Iterable, Optional

from ..utils import get_logger

log = get_logger("fault")


# --------------------------------------------------------- TCP faults
def _kill_socket(sock: Optional[socket.socket]) -> None:
    """Hard-sever a socket: subsequent send/recv on it raise OSError."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


@contextlib.contextmanager
def drop_master_connection(client, every: int = 3, limit: Optional[int] = None,
                           when: str = "request"):
    """Sever ``client``'s TCP connection around every ``every``-th call.

    ``when="request"`` kills the socket *before* the request is sent (the
    request is lost; replay is trivially safe).  ``when="response"``
    first pushes the request bytes to the master, then kills the socket
    (the master processes it but the response is lost — for GET this
    manufactures a granted-but-unheard lease that must time out and
    re-queue server-side).  ``limit`` bounds the number of injected
    drops.  Yields a stats dict: ``{"calls": n, "dropped": n}``.
    """
    orig = client._call
    stats = {"calls": 0, "dropped": 0}

    def faulty_call(line: str, **kw) -> str:
        stats["calls"] += 1
        if stats["calls"] % every == 0 and \
                (limit is None or stats["dropped"] < limit):
            stats["dropped"] += 1
            if when == "response" and client._sock is not None:
                try:
                    client._sock.sendall(line.encode() + b"\n")
                except OSError:
                    pass
            _kill_socket(client._sock)
            log.info("injected connection drop #%d (%s) before %r",
                     stats["dropped"], when, line.split("\t", 1)[0])
        return orig(line, **kw)

    client._call = faulty_call
    try:
        yield stats
    finally:
        client._call = orig


# --------------------------------------------------- master processes
# The child runs the C++ service via ctypes directly — no paddle_tpu /
# jax import, so spawn is fast and a SIGKILL cannot corrupt anything
# but the master's own snapshot (which is what we are testing).
_SERVER_SCRIPT = r"""
import ctypes, sys, time
so, snap, port, timeout_s, failure_max = sys.argv[1:6]
lib = ctypes.CDLL(so)
lib.ptpu_master_create.restype = ctypes.c_void_p
lib.ptpu_master_create.argtypes = [
    ctypes.c_double, ctypes.c_int, ctypes.c_char_p]
lib.ptpu_master_serve.restype = ctypes.c_int
lib.ptpu_master_serve.argtypes = [
    ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
h = lib.ptpu_master_create(float(timeout_s), int(failure_max),
                           snap.encode() if snap else None)
p = lib.ptpu_master_serve(h, int(port), 0)
print(p, flush=True)
while True:
    time.sleep(3600)
"""


class MasterServerProcess:
    """A TCP master service in a SIGKILL-able child process.

    ``start()`` binds (remembering the port so a restart reuses it, which
    keeps the client's address stable across kills), ``kill()`` sends
    SIGKILL — no shutdown hooks run, exactly like a preempted VM — and a
    later ``start()`` recovers from the snapshot path.
    """

    def __init__(self, snapshot_path: str, timeout_s: float = 5.0,
                 failure_max: int = 3, port: int = 0):
        from ..distributed.master import _SO, _load_lib
        _load_lib()  # ensure the .so is built before the child needs it
        self._so = _SO
        self.snapshot_path = snapshot_path
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.port = port
        self.proc: Optional[subprocess.Popen] = None

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self, wait_ready_s: float = 10.0) -> "MasterServerProcess":
        assert self.proc is None or self.proc.poll() is not None, \
            "master process already running"
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _SERVER_SCRIPT, self._so,
             self.snapshot_path, str(self.port), str(self.timeout_s),
             str(self.failure_max)],
            stdout=subprocess.PIPE, text=True)
        port = int(self.proc.stdout.readline())
        assert port > 0, "master serve failed in child"
        self.port = port
        self._wait_ready(wait_ready_s)
        return self

    def _wait_ready(self, budget_s: float) -> None:
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", self.port),
                                              timeout=1.0) as s:
                    s.sendall(b"PING\n")
                    if s.recv(64).startswith(b"PONG"):
                        return
            except OSError:
                time.sleep(0.02)
        raise TimeoutError("master child never answered PING")

    def kill(self) -> None:
        """SIGKILL — the preemption model: no cleanup code runs."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None

    def __enter__(self) -> "MasterServerProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.kill()


# ---------------------------------------------- fleet pusher processes
# The child runs the REAL fleet push client (observe/fleet.py folded
# into the reporter) against a REAL aggregator: it registers with its
# role/pid/node identity, bumps a counter and closes one span per
# tick (spans parented under an optional CTX header handed over by the
# parent — the PR-8 cross-process propagation, so every process's
# spans share one trace id on the merged /fleet/trace timeline), and
# relies on the default SIGTERM hook for its goodbye frame.
_PUSHER_SCRIPT = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
(addr, fleet_id, interval_s, parent_ctx, jsonl, role, trace_jsonl,
 master_addr) = sys.argv[1:9]
from paddle_tpu.utils import FLAGS
from paddle_tpu import observe
from paddle_tpu.observe import trace

FLAGS.set("fleet_addr", addr)
FLAGS.set("fleet_id", fleet_id)
FLAGS.set("fleet_role", role)
FLAGS.set("metrics_interval_s", float(interval_s))
if jsonl:
    FLAGS.set("metrics_jsonl", jsonl)
if trace_jsonl:
    FLAGS.set("trace_jsonl", trace_jsonl)
trace.ensure_ring()          # ring-only: spans ride the push frames
observe.start_from_flags()   # reporter + pusher + SIGTERM flush hook
ctx = trace.parse_header(parent_ctx) if parent_ctx else None
print("READY", os.getpid(), flush=True)
step = 0
with trace.span("child_pass", remote_parent=ctx, child=fleet_id):
    if master_addr:          # one RPC: the C++ master echoes our CTX
        from paddle_tpu.distributed.master import MasterClient
        c = MasterClient(master_addr, retry_max=2)
        c.ping()             # -> master_rpc + master.handle spans
        c.close()
    while True:
        with trace.span("child_step", step=step, child=fleet_id):
            observe.counter("fleet_child_steps_total",
                            "chaos pusher ticks").inc()
        step += 1
        time.sleep(float(interval_s) / 4.0)
"""


class FleetPusherProcess:
    """A real fleet-pushing child process for chaos tests.

    ``start()`` spawns it and waits for the READY line (printed after
    the first registration push), ``kill()`` SIGKILLs it (the
    preemption model — no goodbye frame, the aggregator must notice
    via staleness), ``terminate()`` SIGTERMs it (the orchestrator
    grace path — the shutdown hook flushes and pushes the going-down
    frame), and a later ``start()`` re-registers under the SAME
    ``fleet_id``, flipping the rollup back to ok."""

    def __init__(self, aggregator_addr: str, fleet_id: str,
                 interval_s: float = 0.2, parent_ctx: str = "",
                 jsonl_path: str = "", role: str = "trainer",
                 trace_jsonl: str = "", master_addr: str = ""):
        self.aggregator_addr = aggregator_addr
        self.fleet_id = fleet_id
        self.interval_s = interval_s
        self.parent_ctx = parent_ctx
        self.jsonl_path = jsonl_path
        self.role = role
        self.trace_jsonl = trace_jsonl
        self.master_addr = master_addr
        self.proc: Optional[subprocess.Popen] = None

    def start(self, ready_timeout_s: float = 60.0) -> "FleetPusherProcess":
        assert self.proc is None or self.proc.poll() is not None, \
            "pusher process already running"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _PUSHER_SCRIPT,
             self.aggregator_addr, self.fleet_id, str(self.interval_s),
             self.parent_ctx, self.jsonl_path, self.role,
             self.trace_jsonl, self.master_addr],
            stdout=subprocess.PIPE, text=True, env=env)
        line = self.proc.stdout.readline()   # blocks until READY
        assert line.startswith("READY"), \
            f"pusher child failed to start: {line!r}"
        return self

    @property
    def pid(self) -> int:
        assert self.proc is not None
        return self.proc.pid

    def kill(self) -> None:
        """SIGKILL — preemption: no shutdown hook runs, no goodbye
        frame; the aggregator flips this process to 'missing' only
        via staleness."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def terminate(self, wait_s: float = 30.0) -> int:
        """SIGTERM — the orchestrator grace path: the default
        shutdown hook flushes the final interval and pushes the
        going-down frame, then the process dies BY the signal.
        Returns the child's returncode (-SIGTERM on the default
        disposition)."""
        assert self.proc is not None
        if self.proc.poll() is None:
            self.proc.terminate()
            self.proc.wait(timeout=wait_s)
        return self.proc.returncode

    def __enter__(self) -> "FleetPusherProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.kill()


# --------------------------------------------- serving server process
# The child runs a REAL InferenceServer over a REAL page pool with
# atomic snapshots, serving an endless request stream — so a SIGKILL
# lands between (or inside) pool mutations with high probability.  The
# decoder is deliberately tiny: the chaos under test is allocator
# persistence, not the math.
_SERVE_SCRIPT = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
snap, max_batch, n_pages, page_size = sys.argv[1:5]
from paddle_tpu.serving.model import (DecoderConfig, DecoderModel,
                                      init_decoder_params)
from paddle_tpu.serving.server import InferenceServer

cfg = DecoderConfig(vocab=64, dim=32, heads=2, layers=1, ffn=64,
                    max_context=64, eos_id=1)
model = DecoderModel(init_decoder_params(cfg, seed=0), cfg)
srv = InferenceServer(model, max_batch=int(max_batch),
                      n_pages=int(n_pages), page_size=int(page_size),
                      continuous=True, snapshot_path=snap).start()
print("READY", os.getpid(), flush=True)
i = 0
while True:      # endless churn: every finish releases pages and
    r = srv.submit([2 + (i % 60)] * (2 + i % 10),   # rewrites the
                   max_new_tokens=6)                # snapshot
    srv.result(r, timeout=60.0)
    print("SERVED", i, flush=True)
    i += 1
"""


class ServeServerProcess:
    """A continuous-batching inference server in a SIGKILL-able child.

    ``start()`` spawns the child and blocks on its READY line (server
    thread up, pool snapshotting to ``snapshot_path``);
    :meth:`wait_served` blocks until N requests completed — guaranteeing
    the snapshot has been rewritten through real alloc/release churn
    before the fault lands; ``kill()`` SIGKILLs (the preemption model:
    no flush hook, a snapshot write may be mid-flight — exactly the torn
    state :class:`~paddle_tpu.serving.pagepool.TornSnapshot` exists
    for).  The restarted server is built by the TEST in-process from the
    same snapshot path with the same geometry (``max_batch``,
    ``n_pages``, ``page_size`` attributes) and must verify clean."""

    def __init__(self, snapshot_path: str, max_batch: int = 4,
                 n_pages: int = 32, page_size: int = 8):
        self.snapshot_path = snapshot_path
        self.max_batch = max_batch
        self.n_pages = n_pages
        self.page_size = page_size
        self.proc: Optional[subprocess.Popen] = None

    def start(self, ready_timeout_s: float = 120.0) -> "ServeServerProcess":
        assert self.proc is None or self.proc.poll() is not None, \
            "serve process already running"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-c", _SERVE_SCRIPT, self.snapshot_path,
             str(self.max_batch), str(self.n_pages),
             str(self.page_size)],
            stdout=subprocess.PIPE, text=True, env=env)
        line = self.proc.stdout.readline()   # blocks until READY
        assert line.startswith("READY"), \
            f"serve child failed to start: {line!r}"
        return self

    def wait_served(self, n: int = 5, timeout_s: float = 120.0) -> int:
        """Block until the child reports ``n`` completed requests (so
        the snapshot demonstrably went through churn).  Returns the
        last completed request index."""
        assert self.proc is not None
        deadline = time.monotonic() + timeout_s
        last = -1
        while last + 1 < n:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"serve child completed only {last + 1}/{n} "
                    f"requests in {timeout_s}s")
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError("serve child died before serving")
            if line.startswith("SERVED"):
                last = int(line.split()[1])
        return last

    def kill(self) -> None:
        """SIGKILL — preemption: no shutdown hook, no final snapshot
        flush; whatever bytes were mid-write stay mid-written."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None

    def __enter__(self) -> "ServeServerProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.kill()


# ------------------------------------------------------- data faults
class ShardFault(RuntimeError):
    """Raised by a poisoned ``load_fn`` (distinct type so tests can
    assert the fault propagated through the right path)."""


def poison_load_fn(load_fn: Callable, bad_payloads: Iterable[str],
                   times: int = 1) -> Callable:
    """Wrap ``load_fn`` to raise :class:`ShardFault` the first ``times``
    times each payload in ``bad_payloads`` is loaded; later attempts
    pass through (a transiently bad shard).  ``times < 0`` poisons the
    shard permanently."""
    bad = set(bad_payloads)
    hits: dict = {}

    def wrapped(payload):
        if payload in bad:
            n = hits.get(payload, 0)
            if times < 0 or n < times:
                hits[payload] = n + 1
                raise ShardFault(
                    f"injected shard fault on {payload!r} (hit {n + 1})")
        return load_fn(payload)

    wrapped.hits = hits
    return wrapped


# ------------------------------------------------- checkpoint faults
def corrupt_checkpoint(ckpt_dir: str, fname: str = "params.npz",
                       mode: str = "truncate") -> str:
    """Damage one file of a checkpoint dir in place.

    ``mode="truncate"`` chops the file to half its size (a torn write /
    partial flush); ``mode="bitflip"`` XOR-flips one byte in the middle
    (silent media corruption — the case only digests can catch).
    Returns the damaged path.
    """
    path = os.path.join(ckpt_dir, fname)
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    elif mode == "bitflip":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    log.info("injected %s corruption into %s", mode, path)
    return path


@contextlib.contextmanager
def failing_saves(trainer, times: int = 1,
                  exc: Optional[OSError] = None):
    """Make ``trainer.save`` raise a disk-full ``OSError`` for the next
    ``times`` calls (``times < 0``: every call), then pass through.
    Yields a stats dict ``{"failed": n, "succeeded": n}``."""
    orig = trainer.save
    stats = {"failed": 0, "succeeded": 0}

    def faulty_save(save_dir, pass_id):
        if times < 0 or stats["failed"] < times:
            stats["failed"] += 1
            raise exc or OSError(errno.ENOSPC,
                                 "injected: no space left on device")
        out = orig(save_dir, pass_id)
        stats["succeeded"] += 1
        return out

    trainer.save = faulty_save
    try:
        yield stats
    finally:
        trainer.save = orig

"""Test-support utilities shipped with the package.

:mod:`fault` is the chaos-engineering toolkit: composable fault
injectors (dropped master connections, killed master processes,
poisoned shards, corrupted checkpoints, failing saves) used by
``tests/test_chaos.py`` to *prove* the elastic-training recovery paths
instead of assuming them.
"""

from . import fault  # noqa: F401

__all__ = ["fault"]

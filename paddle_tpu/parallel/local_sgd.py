"""Local SGD — the TPU-native re-expression of async-SGD.

Reference: ``paddle/pserver/ParameterServer2.h:468`` applies trainer
gradients lock-free and asynchronously ("async SGD" mode — each trainer
updates shared parameters without waiting for the others), and the server
exposes ``AVERAGE_PARAMETER`` (``doOperation``, ``ParameterService.proto``
:24-110) to average parameter copies.  The point of both is the same:
decouple workers from the global synchronization barrier.

On a TPU mesh there is no parameter server and XLA collectives make the
*synchronous* barrier nearly free intra-pod, so a literal async port would
be a de-optimization.  The capability the reference actually provides —
trade gradient-staleness for synchronization cost — maps to **K-step
local SGD with periodic parameter averaging** (Stich, "Local SGD
Converges Fast and Communicates Little"): every data shard applies K
optimizer steps on its own parameter copy with NO cross-shard traffic,
then copies are averaged (the AVERAGE_PARAMETER operation) and
re-broadcast.  Staleness is bounded by K like the reference's
``max_lagged_grad``; K=1 with plain SGD is numerically identical to
synchronous all-reduce DP (tested).

Mechanics: parameter/optimizer/buffer pytrees gain a leading ``D`` axis
(one slot per data shard) sharded over the mesh ``data`` axis, the
per-shard step runs under ``jax.vmap`` (SPMD partitions the vmap axis so
each device updates only its own copy, zero collectives), and the
periodic average is a ``mean`` over the D axis — the only collective,
issued every K-th step inside the same jit.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.device import DATA_AXIS, replicated
from ..trainer.trainer import Trainer, _batch_size
from ..utils import enforce, get_logger

log = get_logger("local_sgd")


def _tree_map(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def _stack(tree, d: int):
    """Add a leading local-replica axis of size d to every leaf."""
    return _tree_map(lambda x: jnp.broadcast_to(
        x[None], (d,) + np.shape(x)).copy() if hasattr(x, "shape")
        else x, tree)


def _shard_feed_local(feed: Dict[str, Any], d: int):
    """[B, ...] → [D, B/D, ...] on every leaf (SequenceBatch pytrees
    included)."""
    def split(x):
        if not hasattr(x, "shape") or np.ndim(x) == 0:
            return x
        b = x.shape[0]
        enforce(b % d == 0,
                f"local SGD: batch {b} not divisible by {d} shards")
        return x.reshape((d, b // d) + x.shape[1:])

    return {k: jax.tree_util.tree_map(split, v) for k, v in feed.items()}


class LocalSGDTrainer(Trainer):
    """Trainer whose DP shards run K local steps between parameter
    averages (``OptimizationConfig.local_sgd_steps``)."""

    def __init__(self, network, optimizer=None, opt_config=None, **kwargs):
        super().__init__(network, optimizer=optimizer,
                         opt_config=opt_config, **kwargs)
        if self.precision == "bf16":
            # the local-SGD step is its own vmapped program: the
            # per-shard loss runs under the bf16 policy scope (see
            # _build_train_step), but the master-cast/loss-scaling
            # machinery only wraps the base Trainer step
            from ..utils.logger import warn_once
            warn_once(
                "local_sgd_bf16",
                "precision=bf16 with local_sgd_steps: bf16 compute "
                "applies, but dynamic loss scaling / skipped-step "
                "semantics are not wired into the local-SGD step",
                logger=log)
            self._ls_state = None
        self.local_steps = max(
            1, getattr(opt_config, "local_sgd_steps", 1) or 1)
        self.n_shards = self.mesh.shape.get(DATA_AXIS, 1)
        self._step_count = 0

    @property
    def _stacked(self) -> bool:
        """Params gain their leading replica axis on the first train
        step; eval/save before that must not reduce a real dimension."""
        return self._train_step is not None

    # ----------------------------------------------------------- stacking
    def _local_sharding(self, x):
        from jax.sharding import NamedSharding, PartitionSpec
        spec = PartitionSpec(DATA_AXIS, *(None,) * (np.ndim(x) - 1))
        return NamedSharding(self.mesh, spec)

    def _place_local(self, tree):
        return _tree_map(
            lambda x: jax.device_put(x, self._local_sharding(x))
            if hasattr(x, "shape") and np.ndim(x) >= 1
            else jax.device_put(x, replicated(self.mesh)), tree)

    # --------------------------------------------------------- train step
    def _build_train_step(self):
        net = self.network
        opt = self.optimizer
        lr_scales = self._lr_scales
        d = self.n_shards

        # config-carried bf16 (OptimizationConfig.precision with the
        # flag still fp32): enter the policy scope inside the traced
        # shard step so ops actually dispatch bf16 — the same contract
        # the base Trainer's mixed step keeps
        import contextlib

        from ..core.dtypes import policy_for, policy_scope
        pol = policy_for("bf16") if self.precision == "bf16" else None

        def one_shard(params, slots, buffers, feed, rng, count, progress):
            scope = policy_scope(pol) if pol is not None \
                else contextlib.nullcontext()
            with scope:
                def loss_fn(p):
                    loss, (values, new_buffers) = net.loss(
                        p, feed, buffers, is_training=True, rng=rng)
                    return loss, new_buffers

                (loss, new_buffers), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
            lr = self.schedule(progress)
            new_params, (_, new_slots) = opt.apply(
                params, grads, (count, slots), lr, lr_scales)
            return new_params, new_slots, new_buffers, loss

        def step(params_l, slots_l, buffers_l, feed, rngs, count,
                 progress, do_avg):
            new_p, new_o, new_b, losses = jax.vmap(
                one_shard, in_axes=(0, 0, 0, 0, 0, None, None))(
                    params_l, slots_l, buffers_l, feed, rngs, count,
                    progress)

            # AVERAGE_PARAMETER: mean over the replica axis, re-broadcast.
            # Branchless — jnp.where on the traced do_avg scalar keeps one
            # compiled program for both kinds of step.
            def avg(x):
                if np.ndim(x) < 1 or x.shape[0] != d:
                    return x
                m = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True),
                                     x.shape)
                return jnp.where(do_avg, m.astype(x.dtype), x)

            new_p = _tree_map(avg, new_p)
            new_b = _tree_map(avg, new_b)
            return new_p, new_o, new_b, jnp.mean(losses)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def train_one_batch(self, feed: Dict[str, Any]) -> float:
        if self._train_step is None:
            self._train_step = self._build_train_step()
            self._eval_step = None   # pre-stacking eval step is stale now
            d = self.n_shards
            self.params = self._place_local(
                _stack(self._dealias(self.params), d))
            count, slots = self.opt_state
            self.opt_state = (
                jax.device_put(count, replicated(self.mesh)),
                self._place_local(_stack(self._dealias(slots), d)))
            self.buffers = self._place_local(
                _stack(self._dealias(self.buffers), d))
        batch = _batch_size(feed)
        feed = _shard_feed_local(feed, self.n_shards)
        feed = {k: jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._local_sharding(x))
            if hasattr(x, "shape") and np.ndim(x) >= 1 else x, v)
            for k, v in feed.items()}
        base = jax.random.PRNGKey(
            (self.seed * 1000003 + self.samples_seen) % (2 ** 31))
        rngs = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(self.n_shards))
        self._step_count += 1
        do_avg = jnp.asarray(self._step_count % self.local_steps == 0)
        count, slots = self.opt_state
        new_p, new_slots, new_b, loss = self._train_step(
            self.params, slots, self.buffers, feed, rngs, count,
            jnp.asarray(self.samples_seen, jnp.float32), do_avg)
        self.params = new_p
        self.opt_state = (count + 1, new_slots)
        self.buffers = new_b
        self.samples_seen += batch
        return loss

    # ------------------------------------------------------ consolidation
    def consolidated_params(self) -> Dict[str, jax.Array]:
        """Replica-averaged parameters (for eval/save)."""
        if not self._stacked:
            return self.params
        return _tree_map(lambda x: jnp.mean(x, axis=0), self.params)

    def _build_eval_step(self):
        if not self._stacked:
            return super()._build_eval_step()
        net = self.network
        eval_names = self._eval_output_names()

        # one jitted program: the replica-mean folds into the compiled
        # eval step instead of dispatching per-leaf eager means per batch
        def step(params_l, buffers_l, feed):
            params = _tree_map(lambda x: jnp.mean(x, axis=0), params_l)
            buffers = _tree_map(
                lambda x: x[0] if np.ndim(x) >= 1 else x, buffers_l)
            loss, (values, _) = net.loss(params, feed, buffers,
                                         is_training=False)
            outs = dict(net.outputs(values))
            for n in eval_names:
                if n in values:
                    outs[n] = values[n]
            return loss, outs

        return jax.jit(step)

    def save(self, save_dir: str, pass_id: int) -> str:
        from ..trainer.checkpoint import save_checkpoint

        if not self._stacked:
            return super().save(save_dir, pass_id)
        slots = self.opt_state[1]
        return save_checkpoint(
            save_dir, pass_id, self.consolidated_params(),
            (self.opt_state[0],
             _tree_map(lambda x: jnp.mean(x, axis=0)
                       if np.ndim(x) >= 1 else x, slots)),
            _tree_map(lambda x: x[0] if np.ndim(x) >= 1 else x,
                      self.buffers),
            meta={"samples_seen": self.samples_seen})


def make_trainer(network, opt_config, **kwargs) -> Trainer:
    """Factory honoring ``OptimizationConfig.local_sgd_steps``."""
    cls = LocalSGDTrainer if getattr(opt_config, "local_sgd_steps", 0) \
        else Trainer
    return cls(network, opt_config=opt_config, **kwargs)

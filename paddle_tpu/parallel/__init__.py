"""Parallelism toolkit: mesh-axis sharding for parameters and activations.

Replaces the reference's intra-node parallel machinery with GSPMD
annotations (SURVEY §2.5 mapping):

- ``MultiGradientMachine`` thread-per-GPU data parallelism
  (``MultiGradientMachine.h:45``) → batch sharded over the ``data`` axis
  (already the Trainer default).
- ``ParallelNeuralNetwork`` per-layer device placement (``--parallel_nn``,
  per-layer ``device=`` in ModelConfig) → per-parameter/activation
  PartitionSpec rules over the ``model`` axis (:class:`ShardingRules`).
- Sparse-remote parameter sharding (``SparseRemoteParameterUpdater``,
  row-sparse tables on dedicated pserver ports) → embedding tables sharded
  on the vocab dim over ``model``; the row-gather becomes an XLA
  all-gather/dynamic-slice pair the partitioner inserts.
"""

from .sharding import (ShardingRules, tp_rules, shard_params,
                       constraint, param_dims_of,
                       verify_rules_or_raise,
                       match_partition_rules, fsdp_spec,
                       fsdp_rules_for, make_shard_and_gather_fns,
                       spec_shard_info, FSDP_MIN_SIZE)  # noqa: F401
from .rule_tables import (lstm_fsdp_rules, resnet_fsdp_rules,
                          transformer_fsdp_rules, ctr_fsdp_rules,
                          recommender_fsdp_rules,
                          zoo_fsdp_rules, ZOO_FSDP_RULES)  # noqa: F401
from .ring_attention import (ring_attention, ulysses_attention,
                             full_attention)  # noqa: F401
from ..ops.pallas_attention import flash_attention  # noqa: F401
from .sparse import (SelectedRows, unique_rows, row_gather,
                     row_scatter_add, row_scatter_set, touched_row_mask,
                     prefetch_rows, sparse_embedding_lookup,
                     unique_rows_sorted, lookup_rows, exchange_scope,
                     exchange_entry,
                     exchange_payload_bytes)  # noqa: F401

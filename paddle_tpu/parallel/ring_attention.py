"""Long-context attention: ring attention and Ulysses-style all-to-all
sequence parallelism over the device mesh.

The reference (pre-transformer) handles long sequences only by
variable-length batching (SURVEY §5 long-context); this framework makes
sequence/context parallelism first-class for TPU scale:

- :func:`ring_attention` — q/k/v sharded on the sequence dim over a mesh
  axis; each step computes a flash-style streaming block (running max +
  log-sum-exp accumulation) against the resident k/v shard, then rotates
  k/v around the ring with ``lax.ppermute`` so comms ride ICI and overlap
  with the matmuls.  Memory per chip is O(T/P); exact (not approximate).
- :func:`ulysses_attention` — ``all_to_all`` re-shards from sequence-
  parallel to head-parallel, runs dense local attention, and re-shards
  back (DeepSpeed-Ulysses pattern); cheaper for moderate T with many
  heads.

Both are pure jax and run under ``shard_map`` on any mesh — tested on the
8-device CPU mesh, identical math on a TPU pod slice.  For the
single-chip hot path, :func:`paddle_tpu.ops.pallas_attention.
flash_attention` is the Pallas kernel version of the same blockwise
math (8.4× the dense formulation at T=2048 on v5e); the ring/Ulysses
bodies keep the pure-jax formulation because their backward
differentiates through the scan, which Pallas calls do not support
without a ring-level custom VJP.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import enforce

# jax.shard_map is the 0.5.x spelling; fall back to the experimental
# module on older jax so interpret-mode CI runs on either version
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map

NEG_INF = -1e30


def _as_varying(x, axis_name):
    """Type a replicated value as device-varying over ``axis_name`` so a
    scan carry matches its (idx-dependent) updated value under
    shard_map.  ``lax.pvary`` was deprecated for ``lax.pcast(...,
    to='varying')`` mid-0.9; support both spellings.  Pre-0.6 jax has
    neither and no varying-manual-axes check — identity is correct."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis_name,))
    return x


def _block_attn(q, k, v, m_prev, l_prev, o_prev, mask):
    """One flash-attention block update.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; mask: [Tq, Tk] additive or None.
    Carries the running max ``m``, normalizer ``l`` and unnormalized
    output ``o`` (all fp32).
    """
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # ptpu: lint-ok[PT-DTYPE] fp32-by-design: flash-attention scores
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / jnp.sqrt(q.shape[-1])
    if mask is not None:
        scores = scores + mask[None, None, :, :]
    m_cur = jnp.max(scores, axis=-1)                       # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (max = -inf)
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    l_cur = jnp.sum(p, axis=-1)
    alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0,
                      jnp.exp(m_prev - m_safe))
    l_new = alpha * l_prev + l_cur
    # ptpu: lint-ok[PT-DTYPE] fp32-by-design flash-attention accumulator
    o_new = alpha[..., None] * o_prev + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vf)
    return m_new, l_new, o_new


def _finalize(m, l, o, dtype):
    out = o / jnp.maximum(l, 1e-20)[..., None]             # [B, H, Tq, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(dtype)  # [B, Tq, H, D]


def _local_ring(q, k, v, axis_name: str, causal: bool):
    """Per-shard body under shard_map: q/k/v are the local sequence
    blocks [B, Tl, H, D]."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    # initial carries must be typed as device-varying for the scan carry
    # to match the (idx-dependent) updated values under shard_map
    m0 = _as_varying(jnp.full((b, h, tl), NEG_INF, jnp.float32),
                     axis_name)
    l0 = _as_varying(jnp.zeros((b, h, tl), jnp.float32), axis_name)
    o0 = _as_varying(jnp.zeros((b, h, tl, d), jnp.float32), axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    pos_q = idx * tl + jnp.arange(tl)

    def step(carry, r):
        k_r, v_r, m, l, o = carry
        # k_r currently holds the block of ring-source (idx - r) mod n
        src = (idx - r) % n
        if causal:
            pos_k = src * tl + jnp.arange(tl)
            mask = jnp.where(pos_q[:, None] >= pos_k[None, :], 0.0,
                             NEG_INF)
        else:
            mask = None
        m, l, o = _block_attn(q, k_r, v_r, m, l, o, mask)
        k_r = lax.ppermute(k_r, axis_name, perm)
        v_r = lax.ppermute(v_r, axis_name, perm)
        return (k_r, v_r, m, l, o), None

    (k_f, v_f, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                      jnp.arange(n))
    return _finalize(m, l, o, q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "data",
                   causal: bool = False):
    """Exact attention over sequences sharded on ``axis``.

    q/k/v: [B, T, H, D] with T divisible by the axis size.  Returns
    [B, T, H, D] with the same sharding.
    """
    enforce(q.shape[1] % mesh.shape[axis] == 0,
            f"T={q.shape[1]} not divisible by mesh axis {axis}")
    spec = P(None, axis, None, None)
    fn = _shard_map(
        functools.partial(_local_ring, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _local_ulysses(q, k, v, axis_name: str, causal: bool, t_total: int):
    """all_to_all: [B, T/P, H, D] → [B, T, H/P, D], dense attention,
    back."""
    def seq2head(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    mask = None
    if causal:
        pos = jnp.arange(t_total)
        mask = jnp.where(pos[:, None] >= pos[None, :], 0.0, NEG_INF)
    b, t, h, d = qh.shape
    m0 = jnp.full((b, h, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    m, l, o = _block_attn(qh, kh, vh, m0, l0, o0, mask)
    return head2seq(_finalize(m, l, o, q.dtype))


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "data",
                      causal: bool = False):
    """Sequence-parallel attention via head re-sharding (all-to-all).

    Heads must be divisible by the axis size.
    """
    p = mesh.shape[axis]
    enforce(q.shape[2] % p == 0,
            f"H={q.shape[2]} not divisible by mesh axis {axis}")
    enforce(q.shape[1] % p == 0,
            f"T={q.shape[1]} not divisible by mesh axis {axis}")
    spec = P(None, axis, None, None)
    fn = _shard_map(
        functools.partial(_local_ulysses, axis_name=axis, causal=causal,
                          t_total=q.shape[1]),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference: softmax(q·kᵀ/√d)·v."""
    # ptpu: lint-ok[PT-DTYPE] fp32-by-design reference implementation
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / jnp.sqrt(q.shape[-1])
    if causal:
        t = q.shape[1]
        mask = jnp.where(jnp.arange(t)[:, None] >= jnp.arange(t)[None, :],
                         0.0, NEG_INF)
        scores = scores + mask[None, None]
    w = jax.nn.softmax(scores, axis=-1)
    # ptpu: lint-ok[PT-DTYPE] fp32-by-design reference implementation
    out = jnp.einsum("bhqk,bkhd->bhqd", w, v.astype(jnp.float32))
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)

"""Per-parameter sharding rules (regex → PartitionSpec).

The reference expresses model placement imperatively (a layer's ``device=``
attribute routes it to a compute thread, ``ParallelNeuralNetwork.h:34``).
TPU-native: parameters get ``NamedSharding``s; XLA's SPMD partitioner
derives activation layouts and inserts the collectives.  Rules are
name-pattern based so they compose with any config-driven model.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.device import DATA_AXIS, MODEL_AXIS, get_mesh
from ..utils import get_logger

log = get_logger("sharding")


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules; first match wins."""

    def __init__(self, rules: Sequence[Tuple[str, P]] = ()):
        self.rules: List[Tuple[re.Pattern, P]] = [
            (re.compile(pat), spec) for pat, spec in rules]

    def add(self, pattern: str, spec: P) -> "ShardingRules":
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name) and len(spec) <= ndim:
                return spec
        return P()  # replicated

    def sharding_for(self, name: str, ndim: int,
                     mesh: Optional[Mesh] = None) -> NamedSharding:
        mesh = mesh or get_mesh()
        return NamedSharding(mesh, self.spec_for(name, ndim))

    def verify(self, param_dims: Dict[str, Sequence[int]],
               mesh_axes: Optional[Dict[str, int]] = None,
               strict: bool = False) -> list:
        """Statically verify this table against a model's parameter
        tree on one mesh topology (PT-SHARD,
        :func:`paddle_tpu.analysis.netcheck.check_sharding`): unmatched
        and ambiguously-matched params are flagged, spec ranks checked
        against param ranks, and every sharded dim checked for
        mesh-axis divisibility — milliseconds instead of a pod-compile
        failure.  Returns the issue list; errors are compile-fatal."""
        from ..analysis import netcheck

        if mesh_axes is None:
            mesh = get_mesh()
            mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        return netcheck.check_sharding(self, param_dims, mesh_axes,
                                       strict=strict)


def param_dims_of(net) -> Dict[str, List[int]]:
    """A NeuralNetwork's parameter tree as name → dims, the shape
    :meth:`ShardingRules.verify` consumes (no arrays materialized)."""
    return {n: list(s.dims) if s.dims else [s.size]
            for n, s in net.param_specs.items()}


def verify_rules_or_raise(rules: "ShardingRules",
                          param_dims: Dict[str, Sequence[int]],
                          mesh_axes: Dict[str, int]) -> None:
    """Preflight: raise ``PaddleTpuError`` listing every error-severity
    finding (a bad rule fails fast, before anything compiles)."""
    from ..analysis import netcheck
    from ..utils import PaddleTpuError

    errs = netcheck.errors(rules.verify(param_dims, mesh_axes))
    if errs:
        raise PaddleTpuError(
            f"sharding preflight failed on mesh {mesh_axes} "
            f"({len(errs)} error(s)):\n"
            + "\n".join("  " + e.render() for e in errs))


def tp_rules(model_axis: str = MODEL_AXIS) -> ShardingRules:
    """Default tensor-parallel ruleset for the layer engine's parameter
    naming (``_<layer>.w<i>`` / ``_<layer>.wbias``):

    - embedding tables: shard the vocab (row) dim — the sparse-remote
      equivalent; lookups become gather + collective.
    - fc/projection weights: shard the output (col) dim (Megatron-style
      column parallel); XLA inserts the matching all-reduce.
    - recurrent/batch-norm/bias: replicated (latency-bound, tiny).
    """
    return ShardingRules([
        (r"emb|__table|lookup", P(model_axis, None)),
        (r"\.wbias$|\.b$|bn|batch_norm", P()),
        (r"lstm|gru|recurrent", P()),
        (r"\.w\d*$", P(None, model_axis)),
    ])


def shard_params(params: Dict[str, jax.Array], rules: ShardingRules,
                 mesh: Optional[Mesh] = None) -> Dict[str, jax.Array]:
    """Place every parameter according to the rules (device_put with
    NamedSharding — GSPMD propagates the rest)."""
    mesh = mesh or get_mesh()
    out = {}
    for name, value in params.items():
        leaves = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, rules.sharding_for(name, getattr(x, "ndim", 0), mesh)),
            value)
        out[name] = leaves
    return out


def constraint(x, *spec, mesh: Optional[Mesh] = None):
    """``with_sharding_constraint`` helper for layer authors — the
    per-layer ``device=`` placement equivalent."""
    mesh = mesh or get_mesh()
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))

"""Per-parameter sharding rules (regex → PartitionSpec).

The reference expresses model placement imperatively (a layer's ``device=``
attribute routes it to a compute thread, ``ParallelNeuralNetwork.h:34``).
TPU-native: parameters get ``NamedSharding``s; XLA's SPMD partitioner
derives activation layouts and inserts the collectives.  Rules are
name-pattern based so they compose with any config-driven model.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.device import DATA_AXIS, MODEL_AXIS, get_mesh
from ..utils import get_logger, warn_once

log = get_logger("sharding")


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules; first match wins."""

    def __init__(self, rules: Sequence[Tuple[str, P]] = ()):
        self.rules: List[Tuple[re.Pattern, P]] = [
            (re.compile(pat), spec) for pat, spec in rules]

    def add(self, pattern: str, spec: P) -> "ShardingRules":
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                if len(spec) <= ndim:
                    return spec
                # a matching rule whose spec rank exceeds the param's
                # falls through to the next rule (or replication) — say
                # so once, or a typo'd table quietly replicates a
                # 10^8-row embedding and the "win" is silence
                warn_once(
                    f"sharding.rank_excluded:{pat.pattern}:{name}",
                    "sharding rule %r matches parameter %r but its "
                    "spec %s has rank %d > param rank %d — rule "
                    "skipped (next rule or replication applies)",
                    pat.pattern, name, tuple(spec), len(spec), ndim,
                    logger=log)
        return P()  # replicated

    def sharding_for(self, name: str, ndim: int,
                     mesh: Optional[Mesh] = None) -> NamedSharding:
        mesh = mesh or get_mesh()
        return NamedSharding(mesh, self.spec_for(name, ndim))

    def verify(self, param_dims: Dict[str, Sequence[int]],
               mesh_axes: Optional[Dict[str, int]] = None,
               strict: bool = False) -> list:
        """Statically verify this table against a model's parameter
        tree on one mesh topology (PT-SHARD,
        :func:`paddle_tpu.analysis.netcheck.check_sharding`): unmatched
        and ambiguously-matched params are flagged, spec ranks checked
        against param ranks, and every sharded dim checked for
        mesh-axis divisibility — milliseconds instead of a pod-compile
        failure.  Returns the issue list; errors are compile-fatal."""
        from ..analysis import netcheck

        if mesh_axes is None:
            mesh = get_mesh()
            mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        return netcheck.check_sharding(self, param_dims, mesh_axes,
                                       strict=strict)


def param_dims_of(net) -> Dict[str, List[int]]:
    """A NeuralNetwork's parameter tree as name → dims, the shape
    :meth:`ShardingRules.verify` consumes (no arrays materialized)."""
    return {n: list(s.dims) if s.dims else [s.size]
            for n, s in net.param_specs.items()}


def verify_rules_or_raise(rules: "ShardingRules",
                          param_dims: Dict[str, Sequence[int]],
                          mesh_axes: Dict[str, int]) -> None:
    """Preflight: raise ``PaddleTpuError`` listing every error-severity
    finding (a bad rule fails fast, before anything compiles)."""
    from ..analysis import netcheck
    from ..utils import PaddleTpuError

    errs = netcheck.errors(rules.verify(param_dims, mesh_axes))
    if errs:
        raise PaddleTpuError(
            f"sharding preflight failed on mesh {mesh_axes} "
            f"({len(errs)} error(s)):\n"
            + "\n".join("  " + e.render() for e in errs))


def tp_rules(model_axis: str = MODEL_AXIS) -> ShardingRules:
    """Default tensor-parallel ruleset for the layer engine's parameter
    naming (``_<layer>.w<i>`` / ``_<layer>.wbias``):

    - embedding tables: shard the vocab (row) dim — the sparse-remote
      equivalent; lookups become gather + collective.
    - fc/projection weights: shard the output (col) dim (Megatron-style
      column parallel); XLA inserts the matching all-reduce.
    - recurrent/batch-norm/bias: replicated (latency-bound, tiny).
    """
    return ShardingRules([
        (r"emb|__table|lookup", P(model_axis, None)),
        (r"\.wbias$|\.b$|bn|batch_norm", P()),
        (r"lstm|gru|recurrent", P()),
        (r"\.w\d*$", P(None, model_axis)),
    ])


def shard_params(params: Dict[str, jax.Array], rules: ShardingRules,
                 mesh: Optional[Mesh] = None) -> Dict[str, jax.Array]:
    """Place every parameter according to the rules (device_put with
    NamedSharding — GSPMD propagates the rest)."""
    mesh = mesh or get_mesh()
    out = {}
    for name, value in params.items():
        leaves = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, rules.sharding_for(name, getattr(x, "ndim", 0), mesh)),
            value)
        out[name] = leaves
    return out


def constraint(x, *spec, mesh: Optional[Mesh] = None):
    """``with_sharding_constraint`` helper for layer authors — the
    per-layer ``device=`` placement equivalent."""
    mesh = mesh or get_mesh()
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


# ===================================================== FSDP (data axis)
#: Parameters smaller than this many ELEMENTS stay replicated under the
#: auto heuristic: sharding a 64-float LayerNorm gain buys nothing and
#: fragments the all-gather schedule.
FSDP_MIN_SIZE = 1024


def match_partition_rules(rules: ShardingRules,
                          param_dims: Dict[str, Sequence[int]]) -> Dict[str, P]:
    """Resolve a rule table against a parameter tree: name → the
    PartitionSpec first-match-wins assigns (the SNIPPETS
    ``match_partition_rules`` shape, over our name→dims census instead
    of a pytree of arrays).  Scalars always resolve replicated."""
    return {name: rules.spec_for(name, len(dims))
            for name, dims in param_dims.items()}


def fsdp_spec(shape: Sequence[int], n_shards: int,
              axis: str = DATA_AXIS,
              min_size: int = FSDP_MIN_SIZE) -> P:
    """FSDP auto heuristic for one parameter: shard the LARGEST dim
    divisible by ``n_shards`` over ``axis``; replicate when nothing
    divides or the param is below ``min_size`` elements.  Used when no
    committed rule table covers the model (``fsdp_rules_for`` derives a
    whole tree's specs from it)."""
    shape = tuple(int(d) for d in shape)
    if n_shards <= 1 or not shape \
            or int(np.prod(shape)) < max(min_size, 1):
        return P()
    best = -1
    for d, size in enumerate(shape):
        if size % n_shards == 0 and size > 0 \
                and (best < 0 or size > shape[best]):
            best = d
    if best < 0:
        return P()
    entries: List[Optional[str]] = [None] * len(shape)
    entries[best] = axis
    return P(*entries)


def fsdp_rules_for(param_dims: Dict[str, Sequence[int]],
                   n_shards: int, axis: str = DATA_AXIS,
                   min_size: int = FSDP_MIN_SIZE) -> Dict[str, P]:
    """Auto-derived FSDP placement for a whole parameter tree:
    name → spec via :func:`fsdp_spec` (largest divisible dim over the
    ``data`` axis).  The committed per-zoo tables in
    :mod:`paddle_tpu.parallel.rule_tables` take precedence when the
    model is a known zoo member — they encode intent (replicate norms
    and biases, shard matmul weights on a stable dim) where the
    heuristic only encodes divisibility."""
    return {name: fsdp_spec(dims, n_shards, axis, min_size)
            for name, dims in param_dims.items()}


def make_shard_and_gather_fns(specs: Dict[str, P],
                              mesh: Optional[Mesh] = None):
    """Per-name (shard_fn, gather_fn) pairs for a resolved spec dict —
    the SNIPPETS [3] shape.  ``shard_fns[name](x)`` commits ``x`` to
    its NamedSharding; ``gather_fns[name](x)`` brings the global array
    back fully replicated (checkpoint writers and debuggers use it)."""
    mesh = mesh or get_mesh()
    rep = NamedSharding(mesh, P())

    def _shard(sh):
        return lambda x: jax.device_put(x, sh)

    def _gather(x):
        return jax.device_put(x, rep)

    shard_fns = {name: _shard(NamedSharding(mesh, spec))
                 for name, spec in specs.items()}
    gather_fns = {name: _gather for name in specs}
    return shard_fns, gather_fns


def spec_shard_info(spec: P, mesh: Mesh) -> Optional[Tuple[int, int]]:
    """``(dim, n_shards)`` of the FIRST sharded dim of ``spec`` on
    ``mesh`` (None when fully replicated) — the shape sharded
    checkpoints record per parameter so a loader can reassemble the
    global array without a mesh."""
    axes_by_name = dict(zip(mesh.axis_names, mesh.devices.shape))
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for ax in names:
            n *= int(axes_by_name.get(ax, 1))
        if n > 1:
            return d, n
    return None

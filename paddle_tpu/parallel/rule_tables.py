"""Committed FSDP rule tables for the model zoo (data-axis sharding).

One literal :class:`~paddle_tpu.parallel.sharding.ShardingRules` table
per zoo family — LSTM text classifier, ResNet (cifar/50 block family),
transformer encoder classifier, and the wide&deep CTR/recommender
shape — mapping the layer engine's parameter naming
(``_<layer>.w<i>`` / ``.wbias`` / ``.wo``) to ``data``-axis
PartitionSpecs.  These are the FSDP half of the placement story: the
batch is already sharded over ``data``; these tables additionally
shard every large parameter (and, through the trainer, its Adam/moment
slots) over the SAME axis, so per-chip HBM for params + optimizer
state drops by the data-axis extent while XLA's partitioner turns the
dense gradient all-reduce into an all-gather/reduce-scatter pair.

Authoring rules (see README "Multi-chip"):

- first match wins — put narrow exceptions (norms, biases, heads)
  BEFORE broad catch-alls;
- shard a dim that stays divisible across the family's configured
  sizes (embedding rows, gate-stacked hidden columns, conv output
  channels); replicate 1-D norm/bias params — sharding a 64-float
  LayerNorm gain fragments collectives for no memory win;
- every table here is linted statically by PT-SHARD (patterns must
  compile, no dead/shadowed duplicates, axes are strings) and
  verified per topology by ``ShardingRules.verify`` in the test
  suite (``tests/test_fsdp.py``).
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..core.device import DATA_AXIS
from .sharding import ShardingRules


def lstm_fsdp_rules() -> ShardingRules:
    """LSTM text classifier (``models.text.lstm_text_classifier``):
    embedding rows, gate-stacked ``[in, 4H]`` weight columns, and the
    classifier head's input dim shard over ``data``; biases replicate
    (the fused-gate bias is the only >1 KiB one and it rides the
    recurrent scan — replication keeps the carry local)."""
    return ShardingRules([
        (r"embedding.*\.w\d*$", P(DATA_AXIS, None)),
        (r"\.wbias$", P()),
        (r"(lstm|gru)\d*(_transform)?\.w\d*$", P(None, DATA_AXIS)),
        (r"fc.*\.w\d*$", P(DATA_AXIS, None)),
    ])


def resnet_fsdp_rules() -> ShardingRules:
    """ResNet block family (``models.image.resnet`` /
    ``resnet_cifar10``): conv kernels ``[kh, kw, cin, cout]`` shard the
    output-channel dim, the final fc shards its input dim; batch-norm
    scale/shift and biases replicate (tiny, and BN folding wants them
    whole)."""
    return ShardingRules([
        (r"batch_norm.*\.(w\d*|wbias)$", P()),
        (r"\.wbias$", P()),
        (r"conv.*\.w\d*$", P(None, None, None, DATA_AXIS)),
        (r"fc.*\.w\d*$", P(DATA_AXIS, None)),
    ])


def transformer_fsdp_rules() -> ShardingRules:
    """Transformer encoder classifier
    (``models.text.transformer_text_classifier``): token/position
    embedding rows and the classifier head's input dim shard over
    ``data``; attention QKV/out projections and both ffn matmuls shard
    their output dim (stays divisible across the family's
    ``model_dim``/``ffn_dim`` sizes); LayerNorm params and biases
    replicate."""
    return ShardingRules([
        (r"_ln.*\.(w\d*|wbias)$", P()),
        (r"\.wbias$", P()),
        (r"embedding.*\.w\d*$", P(DATA_AXIS, None)),
        (r"_cls\.w\d*$", P(DATA_AXIS, None)),
        (r"\.(wo|w\d*)$", P(None, DATA_AXIS)),
    ])


def ctr_fsdp_rules() -> ShardingRules:
    """Wide&deep CTR / recommender shape (``demo/ctr``,
    ``demo/recommender``): THE memory is the sparse embedding table —
    shard its rows over ``data``; the dense tower fcs stay replicated
    (a 13-wide dense input and a 2-wide softmax head leave no dim that
    divides across the family, and the tower is KiB-scale anyway)."""
    return ShardingRules([
        (r"emb.*\.w\d*$", P(DATA_AXIS, None)),
        (r".", P()),
    ])


def recommender_fsdp_rules() -> ShardingRules:
    """Dual-tower MovieLens recommender (``demo/recommender``): the
    user-id and movie-id tables (``_usr_emb.w`` / ``_mov_emb.w``, the
    demo's named sparse-update params) carry the memory at production
    row counts — shard their rows over ``data``; the feature embeddings
    (gender/age/job/category bags — tens of rows) and the KiB-scale
    tower fcs replicate, both too small to divide across topologies."""
    return ShardingRules([
        (r"_(usr|mov)_emb\.w\d*$", P(DATA_AXIS, None)),
        (r".", P()),
    ])


#: Zoo-family name → table factory, the lookup ``Trainer(fsdp=True,
#: fsdp_rules=zoo_fsdp_rules("transformer"))`` callers use.
ZOO_FSDP_RULES = {
    "lstm": lstm_fsdp_rules,
    "resnet": resnet_fsdp_rules,
    "transformer": transformer_fsdp_rules,
    "ctr": ctr_fsdp_rules,
    "recommender": recommender_fsdp_rules,
}


def zoo_fsdp_rules(family: str) -> ShardingRules:
    """The committed FSDP table for a zoo ``family`` (KeyError lists
    the known families)."""
    try:
        return ZOO_FSDP_RULES[family]()
    except KeyError:
        raise KeyError(
            f"no committed FSDP rule table for {family!r}; known "
            f"families: {sorted(ZOO_FSDP_RULES)}") from None

"""Row-sparse parameter machinery — the reference's large-model story.

Re-expresses, TPU-first:

- ``SelectedRows`` (``paddle/framework/selected_rows.h:23``): a row-sparse
  value — ``rows`` indices + ``values`` block — used for embedding-style
  gradients and fixed-capacity prefetches.
- Growable/prefetching row-sparse matrices
  (``paddle/math/SparseRowMatrix.h:29,204,235``): on TPU the table itself
  stays a dense (optionally 'model'-axis row-sharded) HBM array — XLA has
  no growable buffers — but *work* is row-sparse: batches touch a fixed
  capacity of unique rows, gathered once up front (the sparse-remote
  "prefetch rows for this batch" contract,
  ``paddle/trainer/RemoteParameterUpdater.h:265``) and scatter-updated.
- Lazy row-sparse optimizer updates (``SparseRowCpuMatrix::sgdUpdate``,
  sparse ``SelectedRows`` optimizer kernels in
  ``paddle/operators/math/selected_rows_functor.cc``): only rows touched
  by the batch get value *and* moment updates; untouched rows — and their
  Adam/Adagrad slots — are left bit-identical.

Two composition styles:

1. **In-graph lazy masking** (`touched_row_mask` + ``Optimizer.apply(...,
   sparse_masks=...)``): the autodiff gradient stays dense-shaped, but the
   update is masked to touched rows.  O(V) elementwise work — fully fused
   by XLA, zero extra HBM traffic beyond the gradient — with exact lazy
   semantics.  This is what ``ParamAttr(sparse_update=True)`` turns on in
   the Trainer.
2. **Fixed-capacity prefetch** (`prefetch_rows` → compute on the gathered
   block → ``Optimizer.apply_rows``): O(K) work and memory, K = unique-row
   capacity; the table is never materialized in the gradient.  For giant
   (sharded) tables — CTR/NCE scale — where O(V) per step is unacceptable.
"""

from __future__ import annotations

import contextlib
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class SelectedRows(NamedTuple):
    """Row-sparse value (``selected_rows.h:23``): ``values[i]`` belongs to
    dense row ``rows[i]``; ``rows`` may contain -1 padding (ignored)."""

    rows: jax.Array        # [K] int32, -1 = empty slot
    values: jax.Array      # [K, ...] row block
    height: int            # dense row count (static)

    def to_dense(self) -> jax.Array:
        """Materialize: scatter-add values into a zero dense tensor
        (duplicate rows accumulate, like SelectedRows merge_add)."""
        dense = jnp.zeros((self.height,) + self.values.shape[1:],
                          self.values.dtype)
        return row_scatter_add(dense, self.rows, self.values)


def unique_rows(ids: jax.Array, capacity: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Deduplicate ids into a fixed-capacity row set (jit-static shapes).

    Returns ``(rows [capacity] int32 padded with -1, inverse)`` with
    ``rows[inverse] == ids.ravel()``.  Capacity overflow policy: jnp.unique
    keeps the smallest ids; callers size capacity >= max unique ids per
    batch (the reference's prefetch buffer is sized the same way,
    ``SparsePrefetchRowCpuMatrix`` ``SparseRowMatrix.h:204``).
    """
    flat = ids.astype(jnp.int32).ravel()
    rows, inverse = jnp.unique(flat, size=capacity, fill_value=-1,
                               return_inverse=True)
    return rows, inverse.reshape(ids.shape)


def row_gather(table: jax.Array, rows: jax.Array) -> jax.Array:
    """Gather table rows; -1 padded slots read row 0 (value unused)."""
    safe = jnp.where(rows < 0, 0, rows)
    return jnp.take(table, safe, axis=0)


def row_scatter_add(table: jax.Array, rows: jax.Array,
                    values: jax.Array) -> jax.Array:
    """table[rows] += values; -1 padded slots are routed out of bounds
    and dropped (mode='drop'), so they can't alias row 0."""
    idx = jnp.where(rows < 0, table.shape[0], rows)
    return table.at[idx].add(values.astype(table.dtype), mode="drop")


def row_scatter_set(table: jax.Array, rows: jax.Array,
                    values: jax.Array) -> jax.Array:
    """table[rows] = values, ignoring -1 padded slots (callers guarantee
    unique real rows — unique_rows output)."""
    idx = jnp.where(rows < 0, table.shape[0], rows)
    return table.at[idx].set(values.astype(table.dtype), mode="drop")


def touched_row_mask(grad: jax.Array,
                     ids: Optional[jax.Array] = None) -> jax.Array:
    """[V] bool mask of rows touched this batch.

    From ``ids`` when the caller has them (exact — the reference's
    SelectedRows rows set); else inferred from non-zero gradient rows
    (equivalent for gather-style layers: untouched rows get exactly-zero
    cotangents from autodiff).
    """
    if ids is not None:
        mask = jnp.zeros((grad.shape[0],), bool)
        return mask.at[ids.astype(jnp.int32).ravel()].set(True)
    return jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)))


def prefetch_rows(table: jax.Array, ids: jax.Array, capacity: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The sparse-remote prefetch contract
    (``RemoteParameterUpdater.h:265``): dedupe this batch's ids, gather
    that fixed-capacity row block once.

    Returns ``(rows [K], block [K, D], inverse ids.shape)``; downstream
    compute uses ``block[inverse]`` and differentiates w.r.t. ``block``
    (a [K, D] cotangent — the table never appears in the gradient).
    On a 'model'-axis row-sharded table the gather lowers to an XLA
    all-gather of just the K rows over ICI.
    """
    rows, inverse = unique_rows(ids, capacity)
    return rows, row_gather(table, rows), inverse


def sparse_embedding_lookup(block: jax.Array, inverse: jax.Array
                            ) -> jax.Array:
    """Second half of the prefetch pattern: ids-shaped embedding from the
    prefetched block ([K, D] → inverse.shape + [D])."""
    return jnp.take(block, inverse, axis=0)


# ================================================ sparse gradient exchange
#
# The trainer-side composition of the fixed-capacity prefetch: with
# ``--sparse_grads`` the jitted train step dedupes each embedding
# table's batch ids ONCE (``unique_rows_sorted``), gathers the touched
# rows into a [K, D] block (Pallas scalar-prefetch kernel on capable
# shapes, ops/pallas_embedding.py), and routes every lookup of that
# table through the block via a TRACE-TIME substitution scope the
# EmbeddingLayer consults.  Autodiff then yields a [K, D] cotangent —
# the (rows, values) exchange payload; the dense [V, D] gradient is
# never materialized, and on a row-sharded table the update is a
# shard-local scatter-add instead of a dense all-reduce (the
# SparseRemoteParameterUpdater exchange, expressed in SPMD).

def unique_rows_sorted(ids: jax.Array, capacity: int, height: int
                       ) -> jax.Array:
    """Dedupe ids into a SORTED fixed-capacity row set padded with
    ``height`` (one-past-the-end, kept sorted — unlike the -1 padding
    of :func:`unique_rows`) so presence lookups are a searchsorted.
    Pad rows route out of bounds in every scatter (mode='drop') and
    clamp in every gather, exactly like -1 pads."""
    flat = ids.astype(jnp.int32).ravel()
    return jnp.unique(flat, size=capacity, fill_value=height)


def lookup_rows(rows: jax.Array, block: jax.Array, ids: jax.Array
                ) -> jax.Array:
    """ids-shaped embedding from a sorted row set + gathered block:
    ``block[searchsorted(rows, ids)]``.  Exact whenever every id is
    present in ``rows`` (the exchange scope's contract — rows came from
    this batch's own ids at sufficient capacity)."""
    pos = jnp.searchsorted(rows, ids.astype(jnp.int32))
    return jnp.take(block, pos.reshape(ids.shape), axis=0)


# Param name → (rows, block) substitution entries for the CURRENT trace.
# A trace-time construct by design: the trainer pushes the scope while
# the step jaxpr is built and the EmbeddingLayer reads it during the
# same trace; the finally rebalances even when tracing aborts.
_exchange_scope: list = []


@contextlib.contextmanager
def exchange_scope(entries):
    """Route embedding lookups of the named tables through their
    prefetched ``(rows, block)`` pair for the duration of this trace
    (``entries``: param name → (rows [K], block [K, D]))."""
    _exchange_scope.append(dict(entries))  # ptpu: lint-ok[PT-TRACE] trace-time stack
    try:
        yield
    finally:
        _exchange_scope.pop()              # ptpu: lint-ok[PT-TRACE] trace-time stack


def exchange_entry(param_name: str):
    """The active ``(rows, block)`` substitution for ``param_name``,
    else None (the dense lookup path)."""
    if _exchange_scope:
        return _exchange_scope[-1].get(param_name)
    return None


def exchange_payload_bytes(capacity: int, dim: int,
                           value_itemsize: int = 4) -> int:
    """Exchanged gradient bytes of one (rows, values) pair: K int32
    row indices + the [K, D] value block — the traffic a dense
    all-reduce of the [V, D] gradient is replaced by."""
    return int(capacity) * (4 + int(dim) * int(value_itemsize))

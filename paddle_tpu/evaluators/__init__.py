from .evaluators import (
    EVALUATORS,
    AucEvaluator,
    ChunkEvaluator,
    ClassificationErrorEvaluator,
    ColumnSumEvaluator,
    CTCErrorEvaluator,
    Evaluator,
    PnpairEvaluator,
    PrecisionRecallEvaluator,
    RankAucEvaluator,
    SumEvaluator,
    create_evaluator,
)

__all__ = [
    "EVALUATORS",
    "AucEvaluator",
    "ChunkEvaluator",
    "ClassificationErrorEvaluator",
    "ColumnSumEvaluator",
    "CTCErrorEvaluator",
    "Evaluator",
    "PnpairEvaluator",
    "PrecisionRecallEvaluator",
    "RankAucEvaluator",
    "SumEvaluator",
    "create_evaluator",
]

"""Streaming evaluators.

Reference: ``paddle/gserver/evaluators/Evaluator.h:42`` — start/eval/finish
lifecycle with values accumulated across batches.  Registered names match
the reference: classification_error, sum, column_sum, precision_recall,
pnpair, rankauc, auc, chunk (IOB/IOE), ctc_edit_distance.

Device work stays minimal: each ``eval`` pulls already-computed outputs
(host numpy) and accumulates python-side, exactly like the reference's CPU
accumulation after the forward pass.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..core.sequence import SequenceBatch, value_of
from ..utils import Registry

EVALUATORS: Registry = Registry("evaluator")


class Evaluator:
    name = "evaluator"

    def __init__(self, **kwargs):
        self.kw = kwargs
        self.start()

    def start(self) -> None:
        raise NotImplementedError

    def eval_batch(self, output, label, weight=None) -> None:
        raise NotImplementedError

    def get_value(self) -> Dict[str, float]:
        raise NotImplementedError

    def finish(self) -> Dict[str, float]:
        return self.get_value()

    @staticmethod
    def _to_np(x):
        if isinstance(x, SequenceBatch):
            data = np.asarray(x.data)
            mask = np.asarray(x.mask())
            return data, mask
        return np.asarray(value_of(x)), None


@EVALUATORS.register("classification_error")
class ClassificationErrorEvaluator(Evaluator):
    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def eval_batch(self, output, label, weight=None):
        out, mask = self._to_np(output)
        lab, _ = self._to_np(label)
        if out.ndim == 3:  # sequence: flatten valid steps
            pred = out.argmax(-1)
            valid = mask > 0
            self.wrong += ((pred != lab[..., : pred.shape[1]]) & valid).sum()
            self.total += valid.sum()
        else:
            pred = out.argmax(-1)
            w = np.ones_like(pred, np.float64) if weight is None \
                else np.asarray(weight).reshape(-1)
            self.wrong += (w * (pred != lab.reshape(-1))).sum()
            self.total += w.sum()

    def get_value(self):
        return {"classification_error":
                float(self.wrong / max(self.total, 1.0))}


@EVALUATORS.register("sum")
class SumEvaluator(Evaluator):
    def start(self):
        self.sum = 0.0
        self.n = 0

    def eval_batch(self, output, label=None, weight=None):
        out, mask = self._to_np(output)
        if mask is not None:
            m = mask.reshape(mask.shape + (1,) * (out.ndim - 2))
            self.sum += (out * m).sum()
            self.n += int(mask.sum())
        else:
            self.sum += out.sum()
            self.n += out.shape[0]

    def get_value(self):
        return {"sum": float(self.sum), "mean": float(self.sum / max(self.n, 1))}


@EVALUATORS.register("column_sum")
class ColumnSumEvaluator(Evaluator):
    def start(self):
        self.sum = None
        self.n = 0

    def eval_batch(self, output, label=None, weight=None):
        out, _ = self._to_np(output)
        s = out.reshape(-1, out.shape[-1]).sum(0)
        self.sum = s if self.sum is None else self.sum + s
        self.n += out.reshape(-1, out.shape[-1]).shape[0]

    def get_value(self):
        if self.sum is None:
            return {"column_sum": []}
        return {"column_sum": (self.sum / max(self.n, 1)).tolist()}


@EVALUATORS.register("precision_recall")
class PrecisionRecallEvaluator(Evaluator):
    """Per-class (or binary w/ positive label) precision/recall/F1."""

    def start(self):
        self.tp = {}
        self.fp = {}
        self.fn = {}

    def eval_batch(self, output, label, weight=None):
        out, _ = self._to_np(output)
        lab, _ = self._to_np(label)
        pred = out.argmax(-1).reshape(-1)
        lab = lab.reshape(-1)[: pred.size]
        for p, l in zip(pred, lab):
            p, l = int(p), int(l)
            if p == l:
                self.tp[p] = self.tp.get(p, 0) + 1
            else:
                self.fp[p] = self.fp.get(p, 0) + 1
                self.fn[l] = self.fn.get(l, 0) + 1

    def get_value(self):
        classes = set(self.tp) | set(self.fp) | set(self.fn)
        precs, recs = [], []
        for c in classes:
            tp, fp, fn = self.tp.get(c, 0), self.fp.get(c, 0), self.fn.get(c, 0)
            precs.append(tp / max(tp + fp, 1))
            recs.append(tp / max(tp + fn, 1))
        p = float(np.mean(precs)) if precs else 0.0
        r = float(np.mean(recs)) if recs else 0.0
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"precision": p, "recall": r, "F1": f1}


@EVALUATORS.register("auc")
class AucEvaluator(Evaluator):
    """Binary AUC by rank statistic over accumulated scores."""

    def start(self):
        self.scores = []
        self.labels = []

    def eval_batch(self, output, label, weight=None):
        out, _ = self._to_np(output)
        lab, _ = self._to_np(label)
        score = out[:, -1] if out.ndim == 2 and out.shape[1] > 1 else out.reshape(-1)
        self.scores.append(score)
        self.labels.append(lab.reshape(-1)[: score.size])

    def get_value(self):
        if not self.scores:
            return {"auc": 0.5}
        s = np.concatenate(self.scores)
        y = np.concatenate(self.labels)
        order = np.argsort(s)
        ranks = np.empty_like(order, np.float64)
        ranks[order] = np.arange(1, len(s) + 1)
        npos = (y == 1).sum()
        nneg = (y == 0).sum()
        if npos == 0 or nneg == 0:
            return {"auc": 0.5}
        auc = (ranks[y == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)
        return {"auc": float(auc)}


@EVALUATORS.register("rankauc")
class RankAucEvaluator(AucEvaluator):
    def get_value(self):
        v = super().get_value()
        return {"rankauc": v["auc"]}


@EVALUATORS.register("pnpair")
class PnpairEvaluator(Evaluator):
    """Positive/negative pair ratio within query groups
    (``PnpairEvaluator``): inputs (score, label, query_id)."""

    def start(self):
        self.rows = []

    def eval_batch(self, output, label, weight=None, query_id=None):
        out, _ = self._to_np(output)
        lab, _ = self._to_np(label)
        qid = np.zeros(out.shape[0]) if query_id is None else \
            np.asarray(value_of(query_id)).reshape(-1)
        for s, l, q in zip(out.reshape(-1), lab.reshape(-1), qid):
            self.rows.append((q, l, s))

    def get_value(self):
        from collections import defaultdict

        groups = defaultdict(list)
        for q, l, s in self.rows:
            groups[q].append((l, s))
        pos, neg = 0.0, 0.0
        for items in groups.values():
            for i in range(len(items)):
                for j in range(i + 1, len(items)):
                    (l1, s1), (l2, s2) = items[i], items[j]
                    if l1 == l2:
                        continue
                    better = (s1 > s2) == (l1 > l2)
                    if s1 == s2:
                        pos += 0.5
                        neg += 0.5
                    elif better:
                        pos += 1
                    else:
                        neg += 1
        return {"pnpair": float(pos / max(neg, 1e-12)),
                "pairs": pos + neg}


@EVALUATORS.register("chunk")
class ChunkEvaluator(Evaluator):
    """Chunk F1 for sequence labeling with IOB/IOE schemes
    (``ChunkEvaluator.cpp``)."""

    def start(self):
        self.correct = 0
        self.output_chunks = 0
        self.label_chunks = 0

    def _extract(self, tags, scheme, num_chunk_types):
        chunks = []
        start = None
        cur_type = None
        for i, t in enumerate(list(tags) + [-1]):
            if scheme == "IOB":
                # tag = chunk_type * 2 + {0: B, 1: I}; last id = O
                if t == -1 or t == num_chunk_types * 2:
                    tag_type, pos = None, None
                else:
                    tag_type, pos = divmod(int(t), 2)
                if start is not None and (
                        pos == 0 or tag_type != cur_type or pos is None):
                    chunks.append((start, i - 1, cur_type))
                    start = None
                if pos == 0:
                    start, cur_type = i, tag_type
                elif pos == 1 and start is None:
                    start, cur_type = i, tag_type
            else:  # IOE
                if t == -1 or t == num_chunk_types * 2:
                    tag_type, pos = None, None
                else:
                    tag_type, pos = divmod(int(t), 2)
                if start is None and pos is not None:
                    start, cur_type = i, tag_type
                if start is not None and (pos is None or tag_type != cur_type):
                    chunks.append((start, i - 1, cur_type))
                    start = None
                elif start is not None and pos == 1:  # E ends chunk
                    chunks.append((start, i, cur_type))
                    start = None
        return set(chunks)

    def eval_batch(self, output, label, weight=None):
        scheme = self.kw.get("chunk_scheme", "IOB")
        nct = self.kw.get("num_chunk_types", 1)
        out, mask = self._to_np(output)
        lab, _ = self._to_np(label)
        pred = out.argmax(-1) if out.ndim == 3 else out
        for b in range(pred.shape[0]):
            n = int(mask[b].sum()) if mask is not None else pred.shape[1]
            pc = self._extract(pred[b, :n], scheme, nct)
            lc = self._extract(lab[b, :n], scheme, nct)
            self.correct += len(pc & lc)
            self.output_chunks += len(pc)
            self.label_chunks += len(lc)

    def get_value(self):
        p = self.correct / max(self.output_chunks, 1)
        r = self.correct / max(self.label_chunks, 1)
        return {"precision": p, "recall": r,
                "F1-score": 2 * p * r / max(p + r, 1e-12)}


@EVALUATORS.register("ctc_edit_distance")
class CTCErrorEvaluator(Evaluator):
    """Sequence error via edit distance after CTC collapse
    (``CTCErrorEvaluator.cpp``)."""

    def start(self):
        self.total_dist = 0.0
        self.total_len = 0

    @staticmethod
    def _collapse(ids, blank=0):
        out = []
        prev = None
        for t in ids:
            if t != prev and t != blank:
                out.append(int(t))
            prev = t
        return out

    @staticmethod
    def _edit_distance(a, b):
        dp = np.arange(len(b) + 1, dtype=np.int64)
        for i in range(1, len(a) + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, len(b) + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (a[i - 1] != b[j - 1]))
        return int(dp[-1])

    def eval_batch(self, output, label, weight=None):
        out, mask = self._to_np(output)
        lab_np, lab_mask = self._to_np(label)
        pred = out.argmax(-1)
        for b in range(pred.shape[0]):
            n = int(mask[b].sum()) if mask is not None else pred.shape[1]
            hyp = self._collapse(pred[b, :n])
            if lab_mask is not None:
                m = int(lab_mask[b].sum())
                ref = [int(x) for x in lab_np[b, :m]]
            else:
                ref = [int(x) for x in lab_np[b]]
            self.total_dist += self._edit_distance(hyp, ref)
            self.total_len += max(len(ref), 1)

    def get_value(self):
        return {"ctc_edit_distance":
                float(self.total_dist / max(self.total_len, 1))}


def create_evaluator(name: str, **kwargs) -> Evaluator:
    return EVALUATORS.create(name, **kwargs)


@EVALUATORS.register("detection_map")
class DetectionMAPEvaluator(Evaluator):
    """SSD mean-average-precision (``DetectionMAPEvaluator.cpp``): streams
    (score, TP/FP) pairs per class, AP by 11-point or natural integral.

    ``eval_batch(output, label)`` takes the ``detection_output`` layer's
    [B, K, 7] rows (image,class,score,xmin,ymin,xmax,ymax; image -1 =
    empty slot) and the padded GT SequenceBatch [B, G, 6]."""

    def __init__(self, overlap_threshold: float = 0.5,
                 background_id: int = 0, evaluate_difficult: bool = False,
                 ap_type: str = "11point", **kw):
        self.overlap_threshold = overlap_threshold
        self.background_id = background_id
        self.evaluate_difficult = evaluate_difficult
        self.ap_type = ap_type
        super().__init__(**kw)

    def start(self):
        self.score_tp = {}      # class -> list of (score, is_tp)
        self.num_gt = {}        # class -> count

    @staticmethod
    def _iou(a, b):
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(ix2 - ix1, 0.0) * max(iy2 - iy1, 0.0)
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def eval_batch(self, output, label, weight=None):
        det, _ = self._to_np(output)
        gt, gt_mask = self._to_np(label)
        B = gt.shape[0]
        for b in range(B):
            n = int(gt_mask[b].sum()) if gt_mask is not None else gt.shape[1]
            rows = gt[b, :n]
            for r in rows:
                c = int(r[0])
                difficult = len(r) > 5 and r[5] > 0.5
                if self.evaluate_difficult or not difficult:
                    self.num_gt[c] = self.num_gt.get(c, 0) + 1
            matched = [False] * n
            dets = det[b]
            dets = dets[dets[:, 0] >= 0]
            # evaluate detections best-score first (reference sorts)
            for d in dets[np.argsort(-dets[:, 2])]:
                c = int(d[1])
                if c == self.background_id:
                    continue
                best, best_i = 0.0, -1
                for i, r in enumerate(rows):
                    if int(r[0]) != c:
                        continue
                    ov = self._iou(d[3:7], r[1:5])
                    if ov > best:
                        best, best_i = ov, i
                tp = False
                if best > self.overlap_threshold and best_i >= 0:
                    difficult = len(rows[best_i]) > 5 and rows[best_i][5] > 0.5
                    if difficult and not self.evaluate_difficult:
                        continue   # reference skips difficult matches
                    if not matched[best_i]:
                        tp = True
                        matched[best_i] = True
                self.score_tp.setdefault(c, []).append((float(d[2]), tp))

    def _average_precision(self, pairs, n_gt):
        if not pairs or n_gt == 0:
            return 0.0
        pairs = sorted(pairs, key=lambda p: -p[0])
        tp = np.cumsum([1.0 if t else 0.0 for _, t in pairs])
        fp = np.cumsum([0.0 if t else 1.0 for _, t in pairs])
        recall = tp / n_gt
        precision = tp / np.maximum(tp + fp, 1e-12)
        if self.ap_type == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                mask = recall >= t
                ap += (precision[mask].max() if mask.any() else 0.0) / 11.0
            return float(ap)
        # natural integral
        ap, prev_r = 0.0, 0.0
        for r, p in zip(recall, precision):
            ap += p * (r - prev_r)
            prev_r = r
        return float(ap)

    def get_value(self):
        aps = [self._average_precision(self.score_tp.get(c, []), n)
               for c, n in self.num_gt.items() if n > 0]
        return {"detection_map": float(np.mean(aps) * 100) if aps else 0.0}


class _PrinterEvaluator(Evaluator):
    """Base for the printer family (``Evaluator.cpp`` toString
    evaluators): accumulates printable lines, logs at finish."""

    def start(self):
        self.lines = []

    def get_value(self):
        for line in self.lines:
            print(line)
        return {}


@EVALUATORS.register("value_printer")
class ValuePrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, output, label=None, weight=None):
        out, _ = self._to_np(output)
        self.lines.append(f"value: {np.array2string(out, threshold=64)}")


@EVALUATORS.register("gradient_printer")
class GradientPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, output, label=None, weight=None):
        out, _ = self._to_np(output)
        self.lines.append(f"gradient: {np.array2string(out, threshold=64)}")


@EVALUATORS.register("maxid_printer")
class MaxIdPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, output, label=None, weight=None):
        out, _ = self._to_np(output)
        ids = out.argmax(-1)
        self.lines.append(f"maxid: {np.array2string(ids, threshold=64)}")


@EVALUATORS.register("maxframe_printer")
class MaxFramePrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, output, label=None, weight=None):
        out, mask = self._to_np(output)
        frames = out.max(-1) if out.ndim > 2 else out
        self.lines.append(f"maxframe: {np.array2string(frames, threshold=64)}")


@EVALUATORS.register("seq_text_printer")
class SeqTextPrinterEvaluator(_PrinterEvaluator):
    """Prints id sequences, optionally mapped through a dict file
    (``--dict_file`` in the reference)."""

    def __init__(self, dict_file=None, **kw):
        self.id2word = None
        if dict_file:
            with open(dict_file) as f:
                self.id2word = [w.rstrip("\n") for w in f]
        super().__init__(**kw)

    def eval_batch(self, output, label=None, weight=None):
        out, mask = self._to_np(output)
        ids = out.argmax(-1) if out.ndim == 3 else out.astype(np.int64)
        for b in range(ids.shape[0]):
            n = int(mask[b].sum()) if mask is not None else ids.shape[1]
            toks = [int(t) for t in np.atleast_1d(ids[b])[:n]]
            if self.id2word:
                words = [self.id2word[t] if 0 <= t < len(self.id2word)
                         else "<unk>" for t in toks]
                self.lines.append(" ".join(words))
            else:
                self.lines.append(" ".join(map(str, toks)))


@EVALUATORS.register("classification_error_printer")
class ClassificationErrorPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, output, label, weight=None):
        out, _ = self._to_np(output)
        lab, _ = self._to_np(label)
        err = (out.argmax(-1) != lab.squeeze().astype(np.int64))
        self.lines.append(
            f"classification_error: {np.array2string(err.astype(np.float32))}")

"""Initializers as init-op emitters (``v2/framework/initializer.py``:
Constant/Uniform/Normal/Xavier/MSRA append ops to the startup program)."""

from __future__ import annotations

import math

import numpy as np


class Initializer:
    def __call__(self, var, block) -> None:
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var]},
                        attrs={"shape": list(var.shape),
                               "value": self.value, "dtype": var.dtype})


class UniformInitializer(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0, seed: int = 0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var]},
                        attrs={"shape": list(var.shape), "min": self.low,
                               "max": self.high, "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var]},
                        attrs={"shape": list(var.shape), "mean": self.loc,
                               "std": self.scale, "seed": self.seed})


def _fan(var):
    shape = var.shape
    if len(shape) < 2:
        return shape[0] if shape else 1, shape[0] if shape else 1
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    return shape[0] * receptive, shape[1] * receptive


class XavierInitializer(Initializer):
    def __init__(self, uniform: bool = True, fan_in=None, fan_out=None,
                 seed: int = 0):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = _fan(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform: bool = True, fan_in=None, seed: int = 0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            NormalInitializer(0.0, math.sqrt(2.0 / fi), self.seed)(var, block)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer

"""Streaming evaluators over Executor fetches
(``python/paddle/v2/framework/evaluator.py`` Accuracy accumulation)."""

from __future__ import annotations

import numpy as np

from . import layers


class Accuracy:
    """Build the per-batch accuracy node and accumulate host-side."""

    def __init__(self, input, label, k: int = 1, main_program=None,
                 **kw):
        self.acc = layers.accuracy(input, label, k=k,
                                   main_program=main_program)
        self.reset()

    def reset(self):
        self._correct = 0.0
        self._total = 0.0

    def metrics(self):
        return [self.acc]

    def update(self, acc_value, batch_size: int):
        self._correct += float(acc_value) * batch_size
        self._total += batch_size

    def eval(self) -> float:
        return self._correct / max(self._total, 1.0)

"""Op-emitting layer functions (``python/paddle/v2/framework/layers.py``):
fc, embedding, conv2d, pool2d, batch_norm, dropout, losses, StaticRNN…
Each appends ops to the current block and returns the output Variable.
Shapes use -1 for the batch dimension.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import ConfigError, enforce
from .layer_helper import LayerHelper
from .program import Program, Variable, default_main_program, unique_name


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0, main_program=None, **kw) -> Variable:
    prog = main_program or default_main_program()
    shape = tuple(shape)
    if not shape or shape[0] != -1:
        shape = (-1,) + shape
    return prog.global_block.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=True)


def fc(input, size: int, act: Optional[str] = None, name=None,
       num_flatten_dims: int = 1, param_attr=None, bias_attr=True,
       main_program=None, startup_program=None) -> Variable:
    helper = LayerHelper("fc", name=name, main_program=main_program,
                         startup_program=startup_program)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for i, x in enumerate(inputs):
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        w = helper.create_parameter(param_attr, shape=(in_dim, size),
                                    suffix=f"w_{i}" if i else "w")
        tmp = helper.create_tmp_variable(shape=x.shape[:num_flatten_dims]
                                         + (size,))
        helper.block.append_op(
            "mul", inputs={"X": [x], "Y": [w]}, outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims,
                   "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre = mul_results[0]
    else:
        pre = helper.create_tmp_variable(shape=mul_results[0].shape)
        helper.block.append_op("sum", inputs={"X": mul_results},
                               outputs={"Out": [pre]})
    if bias_attr:
        pre = helper.append_bias_op(
            pre, bias_attr=bias_attr if isinstance(bias_attr, dict)
            else None)
    return helper.append_activation(pre, act)


def embedding(input, size: Sequence[int], is_sparse: bool = False,
              param_attr=None, dtype="float32", name=None,
              main_program=None, startup_program=None) -> Variable:
    helper = LayerHelper("embedding", name=name, main_program=main_program,
                         startup_program=startup_program)
    w = helper.create_parameter(param_attr, shape=tuple(size), dtype=dtype,
                                suffix="w")
    out = helper.create_tmp_variable(
        dtype, shape=input.shape + (size[1],))
    helper.block.append_op("lookup_table", inputs={"W": [w],
                                                   "Ids": [input]},
                           outputs={"Out": [out]},
                           attrs={"is_sparse": is_sparse})
    return out


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,
           groups: int = 1, act=None, name=None, param_attr=None,
           bias_attr=True, main_program=None,
           startup_program=None) -> Variable:
    helper = LayerHelper("conv2d", name=name, main_program=main_program,
                         startup_program=startup_program)
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    n, c, h, w_sz = input.shape
    flt = helper.create_parameter(
        param_attr,
        shape=(num_filters, c // groups) + tuple(filter_size), suffix="w")
    oh = (h + 2 * padding[0] - filter_size[0]) // stride[0] + 1
    ow = (w_sz + 2 * padding[1] - filter_size[1]) // stride[1] + 1
    out = helper.create_tmp_variable(shape=(n, num_filters, oh, ow))
    helper.block.append_op(
        "conv2d", inputs={"Input": [input], "Filter": [flt]},
        outputs={"Output": [out]},
        attrs={"strides": list(stride), "paddings": list(padding),
               "groups": groups, "dilations": [1, 1]})
    if bias_attr:
        b = helper.create_parameter(None, shape=(num_filters,), suffix="b")
        tmp = helper.create_tmp_variable(shape=out.shape)
        helper.block.append_op("elementwise_add",
                               inputs={"X": [out], "Y": [b]},
                               outputs={"Out": [tmp]}, attrs={"axis": 1})
        out = tmp
    return helper.append_activation(out, act)


def pool2d(input, pool_size, pool_type: str = "max", pool_stride=None,
           pool_padding=0, global_pooling: bool = False, name=None,
           main_program=None, startup_program=None) -> Variable:
    helper = LayerHelper("pool2d", name=name, main_program=main_program,
                         startup_program=startup_program)
    if isinstance(pool_size, int):
        pool_size = (pool_size, pool_size)
    pool_stride = pool_stride or pool_size
    if isinstance(pool_stride, int):
        pool_stride = (pool_stride, pool_stride)
    if isinstance(pool_padding, int):
        pool_padding = (pool_padding, pool_padding)
    n, c, h, w = input.shape
    if global_pooling:
        oh = ow = 1
    else:
        oh = (h + 2 * pool_padding[0] - pool_size[0]) // pool_stride[0] + 1
        ow = (w + 2 * pool_padding[1] - pool_size[1]) // pool_stride[1] + 1
    out = helper.create_tmp_variable(shape=(n, c, oh, ow))
    helper.block.append_op(
        "pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": list(pool_size),
               "strides": list(pool_stride),
               "paddings": list(pool_padding),
               "global_pooling": global_pooling})
    return out


def batch_norm(input, act=None, is_test: bool = False, momentum=0.9,
               epsilon=1e-5, name=None, param_attr=None,
               main_program=None, startup_program=None) -> Variable:
    helper = LayerHelper("batch_norm", name=name,
                         main_program=main_program,
                         startup_program=startup_program)
    c = input.shape[1]
    from .initializer import ConstantInitializer
    scale = helper.create_parameter(param_attr, shape=(c,), suffix="scale",
                                    initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(None, shape=(c,), suffix="bias",
                                   initializer=ConstantInitializer(0.0))
    mean = helper.create_parameter(None, shape=(c,), suffix="mean",
                                   initializer=ConstantInitializer(0.0))
    var = helper.create_parameter(None, shape=(c,), suffix="variance",
                                  initializer=ConstantInitializer(1.0))
    mean.trainable = False
    var.trainable = False
    out = helper.create_tmp_variable(shape=input.shape)
    helper.block.append_op(
        "batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [var]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [var],
                 "SavedMean": [helper.create_tmp_variable(shape=(c,))],
                 "SavedVariance": [helper.create_tmp_variable(shape=(c,))]},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "is_test": is_test})
    return helper.append_activation(out, act)


def dropout(x, dropout_prob: float = 0.5, is_test: bool = False, name=None,
            main_program=None, startup_program=None) -> Variable:
    helper = LayerHelper("dropout", name=name, main_program=main_program,
                         startup_program=startup_program)
    out = helper.create_tmp_variable(shape=x.shape)
    mask = helper.create_tmp_variable(shape=x.shape)
    helper.block.append_op("dropout", inputs={"X": [x]},
                           outputs={"Out": [out], "Mask": [mask]},
                           attrs={"dropout_prob": dropout_prob,
                                  "is_test": is_test})
    return out


def cross_entropy(input, label, soft_label: bool = False, name=None,
                  main_program=None, **kw) -> Variable:
    helper = LayerHelper("cross_entropy", name=name,
                         main_program=main_program)
    out = helper.create_tmp_variable(shape=(input.shape[0], 1))
    helper.block.append_op("cross_entropy",
                           inputs={"X": [input], "Label": [label]},
                           outputs={"Y": [out]},
                           attrs={"soft_label": soft_label})
    return out


def softmax(input, name=None, main_program=None, **kw) -> Variable:
    helper = LayerHelper("softmax", name=name, main_program=main_program)
    out = helper.create_tmp_variable(shape=input.shape)
    helper.block.append_op("softmax", inputs={"X": [input]},
                           outputs={"Out": [out]})
    return out


def square_error_cost(input, label, name=None, main_program=None,
                      **kw) -> Variable:
    helper = LayerHelper("square_error_cost", name=name,
                         main_program=main_program)
    minus_out = helper.create_tmp_variable(shape=input.shape)
    helper.block.append_op("elementwise_sub",
                           inputs={"X": [input], "Y": [label]},
                           outputs={"Out": [minus_out]})
    out = helper.create_tmp_variable(shape=input.shape)
    helper.block.append_op("square", inputs={"X": [minus_out]},
                           outputs={"Out": [out]})
    return out


def mean(x, name=None, main_program=None, **kw) -> Variable:
    helper = LayerHelper("mean", name=name, main_program=main_program)
    out = helper.create_tmp_variable(shape=())
    helper.block.append_op("mean", inputs={"X": [x]},
                           outputs={"Out": [out]})
    return out


def accuracy(input, label, k: int = 1, name=None, main_program=None,
             **kw) -> Variable:
    helper = LayerHelper("accuracy", name=name, main_program=main_program)
    acc = helper.create_tmp_variable(shape=())
    correct = helper.create_tmp_variable(shape=())
    total = helper.create_tmp_variable(shape=())
    helper.block.append_op("accuracy",
                           inputs={"Out": [input], "Label": [label]},
                           outputs={"Accuracy": [acc],
                                    "Correct": [correct],
                                    "Total": [total]}, attrs={"k": k})
    return acc


def concat(input: List[Variable], axis: int = 1, name=None,
           main_program=None, **kw) -> Variable:
    helper = LayerHelper("concat", name=name, main_program=main_program)
    shape = list(input[0].shape)
    shape[axis] = sum(v.shape[axis] for v in input)
    out = helper.create_tmp_variable(shape=tuple(shape))
    helper.block.append_op("concat", inputs={"X": list(input)},
                           outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input: List[Variable], name=None, main_program=None,
         **kw) -> Variable:
    helper = LayerHelper("sum", name=name, main_program=main_program)
    out = helper.create_tmp_variable(shape=input[0].shape)
    helper.block.append_op("sum", inputs={"X": list(input)},
                           outputs={"Out": [out]})
    return out


def elementwise_add(x, y, axis: int = -1, act=None, name=None,
                    main_program=None, **kw) -> Variable:
    helper = LayerHelper("elementwise_add", name=name,
                         main_program=main_program)
    out = helper.create_tmp_variable(shape=x.shape)
    helper.block.append_op("elementwise_add",
                           inputs={"X": [x], "Y": [y]},
                           outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out, act)


def scale(x, scale_val: float = 1.0, bias: float = 0.0, name=None,
          main_program=None, **kw) -> Variable:
    helper = LayerHelper("scale", name=name, main_program=main_program)
    out = helper.create_tmp_variable(shape=x.shape)
    helper.block.append_op("scale", inputs={"X": [x]},
                           outputs={"Out": [out]},
                           attrs={"scale": scale_val, "bias": bias})
    return out


def reshape(x, shape: Sequence[int], name=None, main_program=None,
            **kw) -> Variable:
    helper = LayerHelper("reshape", name=name, main_program=main_program)
    out = helper.create_tmp_variable(shape=tuple(shape))
    helper.block.append_op("reshape", inputs={"X": [x]},
                           outputs={"Out": [out]},
                           attrs={"shape": list(shape)})
    return out


def transpose(x, perm: Sequence[int], name=None, main_program=None,
              **kw) -> Variable:
    helper = LayerHelper("transpose", name=name, main_program=main_program)
    out = helper.create_tmp_variable(
        shape=tuple(x.shape[i] for i in perm))
    helper.block.append_op("transpose", inputs={"X": [x]},
                           outputs={"Out": [out]},
                           attrs={"axis": list(perm)})
    return out


def sequence_pool(input, pool_type: str = "AVERAGE", name=None,
                  main_program=None, **kw) -> Variable:
    helper = LayerHelper("sequence_pool", name=name,
                         main_program=main_program)
    out = helper.create_tmp_variable(shape=(input.shape[0],
                                            input.shape[-1]))
    helper.block.append_op("sequence_pool", inputs={"X": [input]},
                           outputs={"Out": [out]},
                           attrs={"pooltype": pool_type})
    return out


def sequence_conv(input, num_filters: int, filter_size: int = 3,
                  filter_stride: int = 1, act=None, padding=None,
                  name=None, param_attr=None, bias_attr=True,
                  main_program=None, startup_program=None) -> Variable:
    helper = LayerHelper("sequence_conv", name=name,
                         main_program=main_program,
                         startup_program=startup_program)
    in_dim = input.shape[-1]
    flt = helper.create_parameter(
        param_attr, shape=(filter_size * in_dim, num_filters), suffix="w")
    out = helper.create_tmp_variable(shape=input.shape[:-1]
                                     + (num_filters,))
    helper.block.append_op(
        "sequence_conv", inputs={"X": [input], "Filter": [flt]},
        outputs={"Out": [out]},
        attrs={"contextStart": -int(filter_size // 2),
               "contextLength": filter_size,
               "contextStride": filter_stride})
    if bias_attr:
        out = helper.append_bias_op(out)
    return helper.append_activation(out, act)


def lstm(input, size: int, is_reverse: bool = False, name=None,
         param_attr=None, bias_attr=True, gate_activation="sigmoid",
         cell_activation="tanh", main_program=None,
         startup_program=None):
    """Full-sequence LSTM op (``paddle/operators/lstm_op.cc``): input is
    the 4H projection [B, T, 4H]; returns (hidden, cell) LoD outputs."""
    helper = LayerHelper("lstm", name=name, main_program=main_program,
                         startup_program=startup_program)
    w = helper.create_parameter(param_attr, shape=(size, 4 * size),
                                suffix="w")
    inputs = {"Input": [input], "Weight": [w]}
    if bias_attr:
        b = helper.create_parameter(None, shape=(4 * size,), suffix="b")
        inputs["Bias"] = [b]
    hidden = helper.create_tmp_variable(shape=input.shape[:-1] + (size,))
    cell = helper.create_tmp_variable(shape=input.shape[:-1] + (size,))
    bg = helper.create_tmp_variable(shape=input.shape)
    bc = helper.create_tmp_variable(shape=input.shape)
    helper.block.append_op(
        "lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell], "BatchGate": [bg],
                 "BatchCellPreAct": [bc]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation})
    return hidden, cell


def cast(x, dtype: str, name=None, main_program=None, **kw) -> Variable:
    helper = LayerHelper("cast", name=name, main_program=main_program)
    out = helper.create_tmp_variable(dtype=dtype, shape=x.shape)
    helper.block.append_op("cast", inputs={"X": [x]},
                           outputs={"Out": [out]},
                           attrs={"dtype": dtype})
    return out


def topk(input, k: int = 1, name=None, main_program=None, **kw):
    helper = LayerHelper("top_k", name=name, main_program=main_program)
    vals = helper.create_tmp_variable(shape=input.shape[:-1] + (k,))
    idx = helper.create_tmp_variable(dtype="int32",
                                     shape=input.shape[:-1] + (k,))
    helper.block.append_op("top_k", inputs={"X": [input]},
                           outputs={"Out": [vals], "Indices": [idx]},
                           attrs={"k": k})
    return vals, idx


class StaticRNN:
    """Static (padded) RNN builder over a sub-block
    (``python/paddle/v2/framework/layers.py`` StaticRNN → recurrent op);
    lowered by the Executor to ``lax.scan``."""

    def __init__(self, name=None, main_program=None):
        self.helper = LayerHelper("static_rnn", name=name,
                                  main_program=main_program)
        self.prog = self.helper.main_program
        self.sub_block = None
        self.seq_inputs: List[Variable] = []     # outer sequence vars
        self.inner_inputs: List[Variable] = []   # per-step views
        self.memories: List[tuple] = []          # (init, inner_mem, state)
        self.outputs: List[tuple] = []           # (inner, outer)
        self._entered = False

    def step(self):
        rnn = self

        class _Guard:
            def __enter__(self):
                rnn.sub_block = rnn.prog.create_block()
                rnn.prog._current = rnn.sub_block.idx
                return rnn

            def __exit__(self, *a):
                rnn.prog._current = rnn.sub_block.parent_idx
                rnn._complete()
                return False

        return _Guard()

    def step_input(self, x: Variable) -> Variable:
        self.seq_inputs.append(x)
        inner = self.sub_block.create_var(
            name=unique_name("rnn_step_in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self.inner_inputs.append(inner)
        return inner

    def memory(self, init: Optional[Variable] = None, shape=None,
               batch_ref: Optional[Variable] = None,
               init_value: float = 0.0) -> Variable:
        if init is None:
            enforce(batch_ref is not None or shape is not None,
                    "memory needs init or shape/batch_ref")
            b = self.prog.blocks[self.sub_block.parent_idx]
            init = b.create_var(name=unique_name("rnn_mem_init"),
                                shape=tuple(shape), dtype="float32")
            with self.prog.block_guard(b):
                b.append_op("fill_constant_batch_size_like",
                            inputs={"Input": [batch_ref or
                                              self.seq_inputs[0]]},
                            outputs={"Out": [init]},
                            attrs={"shape": [s if s != -1 else 1
                                             for s in init.shape],
                                   "value": init_value})
        mem = self.sub_block.create_var(name=unique_name("rnn_mem"),
                                        shape=init.shape,
                                        dtype=init.dtype)
        self.memories.append([init, mem, None])
        return mem

    def update_memory(self, mem: Variable, new: Variable) -> None:
        for rec in self.memories:
            if rec[1] is mem:
                rec[2] = new
                return
        raise ConfigError("update_memory on unknown memory")

    def output(self, *outputs: Variable) -> None:
        for o in outputs:
            outer = self.prog.blocks[self.sub_block.parent_idx].create_var(
                name=unique_name("rnn_out"),
                shape=(o.shape[0], -1) + tuple(o.shape[1:]),
                dtype=o.dtype)
            self.outputs.append((o, outer))

    def _complete(self):
        for rec in self.memories:
            enforce(rec[2] is not None,
                    "every memory needs update_memory before step ends")
        parent = self.prog.blocks[self.sub_block.parent_idx]
        parent.append_op(
            "recurrent",
            inputs={"inputs": self.seq_inputs,
                    "initial_states": [m[0] for m in self.memories]},
            outputs={"outputs": [o for _, o in self.outputs]},
            attrs={"sub_block": self.sub_block.idx,
                   "inner_inputs": [v.name for v in self.inner_inputs],
                   "ex_states": [m[1].name for m in self.memories],
                   "states": [m[2].name for m in self.memories],
                   "inner_outputs": [o.name for o, _ in self.outputs]})

    def __call__(self):
        outs = [o for _, o in self.outputs]
        return outs[0] if len(outs) == 1 else outs

"""Program IR: Program / Block / Operator / Variable.

Mirrors ``paddle/framework/framework.proto`` (``OpDesc:33``, ``VarDesc:112``,
``BlockDesc:127``, ``ProgramDesc:137``) and the Python wrappers in
``python/paddle/v2/framework/framework.py`` — but as plain dataclasses: the
IR never crosses a language boundary here, the Executor consumes it directly.

Blocks nest (``parent_idx``) exactly like the reference so control-flow ops
(recurrent, cond, while) own sub-blocks; the Executor lowers a sub-block into
the body function of ``lax.scan`` / ``lax.cond`` / ``lax.while_loop``.
"""

from __future__ import annotations

import contextlib
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import ConfigError, enforce

_name_counter = itertools.count()


def unique_name(prefix: str) -> str:
    return f"{prefix}_{next(_name_counter)}"


@dataclass
class Variable:
    """``VarDesc`` equivalent. ``persistable`` vars live in the Scope across
    runs (parameters, optimizer state); non-persistable vars are SSA values
    inside the traced computation."""

    name: str
    shape: tuple = ()
    dtype: str = "float32"
    persistable: bool = False
    lod_level: int = 0           # sequence nesting (LoD), kept for parity
    initializer: Optional[Dict[str, Any]] = None
    trainable: bool = True
    optimize_attr: Dict[str, Any] = field(default_factory=dict)
    regularizer: Optional[Any] = None
    stop_gradient: bool = False
    block: Optional["Block"] = None

    def __repr__(self):
        return f"Var({self.name}, {self.shape}, {self.dtype})"


class Parameter(Variable):
    """Persistable + trainable variable (``framework.py`` Parameter)."""

    def __init__(self, name, shape, dtype="float32", **kw):
        super().__init__(name=name, shape=tuple(shape), dtype=dtype,
                         persistable=True, **kw)


@dataclass
class Operator:
    """``OpDesc`` equivalent: type + name-keyed input/output var lists."""

    type: str
    inputs: Dict[str, List[str]]
    outputs: Dict[str, List[str]]
    attrs: Dict[str, Any] = field(default_factory=dict)

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def __repr__(self):
        return f"Op({self.type})"


class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent(self) -> Optional["Block"]:
        return (self.program.blocks[self.parent_idx]
                if self.parent_idx >= 0 else None)

    def var(self, name: str) -> Variable:
        """Lookup through parent chain (scope nesting, ``scope.h:38``)."""
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise ConfigError(f"variable {name!r} not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except ConfigError:
            return False

    def create_var(self, name: Optional[str] = None, **kw) -> Variable:
        name = name or unique_name("tmp")
        v = Variable(name=name, block=self, **kw)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype="float32",
                         **kw) -> Parameter:
        p = Parameter(name, shape, dtype, **kw)
        p.block = self
        self.vars[name] = p
        # parameters are global — also visible from the root block
        self.program.blocks[0].vars.setdefault(name, p)
        return p

    def append_op(self, type: str, inputs: Dict[str, Sequence] = None,
                  outputs: Dict[str, Sequence] = None,
                  attrs: Dict[str, Any] = None) -> Operator:
        def names(d):
            out = {}
            for k, vs in (d or {}).items():
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                out[k] = [v.name if isinstance(v, Variable) else v
                          for v in vs]
            return out

        op = Operator(type=type, inputs=names(inputs), outputs=names(outputs),
                      attrs=dict(attrs or {}))
        self.ops.append(op)
        return op

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]


class Program:
    """``ProgramDesc``: a list of blocks; block 0 is global."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current = 0
        self.seed = 0

    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    @property
    def current_block(self) -> Block:
        return self.blocks[self._current]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        b = Block(self, len(self.blocks),
                  self._current if parent_idx is None else parent_idx)
        self.blocks.append(b)
        return b

    @contextlib.contextmanager
    def block_guard(self, block: Block):
        old = self._current
        self._current = block.idx
        try:
            yield block
        finally:
            self._current = old

    def parameters(self) -> List[Parameter]:
        seen, out = set(), []
        for b in self.blocks:
            for v in b.vars.values():
                if isinstance(v, Parameter) and v.name not in seen:
                    seen.add(v.name)
                    out.append(v)
        return out

    def prune(self, targets: Sequence[str]) -> "Program":
        """Dead-op elimination (``paddle/framework/prune.cc``): keep only ops
        in block 0 whose outputs (transitively) reach ``targets``."""
        needed = set(targets)
        kept: List[Operator] = []
        for op in reversed(self.global_block.ops):
            if any(o in needed for outs in op.outputs.values()
                   for o in outs):
                kept.append(op)
                for ins in op.inputs.values():
                    needed.update(ins)
        pruned = Program()
        pruned.blocks = list(self.blocks)
        import copy
        pruned.blocks[0] = copy.copy(self.global_block)
        pruned.blocks[0].program = pruned
        pruned.blocks[0].ops = list(reversed(kept))
        return pruned


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main: Program, startup: Optional[Program] = None):
    global _main_program, _startup_program
    old_m, old_s = _main_program, _startup_program
    _main_program = main
    if startup is not None:
        _startup_program = startup
    try:
        yield
    finally:
        _main_program, _startup_program = old_m, old_s

"""LayerHelper: shared parameter/bias/activation plumbing for layer
functions (``python/paddle/v2/framework/layer_helper.py``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from .initializer import ConstantInitializer, XavierInitializer
from .program import (Program, Variable, default_main_program,
                      default_startup_program, unique_name)


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        self.name = kwargs.get("name") or unique_name(layer_type)

    @property
    def main_program(self) -> Program:
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self) -> Program:
        return self.kwargs.get("startup_program") or \
            default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block

    def create_parameter(self, attr: Optional[Dict[str, Any]], shape,
                         dtype="float32", suffix="w",
                         initializer=None) -> Variable:
        attr = dict(attr or {})
        name = attr.get("name") or f"{self.name}.{suffix}"
        init = initializer or attr.get("initializer") or \
            (ConstantInitializer(0.0) if suffix == "b"
             else XavierInitializer())
        p = self.block.create_parameter(name, shape, dtype)
        p.optimize_attr = {"learning_rate": attr.get("learning_rate", 1.0)}
        p.regularizer = attr.get("regularizer")
        # startup program owns initialization (reference behavior)
        sp = self.startup_program.global_block
        sv = sp.create_parameter(name, shape, dtype)
        init(sv, sp)
        return p

    def create_tmp_variable(self, dtype="float32", shape=()) -> Variable:
        return self.block.create_var(
            name=unique_name(f"{self.name}.tmp"), dtype=dtype, shape=shape)

    def append_bias_op(self, input_var: Variable, dim_start=1,
                       bias_attr=None) -> Variable:
        size = input_var.shape[-1] if input_var.shape else 0
        b = self.create_parameter(bias_attr if isinstance(bias_attr, dict)
                                  else None,
                                  shape=(size,), suffix="b",
                                  initializer=ConstantInitializer(0.0))
        out = self.create_tmp_variable(input_var.dtype, input_var.shape)
        self.block.append_op("elementwise_add",
                             inputs={"X": [input_var], "Y": [b]},
                             outputs={"Out": [out]})
        return out

    def append_activation(self, input_var: Variable,
                          act: Optional[str]) -> Variable:
        if not act:
            return input_var
        out = self.create_tmp_variable(input_var.dtype, input_var.shape)
        self.block.append_op(act, inputs={"X": [input_var]},
                             outputs={"Out": [out]})
        return out

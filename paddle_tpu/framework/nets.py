"""Composite networks (``python/paddle/v2/framework/nets.py``)."""

from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, pool_type="max",
                         main_program=None, startup_program=None):
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, act=act,
                             main_program=main_program,
                             startup_program=startup_program)
    return layers.pool2d(conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride,
                         main_program=main_program,
                         startup_program=startup_program)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=None,
                   pool_stride=1, pool_type="max", main_program=None,
                   startup_program=None):
    tmp = input
    if isinstance(conv_padding, int):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if conv_batchnorm_drop_rate is None:
        conv_batchnorm_drop_rate = [0.0] * len(conv_num_filter)
    if isinstance(conv_with_batchnorm, bool):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = layers.conv2d(tmp, num_filters=nf,
                            filter_size=conv_filter_size,
                            padding=conv_padding[i],
                            act=None if conv_with_batchnorm[i]
                            else conv_act,
                            main_program=main_program,
                            startup_program=startup_program)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act,
                                    main_program=main_program,
                                    startup_program=startup_program)
            if conv_batchnorm_drop_rate[i] > 0:
                tmp = layers.dropout(tmp,
                                     conv_batchnorm_drop_rate[i],
                                     main_program=main_program)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride,
                         main_program=main_program)


def sequence_conv_pool(input, num_filters, filter_size, act="tanh",
                       pool_type="MAX", main_program=None,
                       startup_program=None):
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size, act=act,
                                    main_program=main_program,
                                    startup_program=startup_program)
    return layers.sequence_pool(conv_out, pool_type=pool_type,
                                main_program=main_program)

"""Weight-decay regularizers appended as ops on the gradient
(``python/paddle/v2/framework/regularizer.py``)."""

from __future__ import annotations

from .program import Program, default_main_program


class WeightDecayRegularizer:
    def append(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append(self, param, grad, block):
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self.coeff})
        block.append_op("sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [grad]})


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append(self, param, grad, block):
        sign = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self.coeff})
        block.append_op("sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [grad]})


def append_regularization_ops(params_grads, regularization=None,
                              program=None):
    program = program or default_main_program()
    block = program.global_block
    for p, g in params_grads:
        reg = p.regularizer or regularization
        if reg is not None:
            reg.append(p, g, block)
    return params_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer

"""Executor: lower a Program block to ONE jitted XLA computation.

The reference ``Executor::Run`` (``paddle/framework/executor.cc:81``)
creates variables then interprets ops one-by-one, each dispatching an
OpKernel by {DataType, Place} (``operator.h:349``).  TPU-native redesign:
the whole block is **traced once** into a pure function

    (persistables, feeds, rng) -> (fetches, updated persistables)

and jit-compiled; XLA fuses across op boundaries, optimizer ops update
parameters in-place via donated buffers, and there is no per-op dispatch at
runtime.  Compiled programs are cached by (block, feed shapes, mode).

Control flow recurses into sub-blocks as the reference does
(``RecurrentOp``/``CondOp`` own child scopes, ``operators/recurrent_op.cc``)
but lowers them to ``lax.scan`` / ``lax.cond`` so generation/training stay
inside the single compiled computation.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.sequence import SequenceBatch, value_of
from ..utils import ConfigError, enforce, get_logger
from .ops import OPS, OpContext
from .program import Block, Operator, Program, Variable

log = get_logger("executor")

# ops the tracer handles itself (not in the OPS registry)
_CONTROL = {"feed", "fetch", "recurrent", "dynamic_recurrent", "cond",
            "rnn_memory_helper", "rnn_memory_helper_grad",
            "save", "load", "backward",
            "ncclInit", "ncclAllReduce", "ncclBcast", "ncclReduce"}


class Scope:
    """name → value store for persistable variables (``scope.h:38``)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.vars: Dict[str, Any] = {}
        self.parent = parent

    def find(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def set(self, name: str, value) -> None:
        self.vars[name] = value

    def has(self, name: str) -> bool:
        return self.find(name) is not None


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class _Trace:
    """One block-lowering pass: symbolic values flow through op adapters."""

    def __init__(self, block: Block, ctx: OpContext, values: Dict[str, Any]):
        self.block = block
        self.ctx = ctx
        self.values = values
        self.written_persistables: Dict[str, Any] = {}

    def get(self, name: str):
        if name in self.values:
            return self.values[name]
        raise ConfigError(
            f"op input {name!r} has no value (missing feed or init?)")

    def run_op(self, op: Operator) -> None:
        if op.type in _CONTROL:
            self._run_control(op)
            return
        fn = OPS.get(op.type)
        if fn is None:
            raise ConfigError(f"unregistered op type {op.type!r}")
        ins = {slot: [self.get(n) for n in names]
               for slot, names in op.inputs.items() if names}
        outs = fn(self.ctx, ins, op.attrs)
        for slot, names in op.outputs.items():
            vals = outs.get(slot, [])
            for i, name in enumerate(names):
                if i < len(vals) and name:
                    self._write(name, vals[i])

    def _write(self, name: str, value) -> None:
        self.values[name] = value
        try:
            var = self.block.var(name)
            if var.persistable:
                self.written_persistables[name] = value
        except ConfigError:
            pass

    def _run_control(self, op: Operator) -> None:
        t = op.type
        if t in ("rnn_memory_helper", "rnn_memory_helper_grad"):
            src = op.input("X")[0]
            self._write(op.output("Out")[0], self.get(src))
        elif t in ("ncclInit",):
            pass  # device mesh replaces communicator bootstrap
        elif t in ("ncclAllReduce", "ncclReduce", "ncclBcast"):
            # inside pjit/shard_map the partitioner inserts collectives;
            # a standalone op is the identity on a replicated value
            for slot_in, slot_out in (("X", "Out"),):
                names_in = op.input(slot_in)
                names_out = op.output(slot_out)
                for ni, no in zip(names_in, names_out):
                    self._write(no, self.get(ni))
        elif t == "recurrent":
            self._run_recurrent(op)
        elif t == "cond":
            self._run_cond(op)
        elif t in ("feed", "fetch", "save", "load", "backward"):
            raise ConfigError(f"{t} op must be handled by Executor.run")
        else:
            raise ConfigError(f"unhandled control op {t!r}")

    # ---- recurrent: sub-block per timestep lowered to lax.scan ----------
    def _run_recurrent(self, op: Operator) -> None:
        """StaticRNN semantics (``operators/recurrent_op.cc``): sequence
        inputs [B, T, D] are scanned over T; memories (ex-state → state)
        carry across steps; step outputs stack back to [B, T, D]."""
        sub = self.block.program.blocks[op.attrs["sub_block"]]
        seq_ins = op.input("inputs")          # outer seq vars
        inner_ins = op.attrs["inner_inputs"]  # inner per-step names
        init_states = op.input("initial_states")
        state_names = op.attrs["states"]          # inner state (output) name
        ex_state_names = op.attrs["ex_states"]    # inner memory (input) name
        out_names = op.output("outputs")
        inner_outs = op.attrs["inner_outputs"]

        seqs = [self.get(n) for n in seq_ins]
        lengths = next((s.length for s in seqs
                        if isinstance(s, SequenceBatch)), None)
        xs = [value_of(s) for s in seqs]      # [B, T, D]
        carries = [self.get(n) for n in init_states]
        captured = dict(self.values)          # outer values visible inside

        ctx = self.ctx

        def step(carry, xt):
            vals = dict(captured)
            for name, v in zip(ex_state_names, carry):
                vals[name] = v
            for name, v in zip(inner_ins, xt):
                vals[name] = v
            tr = _Trace(sub, ctx, vals)
            for sop in sub.ops:
                tr.run_op(sop)
            new_carry = [vals[n] for n in state_names]
            outs = [vals[n] for n in inner_outs]
            return new_carry, outs

        # scan over time: move T to axis 0
        xs_t = [jnp.moveaxis(x, 1, 0) for x in xs]
        final, stacked = jax.lax.scan(step, carries, xs_t)
        for name, y in zip(out_names, stacked):
            y = jnp.moveaxis(y, 0, 1)         # [B, T, D]
            self._write(name, SequenceBatch(y, lengths)
                        if lengths is not None else y)

    def _run_cond(self, op: Operator) -> None:
        """``cond_op.cc``: pred selects between two sub-blocks with the
        same output signature — lowered to ``lax.cond``."""
        pred = value_of(self.get(op.input("Cond")[0]))
        tb = self.block.program.blocks[op.attrs["true_block"]]
        fb = self.block.program.blocks[op.attrs["false_block"]]
        out_names = op.output("Out")
        captured = dict(self.values)
        ctx = self.ctx

        def branch(blk):
            def f(_):
                vals = dict(captured)
                tr = _Trace(blk, ctx, vals)
                for sop in blk.ops:
                    tr.run_op(sop)
                return tuple(vals[n] for n in out_names)
            return f

        outs = jax.lax.cond(jnp.all(pred > 0), branch(tb), branch(fb),
                            operand=None)
        for name, v in zip(out_names, outs):
            self._write(name, v)


class Executor:
    """``Executor(places)`` equivalent; one jitted computation per
    (block, feed-signature, mode)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Tuple, Any] = {}
        self._run_count = 0

    # ------------------------------------------------------------- run
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Sequence = (),
            scope: Optional[Scope] = None,
            is_test: bool = False,
            seed: int = 0,
            return_numpy: bool = True) -> List[Any]:
        from .program import default_main_program
        program = program or default_main_program()
        scope = scope or global_scope()
        feed = dict(feed or {})
        block = program.global_block

        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in fetch_list]

        # host-side load ops first, save ops after compute
        compute_ops, save_ops = [], []
        for op in block.ops:
            if op.type == "load":
                _host_load(op, scope)
            elif op.type == "save":
                save_ops.append(op)
            elif op.type == "feed":
                pass  # feed dict supersedes feed ops
            elif op.type == "fetch":
                for n in op.input("X"):
                    if n not in fetch_names:
                        fetch_names.append(n)
            else:
                compute_ops.append(op)

        # persistables the compute reads or writes
        persist_in: Dict[str, Any] = {}
        for b in program.blocks:
            for name, var in b.vars.items():
                if var.persistable and scope.has(name):
                    persist_in[name] = scope.find(name)

        feed_vals = {k: _to_device(v) for k, v in feed.items()}
        key = self._sig(program, compute_ops, feed_vals, is_test,
                        tuple(fetch_names), tuple(sorted(persist_in)))
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(program, compute_ops, fetch_names, is_test)
            self._cache[key] = fn

        # fold the run counter in so dropout/random ops draw fresh values
        # every batch even with the default seed
        self._run_count += 1
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), self._run_count)
        fetches, written = fn(persist_in, feed_vals, rng)
        for name, v in written.items():
            scope.set(name, v)
        for op in save_ops:
            _host_save(op, scope)
        if return_numpy:
            return [_to_numpy(f) for f in fetches]
        return list(fetches)

    # ----------------------------------------------------------- build
    def _build(self, program: Program, ops: List[Operator],
               fetch_names: List[str], is_test: bool):
        block = program.global_block

        bi = next((i for i, op in enumerate(ops)
                   if op.type == "backward"), None)

        def fn(persist, feed_vals, rng):
            ctx = OpContext(is_test=is_test, rng=rng)
            init: Dict[str, Any] = {}
            init.update(persist)
            init.update(feed_vals)
            if bi is None:
                values = dict(init)
                tr = _Trace(block, ctx, values)
                for op in ops:
                    tr.run_op(op)
            else:
                bop = ops[bi]
                pnames = [n for n in bop.attrs["parameter_names"]
                          if n in init]
                loss_name = bop.attrs["loss"]

                def loss_fn(pvals):
                    v = dict(init)
                    v.update(pvals)
                    tr_in = _Trace(block, ctx, v)
                    for op in ops[:bi]:
                        tr_in.run_op(op)
                    loss = jnp.sum(value_of(v[loss_name]))
                    return loss, (v, tr_in.written_persistables)

                (_, (values, wrote)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)({n: init[n] for n in pnames})
                for n, g in grads.items():
                    values[n + "@GRAD"] = g
                tr = _Trace(block, ctx, values)
                tr.written_persistables.update(wrote)
                for op in ops[bi + 1:]:
                    tr.run_op(op)
            fetches = tuple(values[n] for n in fetch_names)
            return fetches, tr.written_persistables

        return jax.jit(fn)

    @staticmethod
    def _sig(program, ops, feed_vals, is_test, fetch_names, persist_names):
        shapes = tuple(sorted(
            (k, _shape_sig(v)) for k, v in feed_vals.items()))
        return (id(program), len(ops), shapes, is_test, fetch_names,
                persist_names)


def _shape_sig(v) -> Tuple:
    if isinstance(v, SequenceBatch):
        return ("seq", tuple(v.data.shape), str(v.data.dtype))
    arr = jnp.asarray(v)
    return (tuple(arr.shape), str(arr.dtype))


def _to_device(v):
    if isinstance(v, SequenceBatch):
        return v
    return jnp.asarray(v)


def _to_numpy(v):
    if isinstance(v, SequenceBatch):
        return np.asarray(v.data)
    return np.asarray(v)


def _host_save(op: Operator, scope: Scope) -> None:
    # save_op.cc equivalent; npz (not pickle) so loading an untrusted
    # checkpoint cannot execute code
    path = op.attrs["file_path"]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    data = {n: np.asarray(value_of(scope.find(n)))
            for n in op.input("X")}
    with open(path, "wb") as f:
        np.savez(f, **data)


def _host_load(op: Operator, scope: Scope) -> None:
    path = op.attrs["file_path"]
    with np.load(path) as data:
        for n in op.output("Out"):
            enforce(n in data, f"checkpoint {path} lacks variable {n!r}")
            scope.set(n, jnp.asarray(data[n]))

"""Optimizers building update ops (``python/paddle/v2/framework/optimizer.py``):
``minimize`` = append_backward + per-parameter accumulator creation +
optimizer ops — all of which land in the same single-XLA-computation block.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..utils import enforce
from .backward import append_backward
from .initializer import ConstantInitializer
from .program import Program, Variable, default_main_program, \
    default_startup_program, unique_name
from .regularizer import append_regularization_ops


class Optimizer:
    op_type = ""

    def __init__(self, learning_rate: float = 0.01,
                 global_step: Optional[Variable] = None,
                 regularization=None):
        self.learning_rate = learning_rate
        self.global_step = global_step
        self.regularization = regularization
        self._accumulators: Dict[str, Dict[str, Variable]] = {}

    # -------------------------------------------------------- helpers
    def _lr_var(self, block) -> Variable:
        name = unique_name("learning_rate")
        v = block.create_parameter(name, shape=(), dtype="float32")
        v.trainable = False
        sp = default_startup_program().global_block
        sv = sp.create_parameter(name, shape=(), dtype="float32")
        ConstantInitializer(self.learning_rate)(sv, sp)
        return v

    def _acc(self, block, param: Variable, name: str,
             fill: float = 0.0, shape=None) -> Variable:
        key = f"{param.name}_{name}"
        if key in self._accumulators:
            return self._accumulators[key]
        v = block.create_parameter(key, shape=shape or param.shape,
                                   dtype=param.dtype)
        v.trainable = False
        sp = default_startup_program().global_block
        sv = sp.create_parameter(key, shape=shape or param.shape,
                                 dtype=param.dtype)
        ConstantInitializer(fill)(sv, sp)
        self._accumulators[key] = v
        return v

    def _append_update(self, block, param, grad, lr) -> None:
        raise NotImplementedError

    def _increment_global_step(self, block):
        if self.global_step is not None:
            block.append_op("increment",
                            inputs={"X": [self.global_step]},
                            outputs={"Out": [self.global_step]},
                            attrs={"step": 1.0})

    # ----------------------------------------------------------- api
    def minimize(self, loss: Variable, startup_program=None,
                 parameter_list=None, no_grad_set=None) -> List:
        program = loss.block.program if loss.block else \
            default_main_program()
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       program)
        params_grads = append_regularization_ops(
            params_grads, self.regularization, program)
        block = program.global_block
        lr = self._lr_var(block)
        for p, g in params_grads:
            self._append_update(block, p, g, lr)
        self._increment_global_step(block)
        return params_grads


class SGDOptimizer(Optimizer):
    op_type = "sgd"

    def _append_update(self, block, p, g, lr):
        block.append_op("sgd",
                        inputs={"Param": [p], "Grad": [g],
                                "LearningRate": [lr]},
                        outputs={"ParamOut": [p]})


class MomentumOptimizer(Optimizer):
    op_type = "momentum"

    def __init__(self, learning_rate=0.01, momentum=0.9,
                 use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _append_update(self, block, p, g, lr):
        vel = self._acc(block, p, "velocity")
        block.append_op("momentum",
                        inputs={"Param": [p], "Grad": [g],
                                "Velocity": [vel], "LearningRate": [lr]},
                        outputs={"ParamOut": [p], "VelocityOut": [vel]},
                        attrs={"mu": self.momentum,
                               "use_nesterov": self.use_nesterov})


class AdagradOptimizer(Optimizer):
    op_type = "adagrad"

    def __init__(self, learning_rate=0.01, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon

    def _append_update(self, block, p, g, lr):
        mom = self._acc(block, p, "moment")
        block.append_op("adagrad",
                        inputs={"Param": [p], "Grad": [g], "Moment": [mom],
                                "LearningRate": [lr]},
                        outputs={"ParamOut": [p], "MomentOut": [mom]},
                        attrs={"epsilon": self.epsilon})


class AdamOptimizer(Optimizer):
    op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, block, p, g, lr):
        m1 = self._acc(block, p, "moment1")
        m2 = self._acc(block, p, "moment2")
        b1p = self._acc(block, p, "beta1_pow", fill=1.0, shape=())
        b2p = self._acc(block, p, "beta2_pow", fill=1.0, shape=())
        block.append_op(
            "adam",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [lr],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p]},
            outputs={"ParamOut": [p], "Moment1Out": [m1],
                     "Moment2Out": [m2]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon})
        # advance beta powers
        block.append_op("scale", inputs={"X": [b1p]},
                        outputs={"Out": [b1p]},
                        attrs={"scale": self.beta1})
        block.append_op("scale", inputs={"X": [b2p]},
                        outputs={"Out": [b2p]},
                        attrs={"scale": self.beta2})


class AdamaxOptimizer(Optimizer):
    op_type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, block, p, g, lr):
        m = self._acc(block, p, "moment")
        u = self._acc(block, p, "inf_norm")
        b1p = self._acc(block, p, "beta1_pow", fill=1.0, shape=())
        block.append_op(
            "adamax",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [lr],
                    "Moment": [m], "InfNorm": [u], "Beta1Pow": [b1p]},
            outputs={"ParamOut": [p], "MomentOut": [m],
                     "InfNormOut": [u]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon})
        block.append_op("scale", inputs={"X": [b1p]},
                        outputs={"Out": [b1p]},
                        attrs={"scale": self.beta1})


class AdadeltaOptimizer(Optimizer):
    op_type = "adadelta"

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def _append_update(self, block, p, g, lr):
        ag = self._acc(block, p, "avg_squared_grad")
        au = self._acc(block, p, "avg_squared_update")
        block.append_op(
            "adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [ag],
                    "AvgSquaredUpdate": [au]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [ag],
                     "AvgSquaredUpdateOut": [au]},
            attrs={"rho": self.rho, "epsilon": self.epsilon})


class DecayedAdagradOptimizer(Optimizer):
    op_type = "decayed_adagrad"

    def __init__(self, learning_rate=0.01, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def _append_update(self, block, p, g, lr):
        mom = self._acc(block, p, "moment")
        block.append_op(
            "decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [mom],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [p], "MomentOut": [mom]},
            attrs={"decay": self.decay, "epsilon": self.epsilon})


class RMSPropOptimizer(Optimizer):
    op_type = "rmsprop"

    def __init__(self, learning_rate=0.01, decay=0.95, momentum=0.0,
                 epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.momentum, self.epsilon = decay, momentum, epsilon

    def _append_update(self, block, p, g, lr):
        ms = self._acc(block, p, "mean_square")
        mom = self._acc(block, p, "moment")
        block.append_op(
            "rmsprop",
            inputs={"Param": [p], "Grad": [g], "MeanSquare": [ms],
                    "Moment": [mom], "LearningRate": [lr]},
            outputs={"ParamOut": [p], "MeanSquareOut": [ms],
                     "MomentOut": [mom]},
            attrs={"decay": self.decay, "momentum": self.momentum,
                   "epsilon": self.epsilon})


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer

"""Next-gen framework: Program IR → single-XLA-computation Executor.

TPU-native equivalent of the reference's fluid precursor
(``paddle/framework`` + ``paddle/operators`` + ``python/paddle/v2/framework``):
``ProgramDesc/BlockDesc/OpDesc/VarDesc`` (``paddle/framework/framework.proto:33-137``)
become a pure-Python IR; ``Executor::Run`` (``paddle/framework/executor.cc:81``),
which interprets ops one by one with per-op kernels, becomes a **tracer** that
lowers an entire block into ONE jitted XLA computation (SURVEY §7.8 north
star) — op granularity exists only at trace time, XLA fuses the rest.
"""

from .program import (Program, Block, Operator, Variable, Parameter,
                      default_main_program, default_startup_program,
                      program_guard, unique_name)
from .ops import OPS, register_op
from .executor import Executor, Scope, global_scope
from .backward import append_backward
from . import layers, initializer, optimizer, regularizer, io, nets  # noqa: F401
from .evaluator import Accuracy

"""save/load persistables + inference model export
(``python/paddle/v2/framework/io.py``; save/load ops
``paddle/operators/save_op.cc``/``load_op.cc``)."""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ..core.sequence import value_of
from ..utils import enforce
from .executor import Executor, Scope, global_scope
from .program import Program, Variable, default_main_program


def _persistable_params(program: Program) -> List[Variable]:
    return [p for p in program.parameters()]


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None) -> None:
    program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    data = {}
    for b in program.blocks:
        for name, var in b.vars.items():
            if var.persistable and scope.has(name):
                data[name] = np.asarray(value_of(scope.find(name)))
    with open(os.path.join(dirname, "persistables.pkl"), "wb") as f:
        pickle.dump(data, f)


save_params = save_persistables


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None) -> None:
    import jax.numpy as jnp
    scope = scope or global_scope()
    path = os.path.join(dirname, "persistables.pkl")
    with open(path, "rb") as f:
        data = pickle.load(f)
    for name, arr in data.items():
        scope.set(name, jnp.asarray(arr))


load_params = load_persistables


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable],
                         executor: Executor,
                         main_program: Optional[Program] = None,
                         scope: Optional[Scope] = None) -> None:
    """Prune to the inference subgraph + save params
    (reference: ``io.py`` save_inference_model uses ``core.prune``)."""
    program = main_program or default_main_program()
    pruned = program.prune([v.name for v in target_vars])
    save_persistables(executor, dirname, program, scope)

    def _block_meta(block, ops):
        return {
            "parent_idx": block.parent_idx,
            "ops": [(op.type, op.inputs, op.outputs, op.attrs)
                    for op in ops],
            "vars": {n: (tuple(v.shape), v.dtype, v.persistable,
                         v.lod_level)
                     for n, v in block.vars.items()},
        }

    # all blocks travel so recurrent/cond sub_block indices stay valid
    meta = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
        "blocks": [_block_meta(b, pruned.global_block.ops if b.idx == 0
                               else b.ops)
                   for b in program.blocks],
    }
    with open(os.path.join(dirname, "inference_model.pkl"), "wb") as f:
        pickle.dump(meta, f)


def load_inference_model(dirname: str, executor: Executor,
                         scope: Optional[Scope] = None):
    """Returns (program, feed_names, fetch_vars)."""
    with open(os.path.join(dirname, "inference_model.pkl"), "rb") as f:
        meta = pickle.load(f)
    program = Program()
    blocks_meta = meta.get("blocks")
    if blocks_meta is None:   # legacy single-block format
        blocks_meta = [{"parent_idx": -1, "ops": meta["ops"],
                        "vars": meta["vars"]}]
    for i, bm in enumerate(blocks_meta):
        block = program.global_block if i == 0 else \
            program.create_block(bm["parent_idx"])
        for n, (shape, dtype, persistable, lod) in bm["vars"].items():
            block.create_var(name=n, shape=shape, dtype=dtype,
                             persistable=persistable, lod_level=lod)
        for (t, ins, outs, attrs) in bm["ops"]:
            block.append_op(t, inputs=ins, outputs=outs, attrs=attrs)
    load_persistables(executor, dirname, program, scope)
    gb = program.global_block
    fetch_vars = [gb.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars

"""save/load persistables + inference model export
(``python/paddle/v2/framework/io.py``; save/load ops
``paddle/operators/save_op.cc``/``load_op.cc``)."""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ..core.sequence import value_of
from ..utils import enforce
from .executor import Executor, Scope, global_scope
from .program import Program, Variable, default_main_program


def _persistable_params(program: Program) -> List[Variable]:
    return [p for p in program.parameters()]


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None) -> None:
    program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    data = {}
    for b in program.blocks:
        for name, var in b.vars.items():
            if var.persistable and scope.has(name):
                data[name] = np.asarray(value_of(scope.find(name)))
    with open(os.path.join(dirname, "persistables.pkl"), "wb") as f:
        pickle.dump(data, f)


save_params = save_persistables


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None) -> None:
    import jax.numpy as jnp
    scope = scope or global_scope()
    path = os.path.join(dirname, "persistables.pkl")
    with open(path, "rb") as f:
        data = pickle.load(f)
    for name, arr in data.items():
        scope.set(name, jnp.asarray(arr))


load_params = load_persistables


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable],
                         executor: Executor,
                         main_program: Optional[Program] = None,
                         scope: Optional[Scope] = None) -> None:
    """Prune to the inference subgraph + save params
    (reference: ``io.py`` save_inference_model uses ``core.prune``)."""
    program = main_program or default_main_program()
    pruned = program.prune([v.name for v in target_vars])
    save_persistables(executor, dirname, program, scope)
    meta = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
        "ops": [(op.type, op.inputs, op.outputs, op.attrs)
                for op in pruned.global_block.ops],
        "vars": {n: (tuple(v.shape), v.dtype, v.persistable, v.lod_level)
                 for n, v in program.global_block.vars.items()},
    }
    with open(os.path.join(dirname, "inference_model.pkl"), "wb") as f:
        pickle.dump(meta, f)


def load_inference_model(dirname: str, executor: Executor,
                         scope: Optional[Scope] = None):
    """Returns (program, feed_names, fetch_vars)."""
    with open(os.path.join(dirname, "inference_model.pkl"), "rb") as f:
        meta = pickle.load(f)
    program = Program()
    block = program.global_block
    for n, (shape, dtype, persistable, lod) in meta["vars"].items():
        v = block.create_var(name=n, shape=shape, dtype=dtype,
                             persistable=persistable, lod_level=lod)
    for (t, ins, outs, attrs) in meta["ops"]:
        block.append_op(t, inputs=ins, outputs=outs, attrs=attrs)
    load_persistables(executor, dirname, program, scope)
    fetch_vars = [block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars

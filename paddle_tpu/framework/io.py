"""save/load persistables + inference model export
(``python/paddle/v2/framework/io.py``; save/load ops
``paddle/operators/save_op.cc``/``load_op.cc``).

Format: versioned JSON manifest + ``.npz`` tensor archive — same
discipline as ``trainer/checkpoint.py``.  No pickle anywhere: the
artifact is inspectable, diffable, and loading untrusted files cannot
execute code.  For the *deployment* artifact (a model served without
this framework) use :mod:`paddle_tpu.serving` — this module's format
still requires the framework's executor to run.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.sequence import value_of
from ..utils import enforce
from .executor import Executor, Scope, global_scope
from .program import Program, Variable, default_main_program

FORMAT_VERSION = 1


def _encode_attr(v: Any) -> Any:
    """JSON-encode an op attribute, tagging the non-JSON types."""
    if isinstance(v, (type(None), bool, int, float, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, tuple):
        return {"__t__": "tuple", "v": [_encode_attr(x) for x in v]}
    if isinstance(v, list):
        return [_encode_attr(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_attr(x) for k, x in v.items()}
    if isinstance(v, np.dtype):
        return {"__t__": "dtype", "v": str(v)}
    if isinstance(v, np.ndarray):
        return {"__t__": "ndarray", "dtype": str(v.dtype),
                "shape": list(v.shape), "v": v.ravel().tolist()}
    raise TypeError(f"op attribute of type {type(v).__name__} is not "
                    f"serializable: {v!r}")


def _decode_attr(v: Any) -> Any:
    if isinstance(v, list):
        return [_decode_attr(x) for x in v]
    if isinstance(v, dict):
        tag = v.get("__t__")
        if tag == "tuple":
            return tuple(_decode_attr(x) for x in v["v"])
        if tag == "dtype":
            return np.dtype(v["v"])
        if tag == "ndarray":
            return np.asarray(v["v"], dtype=v["dtype"]).reshape(v["shape"])
        return {k: _decode_attr(x) for k, x in v.items()}
    return v


def _persistable_params(program: Program) -> List[Variable]:
    return [p for p in program.parameters()]


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None) -> None:
    program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    data = {}
    for b in program.blocks:
        for name, var in b.vars.items():
            if var.persistable and scope.has(name):
                data[name] = np.asarray(value_of(scope.find(name)))
    np.savez(os.path.join(dirname, "persistables.npz"), **data)
    with open(os.path.join(dirname, "persistables.json"), "w") as f:
        json.dump({"version": FORMAT_VERSION,
                   "names": sorted(data)}, f, indent=2)


save_params = save_persistables


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None) -> None:
    import jax.numpy as jnp
    scope = scope or global_scope()
    with np.load(os.path.join(dirname, "persistables.npz")) as data:
        for name in data.files:
            scope.set(name, jnp.asarray(data[name]))


load_params = load_persistables


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable],
                         executor: Executor,
                         main_program: Optional[Program] = None,
                         scope: Optional[Scope] = None) -> None:
    """Prune to the inference subgraph + save params
    (reference: ``io.py`` save_inference_model uses ``core.prune``)."""
    program = main_program or default_main_program()
    pruned = program.prune([v.name for v in target_vars])
    save_persistables(executor, dirname, program, scope)

    def _block_meta(block, ops):
        return {
            "parent_idx": block.parent_idx,
            "ops": [{"type": op.type, "inputs": op.inputs,
                     "outputs": op.outputs,
                     "attrs": {k: _encode_attr(v)
                               for k, v in op.attrs.items()}}
                    for op in ops],
            "vars": {n: {"shape": list(v.shape), "dtype": str(v.dtype),
                         "persistable": bool(v.persistable),
                         "lod_level": int(v.lod_level)}
                     for n, v in block.vars.items()},
        }

    # all blocks travel so recurrent/cond sub_block indices stay valid
    meta = {
        "format": "paddle-tpu-inference-program",
        "version": FORMAT_VERSION,
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
        "blocks": [_block_meta(b, pruned.global_block.ops if b.idx == 0
                               else b.ops)
                   for b in program.blocks],
    }
    with open(os.path.join(dirname, "inference_model.json"), "w") as f:
        json.dump(meta, f, indent=2)


def load_inference_model(dirname: str, executor: Executor,
                         scope: Optional[Scope] = None):
    """Returns (program, feed_names, fetch_vars)."""
    path = os.path.join(dirname, "inference_model.json")
    with open(path) as f:
        meta = json.load(f)
    enforce(meta.get("version", 0) <= FORMAT_VERSION,
            f"{path}: written by a newer version ({meta.get('version')})")
    program = Program()
    for i, bm in enumerate(meta["blocks"]):
        block = program.global_block if i == 0 else \
            program.create_block(bm["parent_idx"])
        for n, vm in bm["vars"].items():
            block.create_var(name=n, shape=tuple(vm["shape"]),
                             dtype=vm["dtype"],
                             persistable=vm["persistable"],
                             lod_level=vm["lod_level"])
        for om in bm["ops"]:
            block.append_op(om["type"], inputs=om["inputs"],
                            outputs=om["outputs"],
                            attrs={k: _decode_attr(v)
                                   for k, v in om["attrs"].items()})
    load_persistables(executor, dirname, program, scope)
    gb = program.global_block
    fetch_vars = [gb.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars

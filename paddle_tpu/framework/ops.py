"""Framework op registry — the ``paddle/operators`` inventory as jax adapters.

Each op is ``fn(ctx, ins, attrs) -> outs`` where ``ins``/``outs`` map slot
names to lists of arrays, mirroring ``OpDesc``'s name-keyed var lists
(``paddle/framework/framework.proto:33-60``).  Ops run **inside** the
Executor's trace, so an "op" here is just a composition step — XLA fuses
everything; there is no per-op kernel dispatch at runtime (contrast
``paddle/framework/operator.h:349`` OpKernel dispatch, which this replaces).

Inventory parity: the appendix list in SURVEY.md (grep of ``REGISTER_OP*``
in ``paddle/operators/*.cc``).  Control-flow (recurrent, cond), IO
(feed/fetch/save/load) and collectives are owned by the Executor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from ..core.sequence import SequenceBatch, value_of
from ..ops import activations as A
from ..ops import crf_ops, embedding_ops, loss_ops, math_ops, nn_ops
from ..ops import recurrent_ops, sequence_ops
from ..utils import ConfigError, enforce

OPS: Dict[str, Callable] = {}


def register_op(name: str, *aliases: str):
    def deco(fn):
        OPS[name] = fn
        for a in aliases:
            OPS[a] = fn
        return fn
    return deco


@dataclass
class OpContext:
    """Trace-time context handed to every op."""

    is_test: bool = False
    rng: Any = None            # jax PRNG key or None
    _n: int = 0

    def next_key(self):
        enforce(self.rng is not None, "op needs RNG but none was provided")
        self._n += 1
        return jax.random.fold_in(self.rng, self._n)


def _in(ins, slot, i=0, default=None):
    vs = ins.get(slot) or []
    return vs[i] if len(vs) > i else default


def _wrap_like(ref, data):
    """Preserve SequenceBatch structure for pointwise ops."""
    if isinstance(ref, SequenceBatch):
        return SequenceBatch(data, ref.length)
    return data


def _pointwise(fn):
    def op(ctx, ins, attrs):
        x = _in(ins, "X")
        out = fn(value_of(x), **{k: v for k, v in attrs.items()
                                 if k in fn.__code__.co_varnames})
        return {"Out": [_wrap_like(x, out)]}
    return op


# ----------------------------------------------------------- activations
_ACTS = dict(
    abs=A.abs_, brelu=A.brelu, elu=A.elu, exp=A.exp,
    hard_shrink=A.hard_shrink, hard_sigmoid=A.hard_sigmoid,
    leaky_relu=A.leaky_relu, log=A.log, logsigmoid=A.logsigmoid,
    pow=A.pow_, reciprocal=A.reciprocal, relu=A.relu, relu6=A.relu6,
    sigmoid=A.sigmoid, soft_relu=A.soft_relu, softplus=A.softplus,
    softshrink=A.softshrink, softsign=A.softsign, sqrt=A.sqrt,
    square=A.square, stanh=A.stanh, tanh=A.tanh, tanh_shrink=A.tanh_shrink,
    thresholded_relu=A.thresholded_relu, sign=math_ops.sign,
)
for _name, _fn in _ACTS.items():
    register_op(_name)(_pointwise(_fn))


@register_op("softmax")
def _softmax(ctx, ins, attrs):
    x = _in(ins, "X")
    return {"Out": [_wrap_like(x, A.softmax(value_of(x)))]}


@register_op("sequence_softmax")
def _seq_softmax(ctx, ins, attrs):
    x = _in(ins, "X")
    enforce(isinstance(x, SequenceBatch), "sequence_softmax needs LoD input")
    out = A.sequence_softmax(x.data, mask=x.mask())
    return {"Out": [SequenceBatch(out, x.length)]}


# ------------------------------------------------------------------ math
@register_op("mul")
def _mul(ctx, ins, attrs):
    x, y = value_of(_in(ins, "X")), value_of(_in(ins, "Y"))
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    import numpy as _np
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(_np.prod(xs[:xd])), -1)) if x.ndim > 2 else x
    y2 = y.reshape((int(_np.prod(ys[:yd])), -1)) if y.ndim > 2 else y
    out = x2 @ y2
    if x.ndim > 2 or y.ndim > 2:
        # reference mul_op output shape: xs[:x_num_col_dims] + ys[y_num_col_dims:]
        out = out.reshape(xs[:xd] + ys[yd:])
    return {"Out": [out]}


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    out = math_ops.matmul(value_of(_in(ins, "X")), value_of(_in(ins, "Y")),
                          attrs.get("transpose_X", False),
                          attrs.get("transpose_Y", False))
    return {"Out": [out]}


@register_op("sum")
def _sum(ctx, ins, attrs):
    xs = [value_of(v) for v in ins.get("X", [])]
    return {"Out": [math_ops.sum_arrays(*xs)]}


@register_op("scale")
def _scale(ctx, ins, attrs):
    x = _in(ins, "X")
    out = math_ops.scale(value_of(x), attrs.get("scale", 1.0),
                         attrs.get("bias", 0.0))
    return {"Out": [_wrap_like(x, out)]}


@register_op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": [math_ops.mean(value_of(_in(ins, "X")))]}


@register_op("minus")
def _minus(ctx, ins, attrs):
    return {"Out": [math_ops.minus(value_of(_in(ins, "X")),
                                   value_of(_in(ins, "Y")))]}


@register_op("increment")
def _increment(ctx, ins, attrs):
    return {"Out": [math_ops.increment(value_of(_in(ins, "X")),
                                       attrs.get("step", 1.0))]}


@register_op("clip")
def _clip(ctx, ins, attrs):
    x = _in(ins, "X")
    out = math_ops.clip(value_of(x), attrs.get("min", attrs.get("Min")),
                        attrs.get("max", attrs.get("Max")))
    return {"Out": [_wrap_like(x, out)]}


@register_op("cast")
def _cast(ctx, ins, attrs):
    x = _in(ins, "X")
    return {"Out": [_wrap_like(x, math_ops.cast(value_of(x),
                                                attrs["dtype"]))]}


for _nm, _f in [("elementwise_add", math_ops.elementwise_add),
                ("elementwise_sub", math_ops.elementwise_sub),
                ("elementwise_mul", math_ops.elementwise_mul),
                ("elementwise_div", math_ops.elementwise_div)]:
    def _mk(f):
        def op(ctx, ins, attrs):
            x = _in(ins, "X")
            out = f(value_of(x), value_of(_in(ins, "Y")),
                    attrs.get("axis", -1))
            return {"Out": [_wrap_like(x, out)]}
        return op
    register_op(_nm)(_mk(_f))

for _nm, _f in [("reduce_sum", math_ops.reduce_sum),
                ("reduce_mean", math_ops.reduce_mean),
                ("reduce_max", math_ops.reduce_max),
                ("reduce_min", math_ops.reduce_min)]:
    def _mkr(f):
        def op(ctx, ins, attrs):
            out = f(value_of(_in(ins, "X")), attrs.get("dim"),
                    attrs.get("keep_dim", False))
            return {"Out": [out]}
        return op
    register_op(_nm)(_mkr(_f))


@register_op("reshape")
def _reshape(ctx, ins, attrs):
    return {"Out": [math_ops.reshape(value_of(_in(ins, "X")),
                                     attrs["shape"])]}


@register_op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": [math_ops.transpose(value_of(_in(ins, "X")),
                                       attrs.get("axis"))]}


@register_op("concat")
def _concat(ctx, ins, attrs):
    xs = [value_of(v) for v in ins.get("X", [])]
    return {"Out": [math_ops.concat(*xs, axis=attrs.get("axis", 1))]}


@register_op("split")
def _split(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    sections = attrs.get("sections") or attrs.get("num", 2)
    outs = math_ops.split(x, sections, attrs.get("axis", 1))
    return {"Out": list(outs)}


@register_op("pad")
def _pad(ctx, ins, attrs):
    p = attrs["paddings"]
    pairs = list(zip(p[::2], p[1::2]))
    return {"Out": [math_ops.pad(value_of(_in(ins, "X")), pairs,
                                 attrs.get("pad_value", 0.0))]}


@register_op("crop")
def _crop(ctx, ins, attrs):
    return {"Out": [math_ops.crop(value_of(_in(ins, "X")),
                                  attrs["offsets"], attrs["shape"])]}


@register_op("gather")
def _gather(ctx, ins, attrs):
    return {"Out": [math_ops.gather(value_of(_in(ins, "X")),
                                    value_of(_in(ins, "Index")))]}


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    return {"Out": [math_ops.scatter(value_of(_in(ins, "Ref")),
                                     value_of(_in(ins, "Index")),
                                     value_of(_in(ins, "Updates")))]}


@register_op("top_k")
def _top_k(ctx, ins, attrs):
    vals, idx = math_ops.top_k(value_of(_in(ins, "X")), attrs.get("k", 1))
    return {"Out": [vals], "Indices": [idx]}


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    idx = value_of(_in(ins, "Ids"))
    xs = [value_of(v) for v in ins.get("X", [])]
    return {"Out": [math_ops.multiplex(idx.reshape(-1), *xs)]}


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    return {"Out": [math_ops.fill_constant(attrs["shape"], attrs["value"],
                                           attrs.get("dtype", jnp.float32))]}


@register_op("fill_constant_batch_size_like")
def _fill_cbsl(ctx, ins, attrs):
    return {"Out": [math_ops.fill_constant_batch_size_like(
        value_of(_in(ins, "Input")), attrs["shape"], attrs["value"])]}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    x = _in(ins, "X")
    return {"Out": [_wrap_like(x, math_ops.fill_zeros_like(value_of(x)))]}


@register_op("gaussian_random")
def _gaussian_random(ctx, ins, attrs):
    out = math_ops.gaussian_random(ctx.next_key(), attrs["shape"],
                                   attrs.get("mean", 0.0),
                                   attrs.get("std", 1.0))
    return {"Out": [out]}


@register_op("uniform_random")
def _uniform_random(ctx, ins, attrs):
    out = math_ops.uniform_random(ctx.next_key(), attrs["shape"],
                                  attrs.get("min", -1.0),
                                  attrs.get("max", 1.0))
    return {"Out": [out]}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    out = math_ops.cos_sim(value_of(_in(ins, "X")), value_of(_in(ins, "Y")))
    return {"Out": [out.reshape(-1, 1)]}


@register_op("conv_shift")
def _conv_shift(ctx, ins, attrs):
    return {"Out": [math_ops.conv_shift(value_of(_in(ins, "X")),
                                        value_of(_in(ins, "Y")))]}


# -------------------------------------------------------------------- nn
@register_op("conv2d", "conv_cudnn")
def _conv2d(ctx, ins, attrs):
    """NCHW input [N,C,H,W], filter [Cout,Cin/g,KH,KW] (reference layout,
    ``conv2d`` in ``paddle/operators/conv_op.cc``)."""
    x = value_of(_in(ins, "Input"))
    w = value_of(_in(ins, "Filter"))
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))
    s = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0])
    d = attrs.get("dilations", [1, 1])
    out = nn_ops.conv2d(x, w_hwio, stride=tuple(s),
                        padding=[(p[0], p[0]), (p[1], p[1])],
                        dilation=tuple(d), groups=attrs.get("groups", 1),
                        data_format="NCHW")
    return {"Output": [out]}


@register_op("conv2d_transpose", "conv2d_transpose_cudnn")
def _conv2d_transpose(ctx, ins, attrs):
    x = value_of(_in(ins, "Input"))
    w = value_of(_in(ins, "Filter"))   # [Cin, Cout, KH, KW]
    s = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0])
    # helper wants [KH, KW, Cout, Cin]; it owns the reference
    # (i-1)·s + k − 2p sizing for explicit padding
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))
    out = nn_ops.conv2d_transpose(x, w_hwio, stride=tuple(s),
                                  padding=[(p[0], p[0]), (p[1], p[1])],
                                  data_format="NCHW")
    return {"Output": [out]}


@register_op("pool2d", "pool2d_cudnn")
def _pool2d(ctx, ins, attrs):
    out = nn_ops.pool2d(value_of(_in(ins, "X")),
                        pool_type=attrs.get("pooling_type", "max"),
                        window=tuple(attrs.get("ksize", [2, 2])),
                        stride=tuple(attrs.get("strides", [2, 2])),
                        padding=tuple(attrs.get("paddings", [0, 0])),
                        data_format="NCHW",
                        global_pooling=attrs.get("global_pooling", False))
    return {"Out": [out]}


@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    k = attrs.get("ksize", [2, 2, 2])
    s = attrs.get("strides", k)
    if attrs.get("global_pooling", False):
        red = jnp.max if attrs.get("pooling_type", "max") == "max" \
            else jnp.mean
        return {"Out": [red(x, axis=(2, 3, 4), keepdims=True)]}
    dims = (1, 1) + tuple(k)
    strides = (1, 1) + tuple(s)
    if attrs.get("pooling_type", "max") == "max":
        # python-scalar init keeps the max monoid recognizable under jit
        out = lax.reduce_window(x, -float("inf"), lax.max,
                                dims, strides, "VALID")
    else:
        out = lax.reduce_window(x, 0.0, lax.add,
                                dims, strides, "VALID") / float(
            k[0] * k[1] * k[2])
    return {"Out": [out]}


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    # primitive is NHWC; convert
    xt = jnp.transpose(x, (0, 2, 3, 1))
    out, idx = nn_ops.max_pool2d_with_index(
        xt, window=tuple(attrs.get("ksize", [2, 2])),
        stride=tuple(attrs.get("strides", [2, 2])),
        padding=attrs.get("paddings", [0, 0])[0])
    return {"Out": [jnp.transpose(out, (0, 3, 1, 2))],
            "Mask": [jnp.transpose(idx, (0, 3, 1, 2))]}


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    k = tuple(attrs.get("ksize", [2, 2, 2]))
    s = tuple(attrs.get("strides", k))
    dims, strides = (1, 1) + k, (1, 1) + s
    out = lax.reduce_window(x, -float("inf"), lax.max,
                            dims, strides, "VALID")
    return {"Out": [out], "Mask": [jnp.zeros_like(out, jnp.int32)]}


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    y, rm, rv = nn_ops.batch_norm(
        x, value_of(_in(ins, "Scale")), value_of(_in(ins, "Bias")),
        value_of(_in(ins, "Mean")), value_of(_in(ins, "Variance")),
        momentum=attrs.get("momentum", 0.9),
        eps=attrs.get("epsilon", 1e-5),
        is_training=not attrs.get("is_test", ctx.is_test),
        data_format="NCHW" if x.ndim == 4 else "NC")
    return {"Y": [y], "MeanOut": [rm], "VarianceOut": [rv],
            "SavedMean": [rm], "SavedVariance": [rv]}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))        # NCHW
    xt = jnp.transpose(x, (0, 2, 3, 1))
    out = nn_ops.lrn(xt, n=attrs.get("n", 5), k=attrs.get("k", 2.0),
                     alpha=attrs.get("alpha", 1e-4),
                     beta=attrs.get("beta", 0.75))
    return {"Out": [jnp.transpose(out, (0, 3, 1, 2))],
            "MidOut": [jnp.zeros_like(x)]}


@register_op("dropout")
def _dropout(ctx, ins, attrs):
    x = _in(ins, "X")
    is_test = attrs.get("is_test", ctx.is_test)
    rate = attrs.get("dropout_prob", 0.5)
    if is_test:
        out = value_of(x)
    else:
        out = nn_ops.dropout(value_of(x), ctx.next_key(), rate, True)
    return {"Out": [_wrap_like(x, out)],
            "Mask": [jnp.ones_like(value_of(x))]}


@register_op("prelu")
def _prelu(ctx, ins, attrs):
    return {"Out": [nn_ops.prelu(value_of(_in(ins, "X")),
                                 value_of(_in(ins, "Alpha")))]}


# ------------------------------------------------------------- embedding
@register_op("lookup_table")
def _lookup_table(ctx, ins, attrs):
    w = value_of(_in(ins, "W"))
    ids = _in(ins, "Ids")
    if isinstance(ids, SequenceBatch):
        data = ids.data
        if data.ndim > 2 and data.shape[-1] == 1:
            data = data[..., 0]
        return {"Out": [SequenceBatch(w[data], ids.length)]}
    iv = value_of(ids)
    if iv.ndim == 2 and iv.shape[-1] == 1:
        iv = iv[:, 0]
    return {"Out": [w[iv]]}


# ----------------------------------------------------------------- loss
@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    label = value_of(_in(ins, "Label"))
    if attrs.get("soft_label", False):
        out = loss_ops.cross_entropy(x, label, soft_label=True)
    else:
        out = loss_ops.cross_entropy(x, label.reshape(-1))
    return {"Y": [out.reshape(-1, 1)]}


@register_op("softmax_with_cross_entropy")
def _softmax_with_ce(ctx, ins, attrs):
    logits = value_of(_in(ins, "Logits"))
    label = value_of(_in(ins, "Label"))
    soft = attrs.get("soft_label", False)
    loss = loss_ops.softmax_with_cross_entropy(
        logits, label if soft else label.reshape(-1), soft_label=soft)
    return {"Softmax": [A.softmax(logits)], "Loss": [loss.reshape(-1, 1)]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sig_ce(ctx, ins, attrs):
    out = loss_ops.sigmoid_cross_entropy_with_logits(
        value_of(_in(ins, "X")), value_of(_in(ins, "Label")))
    return {"Out": [out]}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    out = loss_ops.smooth_l1_loss(value_of(_in(ins, "X")),
                                  value_of(_in(ins, "Y")),
                                  attrs.get("sigma", 1.0))
    return {"Out": [out.reshape(-1, 1)], "Diff": [out]}


@register_op("huber_loss")
def _huber(ctx, ins, attrs):
    out = loss_ops.huber_loss(value_of(_in(ins, "X")),
                              value_of(_in(ins, "Y")),
                              attrs.get("delta", 1.0))
    return {"Out": [out.reshape(-1, 1)], "Residual": [out]}


@register_op("modified_huber_loss")
def _modified_huber(ctx, ins, attrs):
    out = loss_ops.modified_huber_loss(value_of(_in(ins, "X")),
                                       value_of(_in(ins, "Y")))
    return {"Out": [out.reshape(-1, 1)],
            "IntermediateVal": [out]}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    out = loss_ops.rank_loss(value_of(_in(ins, "Left")),
                             value_of(_in(ins, "Right")),
                             value_of(_in(ins, "Label")))
    return {"Out": [out]}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    out = loss_ops.margin_rank_loss(value_of(_in(ins, "X1")),
                                    value_of(_in(ins, "X2")),
                                    value_of(_in(ins, "Label")),
                                    attrs.get("margin", 0.0))
    return {"Out": [out], "Activated": [out]}


@register_op("squared_l2_distance")
def _sq_l2_dist(ctx, ins, attrs):
    out = loss_ops.squared_l2_distance(value_of(_in(ins, "X")),
                                       value_of(_in(ins, "Y")))
    return {"Out": [out.reshape(-1, 1)], "sub_result": [out]}


@register_op("squared_l2_norm")
def _sq_l2_norm(ctx, ins, attrs):
    return {"Out": [loss_ops.squared_l2_norm(value_of(_in(ins, "X")))]}


@register_op("l1_norm")
def _l1_norm(ctx, ins, attrs):
    return {"Out": [loss_ops.l1_norm(value_of(_in(ins, "X")))]}


@register_op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    em = _in(ins, "Emission")
    lab = _in(ins, "Label")
    w = value_of(_in(ins, "Transition"))
    enforce(isinstance(em, SequenceBatch), "crf wants LoD emissions")
    nll = crf_ops.crf_nll(em, lab, w)
    return {"LogLikelihood": [(-nll).reshape(-1, 1)],
            "Alpha": [em.data], "EmissionExps": [em.data],
            "TransitionExps": [w]}


@register_op("crf_decoding")
def _crf_decoding(ctx, ins, attrs):
    em = _in(ins, "Emission")
    w = value_of(_in(ins, "Transition"))
    path = crf_ops.crf_decode(em, w)
    return {"ViterbiPath": [path]}


# --------------------------------------------------------------- metrics
@register_op("accuracy")
def _accuracy(ctx, ins, attrs):
    out = value_of(_in(ins, "Out"))
    label = value_of(_in(ins, "Label")).reshape(-1)
    k = attrs.get("k", 1)
    if k <= 1:
        hit = jnp.argmax(out, axis=-1) == label
    else:
        _, topk = lax.top_k(out, k)
        hit = jnp.any(topk == label[:, None], axis=-1)
    correct = jnp.sum(hit.astype(jnp.float32))
    total = jnp.asarray(label.shape[0], jnp.float32)
    return {"Accuracy": [correct / total], "Correct": [correct],
            "Total": [total]}


@register_op("auc")
def _auc(ctx, ins, attrs):
    # streaming AUC is host-side in practice; provide a batch AUC estimate
    out = value_of(_in(ins, "Out"))
    label = value_of(_in(ins, "Label")).reshape(-1)
    score = out[:, 1] if out.ndim == 2 and out.shape[1] > 1 \
        else out.reshape(-1)
    order = jnp.argsort(score)
    ranks = jnp.zeros_like(score).at[order].set(
        jnp.arange(1, score.shape[0] + 1, dtype=score.dtype))
    pos = (label > 0).astype(score.dtype)
    n_pos = jnp.sum(pos)
    n_neg = label.shape[0] - n_pos
    auc = (jnp.sum(ranks * pos) - n_pos * (n_pos + 1) / 2) / \
        jnp.maximum(n_pos * n_neg, 1.0)
    return {"AUC": [auc]}


@register_op("precision_recall")
def _precision_recall(ctx, ins, attrs):
    out = value_of(_in(ins, "Out"))
    label = value_of(_in(ins, "Label")).reshape(-1)
    ncls = out.shape[-1]
    pred = jnp.argmax(out, -1)
    onehot_p = jax.nn.one_hot(pred, ncls)
    onehot_l = jax.nn.one_hot(label, ncls)
    tp = jnp.sum(onehot_p * onehot_l, 0)
    fp = jnp.sum(onehot_p * (1 - onehot_l), 0)
    fn = jnp.sum((1 - onehot_p) * onehot_l, 0)
    prec = tp / jnp.maximum(tp + fp, 1.0)
    rec = tp / jnp.maximum(tp + fn, 1.0)
    return {"BatchMetrics": [jnp.stack([prec, rec])],
            "AccumMetrics": [jnp.stack([prec, rec])],
            "AccumStatesInfo": [jnp.stack([tp, fp, fn])]}


# -------------------------------------------------------------- sequence
@register_op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = _in(ins, "X")
    enforce(isinstance(x, SequenceBatch), "sequence_pool needs LoD input")
    out = sequence_ops.sequence_pool(x, attrs.get("pooltype",
                                                  "AVERAGE").lower())
    return {"Out": [out]}


@register_op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    xs = ins.get("X", [])
    a, b = xs[0], xs[1]
    if attrs.get("axis", 0) == 0:
        return {"Out": [sequence_ops.sequence_concat(a, b)]}
    return {"Out": [SequenceBatch(
        jnp.concatenate([a.data, b.data], axis=-1), a.length)]}


@register_op("seq_expand")
def _seq_expand(ctx, ins, attrs):
    x = value_of(_in(ins, "X"))
    y = _in(ins, "Y")
    return {"Out": [sequence_ops.seq_expand(x, y)]}


@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    x = _in(ins, "X")
    w = value_of(_in(ins, "Filter"))
    out = sequence_ops.sequence_conv(
        x, w, attrs.get("contextStart", -1),
        attrs.get("contextLength", 3))
    return {"Out": [out]}


# -------------------------------------------------------------- recurrent
@register_op("lstm")
def _lstm(ctx, ins, attrs):
    x = _in(ins, "Input")
    enforce(isinstance(x, SequenceBatch), "lstm op wants LoD input")
    w = value_of(_in(ins, "Weight"))       # [H, 4H] recurrent weight
    bias = _in(ins, "Bias")
    # op contract (lstm_op.cc): candidate_activation acts on the
    # candidate c̃; cell_activation acts on the cell when forming
    # h = o·act(c).  lstm_gate_step's cell_act is the candidate slot
    # and out_act the output slot, hence the cross mapping.
    h_seq, final, c_seq = recurrent_ops.lstm_sequence(
        x, None, w, value_of(bias) if bias is not None else None,
        reverse=attrs.get("is_reverse", False),
        gate_act=attrs.get("gate_activation", "sigmoid"),
        cell_act=attrs.get("candidate_activation", "tanh"),
        out_act=attrs.get("cell_activation", "tanh"),
        return_cells=True)
    return {"Hidden": [h_seq], "Cell": [c_seq],
            "BatchGate": [x.data], "BatchCellPreAct": [x.data]}


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    # recurrent_ops.lstm_unit returns (c, h) — C first
    c, h = recurrent_ops.lstm_unit(value_of(_in(ins, "X")),
                                   value_of(_in(ins, "C_prev")),
                                   attrs.get("forget_bias", 0.0))
    return {"H": [h], "C": [c]}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    h = recurrent_ops.gru_unit(value_of(_in(ins, "Input")),
                               value_of(_in(ins, "HiddenPrev")),
                               value_of(_in(ins, "Weight")))
    return {"Hidden": [h], "Gate": [h], "ResetHiddenPrev": [h]}


# -------------------------------------------------------- optimizer ops
@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p = value_of(_in(ins, "Param"))
    g = value_of(_in(ins, "Grad"))
    lr = value_of(_in(ins, "LearningRate"))
    return {"ParamOut": [p - lr * g]}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    v = value_of(_in(ins, "Velocity"))
    lr = value_of(_in(ins, "LearningRate"))
    mu = attrs.get("mu", 0.9)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    m, v = value_of(_in(ins, "Moment1")), value_of(_in(ins, "Moment2"))
    b1p = value_of(_in(ins, "Beta1Pow"))
    b2p = value_of(_in(ins, "Beta2Pow"))
    lr = value_of(_in(ins, "LearningRate"))
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p * b2) / (1 - b1p * b1)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return {"ParamOut": [p_new], "Moment1Out": [m_new],
            "Moment2Out": [v_new]}


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    m = value_of(_in(ins, "Moment"))
    u = value_of(_in(ins, "InfNorm"))
    b1p = value_of(_in(ins, "Beta1Pow"))
    lr = value_of(_in(ins, "LearningRate"))
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    p_new = p - (lr / (1 - b1p * b1)) * m_new / (u_new + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new],
            "InfNormOut": [u_new]}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    mom = value_of(_in(ins, "Moment"))
    lr = value_of(_in(ins, "LearningRate"))
    eps = attrs.get("epsilon", 1e-6)
    m_new = mom + g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_new) + eps)],
            "MomentOut": [m_new]}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    ag = value_of(_in(ins, "AvgSquaredGrad"))
    au = value_of(_in(ins, "AvgSquaredUpdate"))
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    ag_new = rho * ag + (1 - rho) * g * g
    upd = jnp.sqrt(au + eps) / jnp.sqrt(ag_new + eps) * g
    au_new = rho * au + (1 - rho) * upd * upd
    return {"ParamOut": [p - upd], "AvgSquaredGradOut": [ag_new],
            "AvgSquaredUpdateOut": [au_new]}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    mom = value_of(_in(ins, "Moment"))
    lr = value_of(_in(ins, "LearningRate"))
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_new = decay * mom + (1 - decay) * g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_new) + eps)],
            "MomentOut": [m_new]}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    ms = value_of(_in(ins, "MeanSquare"))
    mom = value_of(_in(ins, "Moment"))
    lr = value_of(_in(ins, "LearningRate"))
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    mom_new = momentum * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": [p - mom_new], "MomentOut": [mom_new],
            "MeanSquareOut": [ms_new]}


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    lr = value_of(_in(ins, "LearningRate"))
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": [p_new]}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    p, g = value_of(_in(ins, "Param")), value_of(_in(ins, "Grad"))
    mom = value_of(_in(ins, "Moment"))
    lr = value_of(_in(ins, "LearningRate"))
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    m_new = mom + g * g
    lr_t = lr / jnp.sqrt(m_new + 1e-10)
    prox = p - lr_t * g
    p_new = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - lr_t * l1, 0.0) / (1.0 + lr_t * l2)
    return {"ParamOut": [p_new], "MomentOut": [m_new]}

"""Backward "transpiler" — autodiff-native.

The reference walks the op list appending hand-written grad ops and
sum-merging duplicate gradients (``paddle/framework/backward.cc:336,382``).
TPU-native: gradients come from ``jax.grad`` over the traced forward —
``append_backward`` plants a single ``backward`` marker op; the Executor
lowers everything before it into a differentiable function of the
parameters and emits ``<param>@GRAD`` values for the optimizer ops that
follow.  Grad accumulation for shared parameters is what autodiff does
natively (the reference needed explicit sum-merge).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..utils import enforce
from .program import Parameter, Program, Variable, default_main_program


def grad_var_name(name: str) -> str:
    return name + "@GRAD"


def append_backward(loss: Variable,
                    parameter_list: Optional[Sequence[str]] = None,
                    no_grad_set: Optional[set] = None,
                    program: Optional[Program] = None
                    ) -> List[tuple]:
    """Append the backward pass for ``loss``; returns
    ``[(param_var, grad_var), ...]`` like the reference's
    ``append_backward_ops`` (``python/paddle/v2/framework/backward.py``)."""
    program = program or default_main_program()
    block = program.global_block
    no_grad = set(no_grad_set or ())

    if parameter_list:
        pnames = list(parameter_list)
    else:
        pnames = [p.name for p in program.parameters()
                  if p.trainable and p.name not in no_grad]
    enforce(len(pnames) > 0, "no trainable parameters to differentiate")

    grads = []
    for n in pnames:
        gv = block.create_var(name=grad_var_name(n),
                              shape=block.var(n).shape,
                              dtype=block.var(n).dtype)
        grads.append((block.var(n), gv))

    block.append_op(
        type="backward",
        inputs={"Loss": [loss]},
        outputs={"Grads": [g for _, g in grads]},
        attrs={"parameter_names": pnames, "loss": loss.name})
    return grads

"""paddle.v2.trainer equivalent: the SGD event-loop trainer.

Reference: ``python/paddle/v2/trainer.py:24`` — ``SGD(cost, parameters,
update_equation).train(reader, num_passes, event_handler, feeding)``.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..config.dsl import LayerOutput, topology
from ..data.feeder import DataFeeder
from ..layers.network import NeuralNetwork
from ..trainer.trainer import Trainer as _CoreTrainer
from . import event as _event  # noqa: F401
from .optimizer import Optimizer


class SGD:
    def __init__(self, cost, parameters=None, update_equation: Optimizer = None,
                 extra_layers=None, is_local: bool = True):
        self.model_config = topology(cost, extra_layers)
        self.network = NeuralNetwork(self.model_config)
        opt_conf = update_equation.conf if update_equation else None
        self.core = _CoreTrainer(self.network, opt_config=opt_conf)
        if parameters is not None:
            parameters.attach(self.core)

    def _feeder(self, feeding) -> Optional[DataFeeder]:
        if feeding is None:
            return None
        lmap = {l.name: l for l in self.model_config.layers}
        order = sorted(feeding, key=lambda n: feeding[n]) \
            if isinstance(feeding, dict) else list(feeding)
        from ..data.feeder import InputType

        pairs = []
        for name in order:
            conf = lmap[name]
            pairs.append((name, InputType(
                dim=conf.size,
                seq_level=conf.attrs.get("seq_level", 0),
                kind=conf.attrs.get("kind", "dense"))))
        return DataFeeder(pairs)

    def train(self, reader, num_passes: int = 1, event_handler=None,
              feeding=None, test_reader=None, evaluators: Sequence = ()):
        self.core.train(reader, num_passes=num_passes,
                        event_handler=event_handler,
                        feeder=self._feeder(feeding),
                        test_reader=test_reader, evaluators=evaluators)

    def test(self, reader, feeding=None, evaluators: Sequence = ()):
        return self.core.test(reader, self._feeder(feeding), evaluators)

    @property
    def parameters(self):
        from .parameters import Parameters

        p = Parameters()
        p.attach(self.core)
        return p

from ..config.dsl import (  # noqa: F401
    AvgPooling as Avg,
    MaxPooling as Max,
    SqrtPooling as Sqrt,
    SumPooling as Sum,
)

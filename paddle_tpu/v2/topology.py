"""``paddle.v2.topology`` equivalent.

Reference: ``python/paddle/v2/topology.py:27`` — Topology wraps output
layers and exposes the parsed ModelConfig plus input-type plumbing for
the DataFeeder.
"""

from __future__ import annotations

from ..config.dsl import LayerOutput, topology as _parse
from ..utils import enforce


class Topology:
    def __init__(self, layers, extra_layers=None):
        def check(ls):
            ls = list(ls) if isinstance(ls, (list, tuple)) else [ls]
            for l in ls:
                enforce(isinstance(l, LayerOutput),
                        f"Topology expects LayerOutput, got {type(l)}")
            return ls

        self.layers = check(layers)
        extra = check(extra_layers) if extra_layers is not None else None
        self.__model_config__ = _parse(self.layers, extra)

    def proto(self):
        """The parsed model config (the reference returns the protobuf;
        here it is the dataclass IR with the same field names)."""
        return self.__model_config__

    def get_layer_proto(self, name: str):
        for l in self.__model_config__.layers:
            if l.name == name:
                return l
        return None

    def data_layers(self) -> dict:
        """name → LayerConfig for every data layer, in input order."""
        cfg = self.__model_config__
        by_name = {l.name: l for l in cfg.layers}
        return {n: by_name[n] for n in cfg.input_layer_names
                if n in by_name}

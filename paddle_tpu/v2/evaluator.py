"""paddle.v2.evaluator equivalent — evaluator factory functions."""

from ..evaluators import create_evaluator


def classification_error(**kw):
    return create_evaluator("classification_error", **kw)


def auc(**kw):
    return create_evaluator("auc", **kw)


def precision_recall(**kw):
    return create_evaluator("precision_recall", **kw)


def chunk(**kw):
    return create_evaluator("chunk", **kw)


def sum(**kw):  # noqa: A001 (reference name)
    return create_evaluator("sum", **kw)


def column_sum(**kw):
    return create_evaluator("column_sum", **kw)


def pnpair(**kw):
    return create_evaluator("pnpair", **kw)


def rankauc(**kw):
    return create_evaluator("rankauc", **kw)


def ctc_error(**kw):
    return create_evaluator("ctc_edit_distance", **kw)

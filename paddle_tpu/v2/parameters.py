"""paddle.v2.parameters equivalent: numpy view + tar-style checkpointing.

Reference: ``python/paddle/v2/parameters.py`` (``to_tar:328``/``from_tar:358``
— here the container format is the framework's npz checkpoint).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np


class Parameters:
    def __init__(self):
        self._trainer = None
        self._store: Dict[str, np.ndarray] = {}

    def attach(self, core_trainer) -> None:
        self._trainer = core_trainer

    def names(self):
        if self._trainer is not None:
            return sorted(self._trainer.params)
        return sorted(self._store)

    def get(self, name: str) -> np.ndarray:
        if self._trainer is not None:
            return np.asarray(self._trainer.params[name])
        return self._store[name]

    __getitem__ = get

    def set(self, name: str, value) -> None:
        if self._trainer is not None:
            self._trainer.params[name] = jnp.asarray(value)
        else:
            self._store[name] = np.asarray(value)

    __setitem__ = set

    def to_tar(self, f) -> None:
        """Serialize to an npz stream (keeps the to_tar name for parity)."""
        data = {n: self.get(n) for n in self.names()}
        np.savez(f, **data)

    @staticmethod
    def from_tar(f) -> "Parameters":
        p = Parameters()
        with np.load(f) as z:
            for k in z.files:
                p._store[k] = z[k]
        return p

    def append_gradient_machine(self, *_args) -> None:  # legacy no-op
        pass

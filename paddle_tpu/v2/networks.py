"""Composite network helpers.

Reference: ``trainer_config_helpers/networks.py`` — simple_img_conv_pool,
img_conv_group, vgg_16_network, simple_lstm, lstmemory_group, simple_gru,
bidirectional_lstm, stacked LSTM pieces, sequence_conv_pool,
simple_attention.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..config import dsl
from ..config.dsl import (
    AvgPooling,
    LinearActivation,
    MaxPooling,
    ReluActivation,
    SequenceSoftmaxActivation,
    SigmoidActivation,
    SoftmaxActivation,
    StepInput,
    TanhActivation,
    batch_norm,
    concat,
    data,
    dropout,
    expand,
    fc,
    first_seq,
    full_matrix_projection,
    grumemory,
    img_conv,
    img_pool,
    last_seq,
    lstmemory,
    memory,
    mixed,
    pooling,
    recurrent_group,
)


def simple_img_conv_pool(input, filter_size: int, num_filters: int,
                         pool_size: int, num_channel: Optional[int] = None,
                         pool_stride: int = 2, act=None, padding: int = 1,
                         img_size: Optional[int] = None, name=None):
    conv = img_conv(input, filter_size=filter_size, num_filters=num_filters,
                    num_channels=num_channel, padding=padding,
                    img_size=img_size, act=act or ReluActivation(),
                    name=name and f"{name}_conv")
    return img_pool(conv, pool_size=pool_size, stride=pool_stride,
                    pool_type=MaxPooling(), name=name and f"{name}_pool")


def img_conv_group(input, conv_num_filter: Sequence[int],
                   conv_filter_size: int = 3, num_channels=None,
                   pool_size: int = 2, pool_stride: int = 2,
                   conv_padding: int = 1, conv_act=None,
                   conv_with_batchnorm: bool = False,
                   conv_batchnorm_drop_rate=None, pool_type=None,
                   img_size: Optional[int] = None, **_ignored):
    tmp = input
    channels = num_channels
    for i, nf in enumerate(conv_num_filter):
        tmp = img_conv(tmp, filter_size=conv_filter_size, num_filters=nf,
                       num_channels=channels, padding=conv_padding,
                       img_size=img_size,
                       act=LinearActivation() if conv_with_batchnorm
                       else (conv_act or ReluActivation()))
        img_size = None
        channels = None
        if conv_with_batchnorm:
            drop = 0.0
            if conv_batchnorm_drop_rate:
                drop = conv_batchnorm_drop_rate[i] \
                    if isinstance(conv_batchnorm_drop_rate, (list, tuple)) \
                    else conv_batchnorm_drop_rate
            tmp = batch_norm(tmp, act=conv_act or ReluActivation(),
                             layer_attr=dsl.ExtraAttr(drop_rate=drop))
    return img_pool(tmp, pool_size=pool_size, stride=pool_stride,
                    pool_type=pool_type or MaxPooling())


def vgg_16_network(input_image, num_channels: int, num_classes: int = 1000,
                   img_size: int = 224):
    """``vgg_16_network`` (networks.py): 5 conv groups + 2×fc4096."""
    tmp = img_conv_group(input_image, [64, 64], num_channels=num_channels,
                         conv_with_batchnorm=True, img_size=img_size)
    tmp = img_conv_group(tmp, [128, 128], conv_with_batchnorm=True)
    tmp = img_conv_group(tmp, [256, 256, 256], conv_with_batchnorm=True)
    tmp = img_conv_group(tmp, [512, 512, 512], conv_with_batchnorm=True)
    tmp = img_conv_group(tmp, [512, 512, 512], conv_with_batchnorm=True)
    tmp = fc(tmp, size=4096, act=ReluActivation(),
             layer_attr=dsl.ExtraAttr(drop_rate=0.5))
    tmp = fc(tmp, size=4096, act=ReluActivation(),
             layer_attr=dsl.ExtraAttr(drop_rate=0.5))
    return fc(tmp, size=num_classes, act=SoftmaxActivation())


def simple_lstm(input, size: int, name=None, reverse: bool = False,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None, act=None, gate_act=None,
                state_act=None):
    """fc(4H) + lstmemory (``simple_lstm`` in networks.py)."""
    proj = fc(input, size=size * 4, act=LinearActivation(), bias_attr=False,
              param_attr=mat_param_attr, name=name and f"{name}_transform")
    return lstmemory(proj, name=name, reverse=reverse, act=act,
                     gate_act=gate_act, state_act=state_act,
                     bias_attr=bias_param_attr if bias_param_attr is not None
                     else True,
                     param_attr=inner_param_attr)


def simple_gru(input, size: int, name=None, reverse: bool = False, act=None,
               gate_act=None, **kw):
    proj = fc(input, size=size * 3, act=LinearActivation(), bias_attr=False,
              name=name and f"{name}_transform")
    return grumemory(proj, name=name, reverse=reverse, act=act,
                     gate_act=gate_act)


def bidirectional_lstm(input, size: int, name=None, return_seq: bool = False):
    fwd = simple_lstm(input, size, name=name and f"{name}_fwd")
    bwd = simple_lstm(input, size, name=name and f"{name}_bwd", reverse=True)
    if return_seq:
        return concat([fwd, bwd])
    return concat([last_seq(fwd), first_seq(bwd)])


def stacked_lstm_net(input, hid_dim: int, stacked_num: int = 3,
                     act=None):
    """Stacked alternating-direction LSTM (sentiment demo topology)."""
    lstm = simple_lstm(input, hid_dim)
    inputs = [input, lstm]
    for i in range(2, stacked_num + 1):
        nxt = fc(inputs, size=hid_dim * 4, act=LinearActivation(),
                 bias_attr=False)
        lstm = lstmemory(nxt, reverse=(i % 2 == 0))
        inputs = [nxt, lstm]
    return lstm


def sequence_conv_pool(input, context_len: int, hidden_size: int,
                       name=None, context_start=None, pool_type=None,
                       context_proj_param_attr=None, fc_act=None):
    """context projection + fc + seq pooling (text conv)."""
    ctx = mixed(
        [dsl.context_projection(input, context_len, context_start)],
        size=input.size * context_len, name=name and f"{name}_ctx")
    h = fc(ctx, size=hidden_size, act=fc_act or LinearActivation(),
           name=name and f"{name}_fc")
    return pooling(h, pooling_type=pool_type or MaxPooling(),
                   name=name and f"{name}_pool")


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name=None):
    """Bahdanau attention (``simple_attention`` in networks.py):
    score = v·tanh(enc_proj + dec_proj); context = Σ softmax(score)·enc."""
    name = name or dsl._collector.unique_name("attention")
    decoder_proj = fc(decoder_state, size=encoded_proj.size,
                      act=LinearActivation(), bias_attr=False,
                      param_attr=transform_param_attr,
                      name=f"{name}_transform")
    expanded = expand(decoder_proj, encoded_proj)
    combined = dsl.addto([encoded_proj, expanded], act=TanhActivation(),
                         name=f"{name}_combine")
    attention_weight = fc(combined, size=1, act=SequenceSoftmaxActivation(),
                          bias_attr=False, param_attr=softmax_param_attr,
                          name=f"{name}_weight")
    scaled = dsl.scaling_layer([attention_weight, encoded_sequence],
                               name=f"{name}_scale")
    return pooling(scaled, pooling_type=dsl.SumPooling(),
                   name=f"{name}_context")

"""Composite network helpers.

Reference: ``trainer_config_helpers/networks.py`` — the full ``__all__``
set: sequence_conv_pool/text_conv_pool, simple_img_conv_pool,
img_conv_bn_pool, img_conv_group, small_vgg, vgg_16_network, simple_lstm,
lstmemory_unit, lstmemory_group, gru_unit, gru_group, simple_gru,
simple_gru2, bidirectional_gru, bidirectional_lstm, simple_attention,
dot_product_attention, inputs, outputs (+ stacked_lstm_net from the
sentiment demo).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..config import dsl
from ..utils import ConfigError, enforce
from ..config.dsl import (
    AvgPooling,
    LinearActivation,
    MaxPooling,
    ReluActivation,
    SequenceSoftmaxActivation,
    SigmoidActivation,
    SoftmaxActivation,
    StepInput,
    TanhActivation,
    batch_norm,
    concat,
    data,
    dropout,
    expand,
    fc,
    first_seq,
    full_matrix_projection,
    grumemory,
    img_conv,
    img_pool,
    last_seq,
    lstmemory,
    memory,
    mixed,
    pooling,
    recurrent_group,
)


def simple_img_conv_pool(input, filter_size: int, num_filters: int,
                         pool_size: int, num_channel: Optional[int] = None,
                         pool_stride: int = 2, act=None, padding: int = 1,
                         img_size: Optional[int] = None, name=None):
    conv = img_conv(input, filter_size=filter_size, num_filters=num_filters,
                    num_channels=num_channel, padding=padding,
                    img_size=img_size, act=act or ReluActivation(),
                    name=name and f"{name}_conv")
    return img_pool(conv, pool_size=pool_size, stride=pool_stride,
                    pool_type=MaxPooling(), name=name and f"{name}_pool")


def img_conv_group(input, conv_num_filter: Sequence[int],
                   conv_filter_size: int = 3, num_channels=None,
                   pool_size: int = 2, pool_stride: int = 2,
                   conv_padding: int = 1, conv_act=None,
                   conv_with_batchnorm: bool = False,
                   conv_batchnorm_drop_rate=None, pool_type=None,
                   img_size: Optional[int] = None, **_ignored):
    tmp = input
    channels = num_channels
    for i, nf in enumerate(conv_num_filter):
        tmp = img_conv(tmp, filter_size=conv_filter_size, num_filters=nf,
                       num_channels=channels, padding=conv_padding,
                       img_size=img_size,
                       act=LinearActivation() if conv_with_batchnorm
                       else (conv_act or ReluActivation()))
        img_size = None
        channels = None
        if conv_with_batchnorm:
            drop = 0.0
            if conv_batchnorm_drop_rate:
                drop = conv_batchnorm_drop_rate[i] \
                    if isinstance(conv_batchnorm_drop_rate, (list, tuple)) \
                    else conv_batchnorm_drop_rate
            tmp = batch_norm(tmp, act=conv_act or ReluActivation(),
                             layer_attr=dsl.ExtraAttr(drop_rate=drop))
    return img_pool(tmp, pool_size=pool_size, stride=pool_stride,
                    pool_type=pool_type or MaxPooling())


def vgg_16_network(input_image, num_channels: int, num_classes: int = 1000,
                   img_size: int = 224):
    """``vgg_16_network`` (networks.py): 5 conv groups + 2×fc4096."""
    tmp = img_conv_group(input_image, [64, 64], num_channels=num_channels,
                         conv_with_batchnorm=True, img_size=img_size)
    tmp = img_conv_group(tmp, [128, 128], conv_with_batchnorm=True)
    tmp = img_conv_group(tmp, [256, 256, 256], conv_with_batchnorm=True)
    tmp = img_conv_group(tmp, [512, 512, 512], conv_with_batchnorm=True)
    tmp = img_conv_group(tmp, [512, 512, 512], conv_with_batchnorm=True)
    tmp = fc(tmp, size=4096, act=ReluActivation(),
             layer_attr=dsl.ExtraAttr(drop_rate=0.5))
    tmp = fc(tmp, size=4096, act=ReluActivation(),
             layer_attr=dsl.ExtraAttr(drop_rate=0.5))
    return fc(tmp, size=num_classes, act=SoftmaxActivation())


def simple_lstm(input, size: int, name=None, reverse: bool = False,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None, act=None, gate_act=None,
                state_act=None):
    """fc(4H) + lstmemory (``simple_lstm`` in networks.py)."""
    proj = fc(input, size=size * 4, act=LinearActivation(), bias_attr=False,
              param_attr=mat_param_attr, name=name and f"{name}_transform")
    return lstmemory(proj, name=name, reverse=reverse, act=act,
                     gate_act=gate_act, state_act=state_act,
                     bias_attr=bias_param_attr if bias_param_attr is not None
                     else True,
                     param_attr=inner_param_attr)


def simple_gru(input, size: int, name=None, reverse: bool = False, act=None,
               gate_act=None, **kw):
    proj = fc(input, size=size * 3, act=LinearActivation(), bias_attr=False,
              name=name and f"{name}_transform")
    return grumemory(proj, name=name, reverse=reverse, act=act,
                     gate_act=gate_act)


def bidirectional_lstm(input, size: int, name=None, return_seq: bool = False):
    fwd = simple_lstm(input, size, name=name and f"{name}_fwd")
    bwd = simple_lstm(input, size, name=name and f"{name}_bwd", reverse=True)
    if return_seq:
        return concat([fwd, bwd])
    return concat([last_seq(fwd), first_seq(bwd)])


def stacked_lstm_net(input, hid_dim: int, stacked_num: int = 3,
                     act=None):
    """Stacked alternating-direction LSTM (sentiment demo topology)."""
    lstm = simple_lstm(input, hid_dim)
    inputs = [input, lstm]
    for i in range(2, stacked_num + 1):
        nxt = fc(inputs, size=hid_dim * 4, act=LinearActivation(),
                 bias_attr=False)
        lstm = lstmemory(nxt, reverse=(i % 2 == 0))
        inputs = [nxt, lstm]
    return lstm


def sequence_conv_pool(input, context_len: int, hidden_size: int,
                       name=None, context_start=None, pool_type=None,
                       context_proj_param_attr=None, fc_act=None):
    """context projection + fc + seq pooling (text conv)."""
    ctx = mixed(
        [dsl.context_projection(input, context_len, context_start)],
        size=input.size * context_len, name=name and f"{name}_ctx")
    h = fc(ctx, size=hidden_size, act=fc_act or LinearActivation(),
           name=name and f"{name}_fc")
    return pooling(h, pooling_type=pool_type or MaxPooling(),
                   name=name and f"{name}_pool")


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name=None):
    """Bahdanau attention (``simple_attention`` in networks.py):
    score = v·tanh(enc_proj + dec_proj); context = Σ softmax(score)·enc."""
    name = name or dsl._collector.unique_name("attention")
    decoder_proj = fc(decoder_state, size=encoded_proj.size,
                      act=LinearActivation(), bias_attr=False,
                      param_attr=transform_param_attr,
                      name=f"{name}_transform")
    expanded = expand(decoder_proj, encoded_proj)
    combined = dsl.addto([encoded_proj, expanded], act=TanhActivation(),
                         name=f"{name}_combine")
    attention_weight = fc(combined, size=1, act=SequenceSoftmaxActivation(),
                          bias_attr=False, param_attr=softmax_param_attr,
                          name=f"{name}_weight")
    scaled = dsl.scaling_layer([attention_weight, encoded_sequence],
                               name=f"{name}_scale")
    return pooling(scaled, pooling_type=dsl.SumPooling(),
                   name=f"{name}_context")


def img_conv_bn_pool(input, filter_size: int, num_filters: int,
                     pool_size: int, name=None, pool_type=None, act=None,
                     groups: int = 1, conv_stride: int = 1,
                     conv_padding: int = 0, conv_bias_attr=None,
                     num_channel=None, conv_param_attr=None,
                     pool_stride: int = 1,
                     img_size: Optional[int] = None, **_ignored):
    """conv(linear) → batch_norm(act) → pool (``networks.py:231``)."""
    conv = img_conv(input, filter_size=filter_size, num_filters=num_filters,
                    num_channels=num_channel, groups=groups,
                    stride=conv_stride, padding=conv_padding,
                    act=LinearActivation(), img_size=img_size,
                    bias_attr=conv_bias_attr
                    if conv_bias_attr is not None else True,
                    param_attr=conv_param_attr,
                    name=name and f"{name}_conv")
    bn = batch_norm(conv, act=act or ReluActivation(),
                    name=name and f"{name}_bn")
    return img_pool(bn, pool_size=pool_size, stride=pool_stride,
                    pool_type=pool_type or MaxPooling(),
                    name=name and f"{name}_pool")


def small_vgg(input_image, num_channels: int, num_classes: int,
              img_size: int = 32):
    """The CIFAR VGG (``networks.py:438``): 4 BN-conv groups (64/128/
    256/512) + pool + dropout + fc512 + bn + softmax fc."""
    def group(ipt, num_filter, times, dropouts, channels=None, size=None):
        return img_conv_group(
            ipt, [num_filter] * times, num_channels=channels,
            pool_size=2, pool_stride=2, conv_filter_size=3,
            conv_act=ReluActivation(), conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type=MaxPooling(),
            img_size=size)

    tmp = group(input_image, 64, 2, [0.3, 0], num_channels, img_size)
    tmp = group(tmp, 128, 2, [0.4, 0])
    tmp = group(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = group(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = img_pool(tmp, pool_size=2, stride=2, pool_type=MaxPooling())
    tmp = dsl.dropout_layer(tmp, dropout_rate=0.5)
    tmp = fc(tmp, size=512, act=LinearActivation(),
             layer_attr=dsl.ExtraAttr(drop_rate=0.5))
    tmp = batch_norm(tmp, act=ReluActivation())
    return fc(tmp, size=num_classes, act=SoftmaxActivation())


def lstmemory_unit(input, out_memory=None, name=None,
                   size: Optional[int] = None, param_attr=None, act=None,
                   gate_act=None, state_act=None,
                   input_proj_bias_attr=None, lstm_bias_attr=None,
                   **_ignored):
    """One LSTM time step for use inside ``recurrent_group``
    (``networks.py:638``): the layer's own output memory carries h, a
    ``.state`` memory carries c; gates = input + W·h_prev."""
    if size is None:
        enforce(input.size % 4 == 0,
                f"lstmemory_unit input size {input.size} not divisible by 4")
        size = input.size // 4
    name = name or dsl._collector.unique_name("lstmemory_unit")
    out_mem = out_memory if out_memory is not None \
        else memory(name=name, size=size)
    state_mem = memory(name=f"{name}.state", size=size)
    m = mixed(
        [dsl.identity_projection(input),
         full_matrix_projection(out_mem.out if hasattr(out_mem, "out")
                                else out_mem, size=size * 4,
                                param_attr=param_attr)],
        size=size * 4, name=f"{name}_input_recurrent",
        bias_attr=input_proj_bias_attr
        if input_proj_bias_attr is not None else False)
    return dsl.lstm_step_layer(
        m, state_mem.out, size=size, name=name, act=act,
        gate_act=gate_act, state_act=state_act,
        bias_attr=lstm_bias_attr if lstm_bias_attr is not None else True)


def lstmemory_group(input, size: Optional[int] = None, name=None,
                    out_memory=None, reverse: bool = False, param_attr=None,
                    act=None, gate_act=None, state_act=None,
                    input_proj_bias_attr=None, lstm_bias_attr=None,
                    **_ignored):
    """``recurrent_group`` version of lstmemory (``networks.py:749``) —
    same math, but the per-step hidden/cell states are addressable."""
    name = name or dsl._collector.unique_name("lstmemory_group")

    def step(ipt):
        return lstmemory_unit(
            ipt, out_memory=out_memory, name=name, size=size,
            param_attr=param_attr, act=act, gate_act=gate_act,
            state_act=state_act,
            input_proj_bias_attr=input_proj_bias_attr,
            lstm_bias_attr=lstm_bias_attr)

    return recurrent_group(step, [StepInput(input)],
                           name=f"{name}_recurrent_group", reverse=reverse)


def gru_unit(input, memory_boot=None, size: Optional[int] = None,
             name=None, gru_bias_attr=None, gru_param_attr=None,
             act=None, gate_act=None, naive: bool = False, **_ignored):
    """One GRU time step inside ``recurrent_group``
    (``networks.py:845``); input is the 3H projection."""
    enforce(input.size % 3 == 0,
            f"gru_unit input size {input.size} not divisible by 3")
    if size is None:
        size = input.size // 3
    name = name or dsl._collector.unique_name("gru_unit")
    out_mem = memory(name=name, size=size, boot_layer=memory_boot)
    step_fn = dsl.gru_step_naive_layer if naive else dsl.gru_step_layer
    return step_fn(input, out_mem.out, size=size, name=name,
                   bias_attr=gru_bias_attr
                   if gru_bias_attr is not None else True,
                   param_attr=gru_param_attr, act=act, gate_act=gate_act)


def gru_group(input, memory_boot=None, size: Optional[int] = None,
              name=None, reverse: bool = False, gru_bias_attr=None,
              gru_param_attr=None, act=None, gate_act=None,
              naive: bool = False, **_ignored):
    """``recurrent_group`` version of grumemory (``networks.py:907``)."""
    name = name or dsl._collector.unique_name("gru_group")

    def step(ipt):
        return gru_unit(ipt, memory_boot=memory_boot, name=name, size=size,
                        gru_bias_attr=gru_bias_attr,
                        gru_param_attr=gru_param_attr, act=act,
                        gate_act=gate_act, naive=naive)

    return recurrent_group(step, [StepInput(input)],
                           name=f"{name}_recurrent_group", reverse=reverse)


def simple_gru2(input, size: int, name=None, reverse: bool = False,
                mixed_param_attr=None, mixed_bias_attr=None,
                gru_param_attr=None, gru_bias_attr=None, act=None,
                gate_act=None, **_ignored):
    """Like simple_gru but through ``grumemory`` (``networks.py:1068``)
    — faster, states not addressable."""
    name = name or dsl._collector.unique_name("simple_gru2")
    m = mixed([full_matrix_projection(input, size=size * 3,
                                      param_attr=mixed_param_attr)],
              size=size * 3, name=f"{name}_transform",
              bias_attr=mixed_bias_attr
              if mixed_bias_attr is not None else False)
    return grumemory(m, name=name, reverse=reverse,
                     bias_attr=gru_bias_attr
                     if gru_bias_attr is not None else True,
                     param_attr=gru_param_attr, act=act, gate_act=gate_act)


def bidirectional_gru(input, size: int, name=None,
                      return_seq: bool = False, **kw):
    """Forward + backward simple_gru2, concatenated
    (``networks.py:1130``); kwargs prefixed fwd_/bwd_ route to the
    respective direction."""
    name = name or dsl._collector.unique_name("bidirectional_gru")
    allowed_plain = {"concat_act", "concat_attr", "last_seq_attr",
                     "first_seq_attr"}
    unknown = [k for k in kw
               if not (k.startswith("fwd_") or k.startswith("bwd_")
                       or k in allowed_plain)]
    if unknown:
        raise ConfigError(
            f"bidirectional_gru: unknown kwargs {unknown} — direction "
            "attrs must be prefixed fwd_/bwd_ (e.g. fwd_gru_bias_attr)")
    fwd_kw = {k[len("fwd_"):]: v for k, v in kw.items()
              if k.startswith("fwd_")}
    bwd_kw = {k[len("bwd_"):]: v for k, v in kw.items()
              if k.startswith("bwd_")}
    fw = simple_gru2(input, size, name=f"{name}_fw", **fwd_kw)
    bw = simple_gru2(input, size, name=f"{name}_bw", reverse=True,
                     **bwd_kw)
    if return_seq:
        return concat([fw, bw], act=kw.get("concat_act"),
                      layer_attr=kw.get("concat_attr"))
    return concat([last_seq(fw, layer_attr=kw.get("last_seq_attr")),
                   first_seq(bw, layer_attr=kw.get("first_seq_attr"))],
                  act=kw.get("concat_act"),
                  layer_attr=kw.get("concat_attr"))


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None):
    """Dot-product attention (``networks.py:1402``): score =
    stateᵀ·h_j, context = Σ softmax(score)·z_j over attended_sequence."""
    enforce(transformed_state.size == encoded_sequence.size,
            "dot_product_attention: transformed_state and encoded_sequence "
            f"sizes differ ({transformed_state.size} vs "
            f"{encoded_sequence.size})")
    name = name or dsl._collector.unique_name("dot_product_attention")
    expanded = expand(transformed_state, encoded_sequence,
                      name=f"{name}_expand")
    m = dsl.linear_comb_layer(weights=expanded, vectors=encoded_sequence,
                              name=f"{name}_dot-product")
    attention_weight = fc(m, size=1, act=SequenceSoftmaxActivation(),
                          param_attr=softmax_param_attr, bias_attr=False,
                          name=f"{name}_softmax")
    scaled = dsl.scaling_layer([attention_weight, attended_sequence],
                               name=f"{name}_scaling")
    return pooling(scaled, pooling_type=dsl.SumPooling(),
                   name=f"{name}_pooling")


# text_conv_pool is the reference's other name for the same composite
text_conv_pool = sequence_conv_pool

# input/output declarations (networks.py:1485/1503) — the v1 config-file
# forms live in config_parser; re-exported here for helper parity
from ..config.config_parser import outputs  # noqa: E402,F401
from ..config.dsl import inputs  # noqa: E402,F401

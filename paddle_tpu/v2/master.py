"""``paddle.v2.master`` equivalent.

Reference: ``python/paddle/v2/master/client.py`` — a ctypes client for
the Go master.  Here the master is the in-tree C++ service
(``native/master/master.cc``); ``client(addr)`` returns a TCP
:class:`~paddle_tpu.distributed.MasterClient` speaking its line
protocol, or an in-process :class:`~paddle_tpu.distributed.Master` when
``addr`` is None (no etcd — addresses are explicit in the TPU build).
"""

from __future__ import annotations

from typing import Optional

from ..distributed.master import Master, MasterClient


def client(addr: Optional[str] = None, timeout_sec: float = 5.0,
           buf_size: int = 0):
    """The reference signature is ``client(etcd_endpoints, timeout_sec,
    buf_size)``; etcd endpoints are replaced by the master's host:port.
    ``buf_size`` is unused — buffering lives in the reader combinators
    (``buffered()``), not the client."""
    if addr is None:
        # timeout_sec is a CONNECTION timeout in the reference API; the
        # in-process master's lease timeout keeps its own default (60s,
        # go/master/service.go task re-dispatch semantics)
        return Master(timeout_s=60.0, failure_max=3)
    return MasterClient(addr, timeout=timeout_sec)

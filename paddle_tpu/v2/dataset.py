"""paddle.v2.dataset equivalent (synthetic-fallback corpora)."""

from ..data import datasets as _d


class mnist:
    train = staticmethod(_d.mnist_train)
    test = staticmethod(_d.mnist_test)


class cifar:
    train10 = staticmethod(_d.cifar10_train)
    test10 = staticmethod(_d.cifar10_test)


class imdb:
    word_dict = staticmethod(_d.imdb_word_dict)
    train = staticmethod(_d.imdb_train)
    test = staticmethod(_d.imdb_test)


class imikolov:
    train = staticmethod(_d.imikolov_train)


class uci_housing:
    train = staticmethod(_d.uci_housing_train)
    test = staticmethod(_d.uci_housing_test)


class wmt14:
    train = staticmethod(_d.wmt14_train)
    test = staticmethod(_d.wmt14_test)
    dicts = staticmethod(_d.wmt14_dicts)


class conll05:
    test = staticmethod(_d.conll05_train)
    train = staticmethod(_d.conll05_train)


class criteo:
    train = staticmethod(_d.criteo_ctr_train)


class movielens:
    train = staticmethod(_d.movielens_train)
    test = staticmethod(_d.movielens_test)
    movie_categories = staticmethod(_d.movielens_movie_categories)
    get_movie_title_dict = staticmethod(_d.movielens_get_movie_title_dict)
    max_user_id = staticmethod(_d.movielens_max_user_id)
    max_movie_id = staticmethod(_d.movielens_max_movie_id)
    max_job_id = staticmethod(_d.movielens_max_job_id)
    user_info = staticmethod(_d.movielens_user_info)
    movie_info = staticmethod(_d.movielens_movie_info)


class sentiment:
    train = staticmethod(_d.sentiment_train)
    test = staticmethod(_d.sentiment_test)
    get_word_dict = staticmethod(_d.sentiment_word_dict)


class voc2012:
    train = staticmethod(_d.voc2012_train)
    test = staticmethod(_d.voc2012_test)
    val = staticmethod(_d.voc2012_val)


class flowers:
    train = staticmethod(_d.flowers_train)
    test = staticmethod(_d.flowers_test)
    valid = staticmethod(_d.flowers_valid)


class mq2007:
    train = staticmethod(_d.mq2007_train)
    test = staticmethod(_d.mq2007_test)


class common:
    """``paddle.v2.dataset.common`` — download cache + shard tools."""

    from ..data.download import (  # noqa: F401
        DATA_HOME,
        cluster_files_reader,
        convert,
        download,
        md5file,
        split,
    )

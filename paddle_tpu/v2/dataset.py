"""paddle.v2.dataset equivalent (synthetic-fallback corpora)."""

from ..data import datasets as _d


class mnist:
    train = staticmethod(_d.mnist_train)
    test = staticmethod(_d.mnist_test)


class cifar:
    train10 = staticmethod(_d.cifar10_train)
    test10 = staticmethod(_d.cifar10_test)


class imdb:
    word_dict = staticmethod(_d.imdb_word_dict)
    train = staticmethod(_d.imdb_train)
    test = staticmethod(_d.imdb_test)


class imikolov:
    train = staticmethod(_d.imikolov_train)


class uci_housing:
    train = staticmethod(_d.uci_housing_train)
    test = staticmethod(_d.uci_housing_test)


class wmt14:
    train = staticmethod(_d.wmt14_train)
    test = staticmethod(_d.wmt14_test)
    dicts = staticmethod(_d.wmt14_dicts)


class conll05:
    test = staticmethod(_d.conll05_train)
    train = staticmethod(_d.conll05_train)


class criteo:
    train = staticmethod(_d.criteo_ctr_train)

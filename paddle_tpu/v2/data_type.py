from ..data.feeder import (  # noqa: F401
    InputType,
    dense_vector,
    dense_vector_sequence,
    dense_vector_sub_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_binary_vector_sequence,
    sparse_float_vector,
    sparse_float_vector_sequence,
)

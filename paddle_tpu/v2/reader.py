from ..data.reader import (  # noqa: F401
    batch,
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    np_array,
    shuffle,
    text_file,
    xmap_readers,
)

creator = type("creator", (), {"np_array": staticmethod(np_array),
                               "text_file": staticmethod(text_file)})

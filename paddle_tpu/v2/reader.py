from ..data.pipeline import prefetch_reader  # noqa: F401
from ..data.reader import (  # noqa: F401
    batch,
    buffered,
    cache,
    chain,
    cloud_reader,
    compose,
    firstn,
    map_readers,
    np_array,
    recordio,
    shuffle,
    text_file,
    xmap_readers,
)

creator = type("creator", (), {"np_array": staticmethod(np_array),
                               "text_file": staticmethod(text_file),
                               "recordio": staticmethod(recordio),
                               "cloud_reader": staticmethod(cloud_reader)})

"""``paddle.v2.model`` equivalent — distributed-aware checkpointing.

Reference: ``python/paddle/v2/model.py`` — ``save_model`` asks the
master which trainer should checkpoint (save-model election,
``go/master/service.go:481``) and writes ``parameters.to_tar``;
``load_model`` is the inverse.  The Kubernetes/etcd discovery is
replaced by an explicit master handle.
"""

from __future__ import annotations

import os
import uuid
from typing import Optional

__all__ = ["save_model", "load_model", "trainer_id"]

trainer_id = str(uuid.uuid4())


def save_model(parameters, path: str, master=None,
               interval_s: float = 60.0,
               trainer: Optional[str] = None) -> Optional[str]:
    """Write ``parameters`` to ``path``; with a ``master`` handle, only
    the elected trainer writes (returns None on the losers, the written
    path on the winner).

    ``trainer`` defaults to the per-process uuid — distinct across
    trainer *processes* (the reference deployment unit); in-process
    multi-trainer callers must pass distinct ids or they all win the
    election and race on the same file."""
    tid = trainer or trainer_id
    if master is not None:
        if not master.request_save_model(tid, interval_s):
            return None
        path = os.path.join(path, tid, "model.tar")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        parameters.to_tar(f)
    return path


def load_model(parameters, path: str) -> None:
    with open(path, "rb") as f:
        loaded = parameters.from_tar(f)
    for n in loaded.names():
        parameters.set(n, loaded.get(n))

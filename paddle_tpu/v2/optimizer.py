"""paddle.v2.optimizer equivalent.

Reference: ``python/paddle/v2/optimizer.py`` — optimizer objects carrying
OptimizationConfig, consumed by the trainer (``create_updater`` chose
local/remote updaters; on TPU there is one jitted update path).
"""

from __future__ import annotations

from typing import Optional

from ..config.model_config import OptimizationConfig


class Optimizer:
    method = "sgd"

    def __init__(self, learning_rate: float = 0.01,
                 learning_rate_schedule: str = "constant",
                 learning_rate_decay_a: float = 0.0,
                 learning_rate_decay_b: float = 0.0,
                 learning_rate_args: str = "",
                 regularization=None,
                 gradient_clipping_threshold: float = 0.0,
                 model_average=None, batch_size: int = 32, **kw):
        self.conf = OptimizationConfig(
            batch_size=batch_size,
            learning_rate=learning_rate,
            learning_method=self.method,
            learning_rate_schedule=learning_rate_schedule,
            learning_rate_decay_a=learning_rate_decay_a,
            learning_rate_decay_b=learning_rate_decay_b,
            learning_rate_args=learning_rate_args,
            gradient_clipping_threshold=gradient_clipping_threshold,
        )
        if regularization is not None:
            self.conf.l2_weight_decay = getattr(regularization, "l2", 0.0)
            self.conf.l1_weight_decay = getattr(regularization, "l1", 0.0)
        if model_average is not None:
            self.conf.average_window = model_average.average_window
            self.conf.max_average_window = model_average.max_average_window
        for k, v in kw.items():
            if hasattr(self.conf, k):
                setattr(self.conf, k, v)


class SGD(Optimizer):
    method = "sgd"


class Momentum(Optimizer):
    method = "momentum"

    def __init__(self, momentum: float = 0.9, **kw):
        super().__init__(**kw)
        self.conf.momentum = momentum


class Adam(Optimizer):
    method = "adam"

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, **kw):
        super().__init__(**kw)
        self.conf.adam_beta1 = beta1
        self.conf.adam_beta2 = beta2
        self.conf.adam_epsilon = epsilon


class Adamax(Optimizer):
    method = "adamax"

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999, **kw):
        super().__init__(**kw)
        self.conf.adam_beta1 = beta1
        self.conf.adam_beta2 = beta2


class AdaGrad(Optimizer):
    method = "adagrad"


class DecayedAdaGrad(Optimizer):
    method = "decayed_adagrad"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.conf.ada_rou = rho
        self.conf.ada_epsilon = epsilon


class AdaDelta(Optimizer):
    method = "adadelta"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.conf.ada_rou = rho
        self.conf.ada_epsilon = epsilon


class RMSProp(Optimizer):
    method = "rmsprop"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.conf.ada_rou = rho
        self.conf.ada_epsilon = epsilon


class L2Regularization:
    def __init__(self, rate: float):
        self.l2 = rate
        self.l1 = 0.0


class L1Regularization:
    def __init__(self, rate: float):
        self.l1 = rate
        self.l2 = 0.0


class ModelAverage:
    def __init__(self, average_window: float = 0.5,
                 max_average_window: int = 10000):
        self.average_window = average_window
        self.max_average_window = max_average_window

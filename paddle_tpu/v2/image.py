"""paddle.v2.image equivalent — image preprocessing for CHW pipelines.

Reference: ``python/paddle/v2/image.py`` (cv2-based).  Same ``__all__``
surface re-implemented on PIL + numpy (cv2 is not in this stack); images
flow as HWC uint8/float arrays and convert to the reference's CHW layout
with :func:`to_chw` exactly as the reference documents.
"""

from __future__ import annotations

import io
import tarfile
from typing import Optional

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def _pil():
    from PIL import Image
    return Image


def load_image_bytes(bytes_data, is_color: bool = True) -> np.ndarray:
    """Decode an in-memory image to HWC (or HW when grayscale)."""
    img = _pil().open(io.BytesIO(bytes_data))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(file: str, is_color: bool = True) -> np.ndarray:
    img = _pil().open(file)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the SHORTER edge equals ``size`` (aspect preserved)."""
    h, w = im.shape[:2]
    if h > w:
        h_new, w_new = size * h // w, size
    else:
        h_new, w_new = size, size * w // h
    pil = _pil().fromarray(np.asarray(im, np.uint8))
    return np.asarray(pil.resize((w_new, h_new), _pil().BILINEAR))


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC → CHW (the reference's storage layout for dense image rows)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int,
                is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = int(rng.randint(0, h - size + 1))
    w_start = int(rng.randint(0, w - size + 1))
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean=None,
                     rng: Optional[np.random.RandomState] = None
                     ) -> np.ndarray:
    """resize-short → crop (random+flip when training, center otherwise)
    → CHW float32 → optional mean subtraction (``image.py``
    simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(0, 2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and len(im.shape) == 3:
            mean = mean[:, None, None]     # per-channel
        im -= mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean=None) -> np.ndarray:
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file: str, dataset_name: str,
                          img2label: dict, num_per_batch: int = 1024):
    """Pack images from a tar into pickled batches next to the tar
    (``image.py`` batch_images_from_tar; numpy arrays instead of the
    reference's cPickle'd cv2 buffers)."""
    import os
    import pickle

    out_path = f"{data_file}_{dataset_name}_batch"
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id = [], [], 0
    meta = []
    with tarfile.open(data_file) as f:
        for mem in f:
            if mem.name not in img2label:
                continue
            data.append(load_image_bytes(f.extractfile(mem).read()))
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                output = {"label": labels,
                          "data": [np.asarray(d) for d in data]}
                name = os.path.join(out_path, f"batch_{file_id}")
                with open(name, "wb") as o:
                    pickle.dump(output, o, protocol=2)
                meta.append(name)
                file_id += 1
                data, labels = [], []
    if data:
        output = {"label": labels, "data": [np.asarray(d) for d in data]}
        name = os.path.join(out_path, f"batch_{file_id}")
        with open(name, "wb") as o:
            pickle.dump(output, o, protocol=2)
        meta.append(name)
    with open(os.path.join(out_path, "batch_meta"), "w") as o:
        o.write("\n".join(meta))
    return out_path

from ..config.dsl import ExtraAttr, ParamAttr  # noqa: F401

Param = ParamAttr
Extra = ExtraAttr
ParameterAttribute = ParamAttr
ExtraLayerAttribute = ExtraAttr

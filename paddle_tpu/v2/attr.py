from ..config.dsl import ExtraAttr, HookAttr, HookAttribute, ParamAttr  # noqa: F401

Param = ParamAttr
Extra = ExtraAttr
Hook = HookAttribute
ParameterAttribute = ParamAttr
ExtraLayerAttribute = ExtraAttr

from ..trainer.events import (  # noqa: F401
    BeginIteration,
    BeginPass,
    EndIteration,
    EndPass,
    TestResult,
)

"""paddle.v2.layer equivalent — re-export of the DSL."""

from ..config.dsl import *  # noqa: F401,F403
from ..config.dsl import (  # noqa: F401
    LayerOutput,
    StepInput,
    memory,
    mixed,
    recurrent_group,
    topology,
)

# parse_network equivalent
parse_network = topology

"""paddle.v2.plot equivalent — cost-curve plotting during training.

Reference: ``python/paddle/v2/plot/plot.py`` (``Ploter``/``PlotData``,
matplotlib + IPython display, ``DISABLE_PLOT`` escape hatch).  This port
works headless: ``plot(path=...)`` saves a PNG via the Agg backend; in a
notebook it displays inline like the reference.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional


class PlotData:
    def __init__(self):
        self.step: List[float] = []
        self.value: List[float] = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(float(value))

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args: str):
        self.__args__ = args
        self.__plot_data__: Dict[str, PlotData] = {t: PlotData()
                                                   for t in args}

    def __plot_is_disabled__(self) -> bool:
        # read at call time — the reference's DISABLE_PLOT escape hatch
        # may be toggled after construction
        return os.environ.get("DISABLE_PLOT") == "True"

    def append(self, title: str, step, value) -> None:
        assert title in self.__plot_data__, title
        self.__plot_data__[title].append(step, value)

    def plot(self, path: Optional[str] = None) -> None:
        if self.__plot_is_disabled__():
            return
        import matplotlib
        if path is not None:
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        titles = []
        for title in self.__args__:
            data = self.__plot_data__[title]
            if len(data.step) > 0:
                plt.plot(data.step, data.value)
                titles.append(title)
        plt.legend(titles, loc="upper left")
        if path is None:  # notebook / interactive
            try:
                from IPython import display
                display.clear_output(wait=True)
                plt.pause(0.01)
            except ImportError:
                plt.show()
        else:
            plt.savefig(path)
            plt.close()

    def reset(self) -> None:
        for data in self.__plot_data__.values():
            data.reset()

"""paddle.v2.activation equivalent."""

from ..config.dsl import (  # noqa: F401
    AbsActivation as Abs,
    BReluActivation as BRelu,
    ExpActivation as Exp,
    LinearActivation as Linear,
    LogActivation as Log,
    ReciprocalActivation as Reciprocal,
    ReluActivation as Relu,
    SequenceSoftmaxActivation as SequenceSoftmax,
    SigmoidActivation as Sigmoid,
    SoftmaxActivation as Softmax,
    SoftReluActivation as SoftRelu,
    SqrtActivation as Sqrt,
    SquareActivation as Square,
    STanhActivation as STanh,
    TanhActivation as Tanh,
)

"""``paddle_tpu.v2`` — the v2-API-compatible namespace.

Mirrors ``python/paddle/v2``'s module layout so reference user code ports
with an import swap: ``layer``, ``activation``, ``pooling``, ``attr``,
``data_type``, ``optimizer``, ``trainer``, ``event``, ``dataset``,
``reader``, ``networks``, ``evaluator``, ``inference``, ``parameters``.
"""

from . import activation, attr, data_type, dataset, evaluator, event
from . import image, inference, layer, master, model, networks, optimizer
from . import plot, pooling, reader, topology, trainer
from . import parameters
from .inference import infer
from .parameters import Parameters
from .reader import batch  # paddle.batch (v2/minibatch.py alias)

__all__ = [
    "activation", "attr", "data_type", "dataset", "evaluator", "event",
    "image", "inference", "infer", "layer", "master", "model", "networks",
    "optimizer", "parameters", "plot", "pooling", "reader", "topology",
    "trainer", "Parameters", "batch", "init",
]


def init(use_gpu: bool = False, trainer_count: int = 1, **kwargs) -> None:
    """v2 ``paddle.init`` compatibility shim (device setup is automatic on
    TPU; trainer_count maps to the data-mesh axis)."""
    from ..utils import FLAGS

    if trainer_count:
        FLAGS.set("trainer_count", trainer_count)

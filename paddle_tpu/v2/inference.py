"""paddle.v2.inference equivalent (``Inference:10``, ``infer():111``)."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..config.dsl import topology
from ..core.sequence import SequenceBatch, value_of
from ..layers.network import NeuralNetwork


class Inference:
    def __init__(self, output_layer, parameters=None):
        self.model_config = topology(output_layer)
        self.network = NeuralNetwork(self.model_config)
        self.params = self.network.init_params()
        self.buffers = self.network.init_buffers()
        if parameters is not None:
            import jax.numpy as jnp

            for name in parameters.names():
                if name in self.params:
                    self.params[name] = jnp.asarray(parameters.get(name))

    def iter_infer(self, input, feeding=None, field=None):
        from .trainer import SGD

        feeder = SGD._feeder(self, feeding) if feeding else None
        for batch in input:
            feed = feeder.convert(batch) if feeder else batch
            values, _ = self.network.forward(
                self.params, feed, self.buffers, is_training=False)
            outs = self.network.outputs(values)
            if field is None:
                yield [np.asarray(value_of(v)) for v in outs.values()]
                continue
            # generation fields (SWIG SequenceGenerator parity):
            # "id" → generated token ids, "prob"/"score" → beam scores,
            # "len" → sequence lengths, "value" → the raw output value
            row = []
            for name in outs:
                for f in (field if isinstance(field, (list, tuple))
                          else [field]):
                    if f in ("prob", "score"):
                        row.append(np.asarray(
                            value_of(values[f"{name}.scores"])))
                    elif f == "len":
                        row.append(np.asarray(
                            value_of(values[f"{name}.lengths"])))
                    else:   # "id" / "value"
                        row.append(np.asarray(value_of(values[name])))
            yield row

    def infer(self, input, feeding=None, field=None):
        results = []
        for out in self.iter_infer(input, feeding, field=field):
            results.append(out[0] if len(out) == 1 else out)
        if len(results) == 1:
            return results[0]
        if not results:
            return results
        if isinstance(results[0], list):
            # multi-output net: concatenate each output across batches
            return [np.concatenate(per_out) if per_out[0].ndim > 0
                    else np.asarray(per_out)
                    for per_out in zip(*results)]
        return np.concatenate(results) if results[0].ndim > 0 else results


def infer(output_layer, parameters=None, input=None, feeding=None,
          field=None):
    return Inference(output_layer, parameters).infer(input, feeding,
                                                     field=field)
